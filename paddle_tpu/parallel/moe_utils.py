"""MoE routing + dispatch utilities.

Two layers live here:

1. **The fixed-shape top-k capacity router** (ISSUE 10): softmax gate,
   per-expert capacity slots, overflow dropped (the caller's residual
   path covers dropped tokens), GShard-style load-balance loss and
   router z-loss. Dispatch and combine are expressed as one-hot
   einsums over `[T, k, C]` / `[T, k, E]` masks, so the whole MoE
   block is static-shape and XLA fuses it — the TPU replacement for
   the reference's `number_count`/`assign_pos`/
   `prune_gate_by_capacity` CUDA op chain. Every MoE consumer shares
   this one core: `parallel.hybrid_gpt._moe_ffn` (training),
   `incubate.nn.fused_transformer._ffn_moe` (fused stack + eager),
   `incubate.distributed.models.moe.MoELayer`, and the serving mixed
   step (`serving.engine`).

2. **Expert-parallel exchange.** `all_to_all_dispatch` /
   `all_to_all_combine` move the `[E, C, d]` dispatch tensors over an
   expert-parallel mesh axis inside a compiled step (the
   `global_scatter/global_gather` capability riding `lax.all_to_all`
   on ICI); the eager `global_scatter/global_gather` wrappers keep
   parity with `python/paddle/distributed/utils/moe_utils.py:21,144`
   for the reference's dygraph API surface.
"""
from __future__ import annotations

import dataclasses
import math

import numpy as np

from ..core.tensor import Tensor
from . import env as dist_env


# ---------------------------------------------------------------------
# fixed-shape top-k capacity routing (pure jax; shapes never depend on
# routing decisions, so the consumers stay one-compile)
# ---------------------------------------------------------------------


def expert_capacity(num_tokens, num_experts, top_k, capacity_factor):
    """Per-expert capacity slots C = ceil(factor * T * k / E), floored
    at 1. At `capacity_factor >= E / top_k` (e.g. >= top_k when
    E == top_k**2) C reaches T, so no token can overflow — the
    zero-drop regime the smoke contracts pin."""
    c = capacity_factor * float(num_tokens) * float(top_k) \
        / float(num_experts)
    return max(1, int(math.ceil(c)))


@dataclasses.dataclass
class DispatchPlan:
    """Fixed-shape masks for one routed token set.

    disp  [T, k, C]  0/1 dispatch mask (capacity slot per choice);
                     None when built with `build_masks=False` (the
                     index-based grouped-matmul path never reads it)
    comb  [T, k, C]  gate-weighted combine mask (disp * gate value);
                     None like `disp` under `build_masks=False`
    e_oh  [T, k, E]  expert one-hot per choice (invalid/padded rows 0)
    counts  [E] f32  tokens each expert actually received (post-drop)
    dropped    f32   (token, choice) pairs lost to capacity overflow
    gate_idx [T, k]  chosen expert per (token, choice)
    slot  [T, k]     capacity slot within the chosen expert
    in_cap [T, k]    bool: the choice landed inside capacity
    gates [T, k]     renormalized gate values (the combine weights)
    """
    disp: object
    comb: object
    e_oh: object
    counts: object
    dropped: object
    gate_idx: object = None
    slot: object = None
    in_cap: object = None
    gates: object = None


def capacity_dispatch(gate_val, gate_idx, num_experts, capacity,
                      valid=None, dtype=None, build_masks=True):
    """Build the dispatch/combine masks for already-chosen experts.

    gate_val/gate_idx [T, k]; `valid` [T] bool masks padding tokens
    (they claim no capacity and never reach an expert — the serving
    engine's empty slots). Slot assignment is a cumulative count in
    token-major, choice-minor order, so earlier tokens win capacity
    (GShard's position-in-expert semantics); an overflowing choice is
    dropped: its disp/comb rows are zero and the caller's residual
    connection carries the token through unchanged.

    `build_masks=False` skips materializing the [T, k, C] one-hot
    disp/comb masks — the index-based dispatch/combine below only
    needs the (gate_idx, slot, in_cap, gates) integer plan, and for
    serving-scale C the masks are the dominant memory term."""
    import jax
    import jax.numpy as jnp

    T, k = gate_val.shape
    E, C = int(num_experts), int(capacity)
    dtype = dtype or gate_val.dtype
    oh = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)          # [T,k,E]
    if valid is not None:
        oh = oh * valid.astype(jnp.int32)[:, None, None]
    flat_oh = oh.reshape(T * k, E)
    # position of each (token, choice) within its expert's arrival order
    pos = jnp.cumsum(flat_oh, axis=0) * flat_oh - 1            # [T*k,E]
    slot = jnp.sum(pos * flat_oh, axis=-1).reshape(T, k)       # [T,k]
    routed = jnp.sum(oh, axis=-1) > 0                          # [T,k]
    in_cap = routed & (slot < C)
    disp = comb = None
    if build_masks:
        disp = (jax.nn.one_hot(slot, C, dtype=dtype)
                * in_cap[..., None].astype(dtype))             # [T,k,C]
        comb = disp * gate_val.astype(dtype)[..., None]
    e_oh = oh.astype(dtype)
    # counts summed in f32 from the int masks: a bf16 compute dtype
    # would round the running sum past ~256 tokens per expert and
    # break the exact-count contracts (sum == T*k) the smokes pin
    kept = jnp.sum(oh.astype(jnp.float32)
                   * in_cap[..., None].astype(jnp.float32),
                   axis=(0, 1))                                # [E]
    dropped = (jnp.sum(routed.astype(jnp.float32))
               - jnp.sum(in_cap.astype(jnp.float32)))
    return DispatchPlan(disp=disp, comb=comb, e_oh=e_oh, counts=kept,
                        dropped=dropped, gate_idx=gate_idx, slot=slot,
                        in_cap=in_cap, gates=gate_val)


def _masked_axis_sums(vals, valid, axes):
    """Sum `vals` ([T, ...]) over tokens (masked by `valid`) and over
    the given mesh axes; returns (sums, n_tokens) — the ingredients of
    an EP/DP-invariant mean."""
    import jax
    import jax.numpy as jnp

    if valid is not None:
        v = valid.astype(vals.dtype)
        vals = vals * v.reshape((-1,) + (1,) * (vals.ndim - 1))
        n = jnp.sum(v.astype(jnp.float32))
    else:
        n = jnp.asarray(float(vals.shape[0]), jnp.float32)
    s = jnp.sum(vals, axis=0)
    if axes:
        s = jax.lax.psum(s, axes)
        n = jax.lax.psum(n, axes)
    return s, n


def router_balance_loss(probs, e_oh, valid=None, axes=None):
    """GShard/Switch load-balance loss, top-k generalized:

        aux = E * sum_e  mean_t(probs[t, e]) * f_e
        f_e = (1 / (T * k)) * sum_{t,j} 1[choice (t, j) routed to e]

    Uniform routing gives aux == 1 (the minimum for a fixed me). When
    `axes` names mesh axes (("dp", "ep") in the hybrid step), the two
    means are computed over the GLOBAL token set via psums, so the
    loss — and its gradient — is invariant to how tokens are sharded
    (the EP=2 vs EP=1 parity contract)."""
    import jax.numpy as jnp

    E = probs.shape[-1]
    k = e_oh.shape[1]
    me_s, n = _masked_axis_sums(probs.astype(jnp.float32), valid, axes)
    ce_s, _ = _masked_axis_sums(
        jnp.sum(e_oh.astype(jnp.float32), axis=1), valid, axes)
    n = jnp.maximum(n, 1.0)
    me = me_s / n
    ce = ce_s / (n * float(k))
    return float(E) * jnp.sum(me * ce)


def router_z_loss(logits, valid=None, axes=None):
    """Router z-loss (ST-MoE): mean_t logsumexp(logits[t])^2 — keeps
    the gate logits small so the softmax stays in its stable range."""
    import jax
    import jax.numpy as jnp

    z = jax.scipy.special.logsumexp(
        logits.astype(jnp.float32), axis=-1) ** 2
    s, n = _masked_axis_sums(z, valid, axes)
    return s / jnp.maximum(n, 1.0)


@dataclasses.dataclass
class RouterOutput:
    plan: DispatchPlan
    gates: object        # [T, k] renormalized top-k gate values
    balance_loss: object  # scalar f32
    z_loss: object        # scalar f32


def top_k_routing(logits, top_k, capacity, valid=None, axes=None,
                  dtype=None, build_masks=True):
    """Softmax gate -> top-k -> renormalize -> capacity dispatch.

    logits [T, E] f32-castable; returns a `RouterOutput` whose plan
    carries the fixed-shape dispatch/combine masks plus the aux
    losses. `axes` (mesh axis names) makes the aux statistics global —
    pass the data-sharding axes when tracing inside shard_map.
    `build_masks=False` keeps the plan index-only (the grouped-matmul
    dispatch path — see `capacity_dispatch`)."""
    import jax
    import jax.numpy as jnp

    lf = logits.astype(jnp.float32)
    probs = jax.nn.softmax(lf, axis=-1)
    topv, topi = jax.lax.top_k(probs, int(top_k))
    gates = topv / jnp.maximum(
        jnp.sum(topv, axis=-1, keepdims=True), 1e-12)
    plan = capacity_dispatch(gates, topi, logits.shape[-1], capacity,
                             valid=valid, dtype=dtype or logits.dtype,
                             build_masks=build_masks)
    aux = router_balance_loss(probs, plan.e_oh, valid=valid, axes=axes)
    z = router_z_loss(lf, valid=valid, axes=axes)
    return RouterOutput(plan=plan, gates=gates, balance_loss=aux,
                        z_loss=z)


def dispatch_tokens(x, plan, e_oh=None):
    """x [T, d] -> dispatched [E, C, d] (each expert's capacity
    buffer, zero-padded on unclaimed slots). Pass a sliced `e_oh`
    ([T, k, E_loc]) to build only one shard's resident-expert buffers
    — the serving EP path, where computing all E and slicing after
    would waste (ep-1)/ep of the dispatch einsum."""
    import jax.numpy as jnp
    e_oh = plan.e_oh if e_oh is None else e_oh
    return jnp.einsum("tkc,tke,td->ecd", plan.disp, e_oh,
                      x.astype(plan.disp.dtype))


def combine_tokens(eout, plan):
    """eout [E, C, d] expert outputs -> [T, d] gate-weighted mixture;
    dropped (token, choice) pairs contribute 0."""
    import jax.numpy as jnp
    return jnp.einsum("tkc,tke,ecd->td", plan.comb, plan.e_oh,
                      eout.astype(plan.comb.dtype))


# ---------------------------------------------------------------------
# index-based dispatch/combine (ISSUE 11): the grouped-expert-matmul
# companions. Instead of contracting [T, k, C] x [T, k, E] one-hot
# masks, the capacity assignment becomes ONE [E, C] token-index table
# (a scatter) and dispatch/combine become gathers — no mask tensor is
# ever materialized, and the expert FFN runs on the dense [E, C, d]
# buffers via `ops.pallas.grouped_matmul.grouped_expert_matmul`.
# The einsum pair above stays the parity oracle and the fallback.
# ---------------------------------------------------------------------


def dispatch_indices(plan, num_experts, capacity):
    """[E, C] int32 token index per capacity slot (-1 = unclaimed).

    Each in-capacity (token, choice) owns a unique (expert, slot) by
    construction (`slot` is the arrival position within the expert),
    so the scatter has no collisions; dropped/padded choices are
    routed out of bounds and dropped by the scatter mode."""
    import jax.numpy as jnp
    T, k = plan.slot.shape
    E, C = int(num_experts), int(capacity)
    ok = plan.in_cap.reshape(-1)
    e = jnp.where(ok, plan.gate_idx.reshape(-1), E)
    c = jnp.where(ok, plan.slot.reshape(-1), 0)
    tok = jnp.repeat(jnp.arange(T, dtype=jnp.int32), k)
    tos = jnp.full((E, C), -1, jnp.int32)
    return tos.at[e, c].set(tok, mode="drop")


def dispatch_tokens_indexed(x, plan, num_experts, capacity,
                            indices=None):
    """x [T, d] -> [E, C, d] capacity buffers via gather (unclaimed
    slots zero) — semantically identical to `dispatch_tokens`."""
    import jax.numpy as jnp
    tos = dispatch_indices(plan, num_experts, capacity) \
        if indices is None else indices
    g = x[jnp.maximum(tos, 0)]                       # [E, C, d]
    return g * (tos >= 0).astype(x.dtype)[..., None]


def combine_tokens_indexed(eout, plan, e_offset=0, num_local=None):
    """eout [E_loc, C, d] -> [T, d] gate-weighted mixture via gather —
    semantically identical to `combine_tokens`. `e_offset`/`num_local`
    select a resident expert range (the serving EP path: each shard
    combines only its local experts' outputs and psums the partial
    mixtures over the ep axis)."""
    import jax.numpy as jnp
    E_loc, C = eout.shape[0], eout.shape[1]
    if num_local is None:
        num_local = E_loc
    e = plan.gate_idx
    local = plan.in_cap & (e >= e_offset) & (e < e_offset + num_local)
    el = jnp.clip(e - e_offset, 0, E_loc - 1)
    cl = jnp.clip(plan.slot, 0, C - 1)
    vals = eout[el, cl]                              # [T, k, d]
    w = plan.gates.astype(eout.dtype) * local.astype(eout.dtype)
    return jnp.sum(vals * w[..., None], axis=1)


# ---------------------------------------------------------------------
# expert-parallel exchange over a mesh axis (inside shard_map)
# ---------------------------------------------------------------------


def all_to_all_dispatch(dispatched, axis, ep):
    """[E, C, d] per-rank dispatch buffers -> [E_loc, ep * C, d] per-
    expert inputs on the expert's owner rank. The compiled
    `global_scatter`: each rank keeps the buckets of its resident
    experts from every source rank (the received leading dim indexes
    the source, concatenated into the capacity axis)."""
    import jax
    import jax.numpy as jnp
    E, C, d = dispatched.shape
    E_loc = E // int(ep)
    t = dispatched.reshape(int(ep), E_loc, C, d)
    t = jax.lax.all_to_all(t, axis, split_axis=0, concat_axis=0,
                           tiled=False)
    return jnp.swapaxes(t, 0, 1).reshape(E_loc, int(ep) * C, d)


def all_to_all_combine(eout, axis, ep):
    """Inverse of `all_to_all_dispatch` (the compiled `global_gather`):
    [E_loc, ep * C, d] expert outputs -> [E, C, d] back on the token
    owners."""
    import jax
    import jax.numpy as jnp
    E_loc, epC, d = eout.shape
    C = epC // int(ep)
    t = jnp.swapaxes(eout.reshape(E_loc, int(ep), C, d), 0, 1)
    t = jax.lax.all_to_all(t, axis, split_axis=0, concat_axis=0,
                           tiled=False)
    return t.reshape(E_loc * int(ep), C, d)


def _counts(t):
    return np.asarray(t._data if isinstance(t, Tensor) else t,
                      np.int64).reshape(-1)


def _world():
    # eager per-"card" exchange: a card is a PROCESS under the
    # single-controller SPMD model (the 8 local devices of one process
    # are driven by one copy of this python code)
    import jax
    return jax.process_count()


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True):
    """x [B, d]; local_count/global_count [n_expert * world_size].
    Returns the rows this card's experts receive (expert-major)."""
    world = _world()
    lc, gc = _counts(local_count), _counts(global_count)
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    if world == 1:
        # single card: receiving (card0, expert e) == sending bucket e;
        # x is already bucket-ordered by local_count
        if not np.array_equal(lc, gc):
            raise ValueError(
                "global_scatter single-card: local_count != global_count")
        return Tensor(arr[:int(lc.sum())])
    # multi-card eager: exchange the per-bucket segments over the object
    # collective (CPU path; compiled MoE uses all_to_all on-device)
    from .comm_extras import all_gather_object
    n_e = lc.size // world
    offs = np.concatenate([[0], np.cumsum(lc)])
    segs = [arr[offs[i]:offs[i + 1]] for i in range(lc.size)]
    everyone = []
    all_gather_object(everyone, segs, group=group)
    rank = dist_env.get_rank()
    out = []
    for src in range(world):               # global_count layout
        for e in range(n_e):
            out.append(everyone[src][rank * n_e + e])
    got = np.concatenate([s for s in out if len(s)]) if any(
        len(s) for s in out) else arr[:0]
    if got.shape[0] != int(gc.sum()):
        raise ValueError("global_scatter: global_count mismatch")
    return Tensor(got)


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True):
    """Inverse of global_scatter: return expert outputs to the cards
    that sent the tokens."""
    world = _world()
    lc, gc = _counts(local_count), _counts(global_count)
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    if world == 1:
        if not np.array_equal(lc, gc):
            raise ValueError(
                "global_gather single-card: local_count != global_count")
        return Tensor(arr[:int(gc.sum())])
    from .comm_extras import all_gather_object
    n_e = lc.size // world
    rank = dist_env.get_rank()
    offs = np.concatenate([[0], np.cumsum(gc)])
    # my received buckets, keyed by (src card, expert)
    segs = [arr[offs[i]:offs[i + 1]] for i in range(gc.size)]
    everyone = []
    all_gather_object(everyone, segs, group=group)
    out = []
    for dst in range(world):               # local_count layout
        for e in range(n_e):
            # the rows I sent to (dst, e) came back in dst's bucket
            # indexed by my rank
            out.append(everyone[dst][rank * n_e + e])
    got = np.concatenate([s for s in out if len(s)]) if any(
        len(s) for s in out) else arr[:0]
    if got.shape[0] != int(lc.sum()):
        raise ValueError("global_gather: local_count mismatch")
    return Tensor(got)
