"""`paddle.distributed.utils.global_scatter/global_gather` parity
(`python/paddle/distributed/utils/moe_utils.py:21,144` over the
`global_scatter/global_gather` CUDA ops).

Count-based MoE token exchange: rows of x are grouped per
(destination card, expert); each card keeps the rows routed to its own
experts. Single-process world (world_size=1) runs the permutation
directly; the multi-card compiled path is `incubate.distributed.models
.moe` (capacity all_to_all inside the jitted step), which is how the
TPU build actually trains MoE — these eager wrappers exist for the
reference's dygraph API surface.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from . import env as dist_env


def _counts(t):
    return np.asarray(t._data if isinstance(t, Tensor) else t,
                      np.int64).reshape(-1)


def _world():
    # eager per-"card" exchange: a card is a PROCESS under the
    # single-controller SPMD model (the 8 local devices of one process
    # are driven by one copy of this python code)
    import jax
    return jax.process_count()


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True):
    """x [B, d]; local_count/global_count [n_expert * world_size].
    Returns the rows this card's experts receive (expert-major)."""
    world = _world()
    lc, gc = _counts(local_count), _counts(global_count)
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    if world == 1:
        # single card: receiving (card0, expert e) == sending bucket e;
        # x is already bucket-ordered by local_count
        if not np.array_equal(lc, gc):
            raise ValueError(
                "global_scatter single-card: local_count != global_count")
        return Tensor(arr[:int(lc.sum())])
    # multi-card eager: exchange the per-bucket segments over the object
    # collective (CPU path; compiled MoE uses all_to_all on-device)
    from .comm_extras import all_gather_object
    n_e = lc.size // world
    offs = np.concatenate([[0], np.cumsum(lc)])
    segs = [arr[offs[i]:offs[i + 1]] for i in range(lc.size)]
    everyone = []
    all_gather_object(everyone, segs, group=group)
    rank = dist_env.get_rank()
    out = []
    for src in range(world):               # global_count layout
        for e in range(n_e):
            out.append(everyone[src][rank * n_e + e])
    got = np.concatenate([s for s in out if len(s)]) if any(
        len(s) for s in out) else arr[:0]
    if got.shape[0] != int(gc.sum()):
        raise ValueError("global_scatter: global_count mismatch")
    return Tensor(got)


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True):
    """Inverse of global_scatter: return expert outputs to the cards
    that sent the tokens."""
    world = _world()
    lc, gc = _counts(local_count), _counts(global_count)
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    if world == 1:
        if not np.array_equal(lc, gc):
            raise ValueError(
                "global_gather single-card: local_count != global_count")
        return Tensor(arr[:int(gc.sum())])
    from .comm_extras import all_gather_object
    n_e = lc.size // world
    rank = dist_env.get_rank()
    offs = np.concatenate([[0], np.cumsum(gc)])
    # my received buckets, keyed by (src card, expert)
    segs = [arr[offs[i]:offs[i + 1]] for i in range(gc.size)]
    everyone = []
    all_gather_object(everyone, segs, group=group)
    out = []
    for dst in range(world):               # local_count layout
        for e in range(n_e):
            # the rows I sent to (dst, e) came back in dst's bucket
            # indexed by my rank
            out.append(everyone[dst][rank * n_e + e])
    got = np.concatenate([s for s in out if len(s)]) if any(
        len(s) for s in out) else arr[:0]
    if got.shape[0] != int(lc.sum()):
        raise ValueError("global_gather: local_count mismatch")
    return Tensor(got)
