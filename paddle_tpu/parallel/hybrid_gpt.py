"""Hybrid-parallel GPT trainer: dp x pp x mp (+ sequence parallel, + MoE
expert parallel), manual-collective shard_map implementation.

This is the TPU-native equivalent of the reference's dygraph hybrid 3D
parallel path (SURVEY.md §3.6): `HybridCommunicateGroup`
(`fleet/base/topology.py:140`) -> mesh axes; TP layers
(`fleet/layers/mpu/mp_layers.py:39,155,293` Vocab/Column/RowParallel) ->
mp-sharded matmuls with psum/psum_scatter; `PipelineParallel` 1F1B +
`p2p_communication.py` NCCL send/recv -> GPipe microbatch loop over
`lax.ppermute` on the pp mesh axis; `c_softmax_with_cross_entropy_op.cu`
-> vocab-parallel CE with psums; MoE `global_scatter/global_gather`
(`collective/global_scatter_op.cu.cc`) -> `lax.all_to_all` over dp;
sharding stage1/2 (`group_sharded_optimizer_stage2.py:51`) -> ZeRO
reduce-scatter/all-gather of the flattened param vector over dp; recompute
(`fleet/recompute/recompute.py`) -> `jax.checkpoint` on each block.

Sequence parallelism (Megatron-SP style: activations sharded over seq on
the mp axis between blocks, all_gather in / psum_scatter out) is a
first-class extension the reference snapshot lacks (SURVEY.md §5.7).

Everything — forward, backward (jax.grad INSIDE shard_map), grad
reduction, ZeRO-sharded Adam — compiles into ONE XLA executable; the
collectives ride ICI.
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..analysis.specs import canonical_sharding
from ..jit.functional import instrumented_jit
from ..profiler import metrics as _metrics
from . import shard_map as _shard_map


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    seq_len: int = 1024
    d_model: int = 2048
    n_heads: int = 16
    n_layers: int = 24
    d_ff: int = 0            # default 4*d_model
    dropout: float = 0.0     # pretraining default
    # parallelism
    dp: int = 1
    pp: int = 1
    mp: int = 1
    ep: int = 1              # expert parallel: experts sharded over a
                             # dedicated "ep" mesh axis; tokens are
                             # data-sharded over (dp, ep) jointly and
                             # shared-param grads psum across ep like dp
    micro_batches: int = 1   # per train_batch, split over pp schedule
    sequence_parallel: bool = False
    # MoE (ISSUE 10): top-k capacity-factor router, fixed [E, C, d]
    # dispatch tensors, all_to_all over "ep" (parallel/moe_utils.py).
    # moe_num_experts is a CONSTRUCTOR-ONLY alias (an InitVar, not a
    # field, and deliberately no read property): dataclasses.replace
    # must see only the one real field, so replace(cfg, moe_experts=0)
    # really produces a dense config instead of the alias
    # resurrecting the expert count
    moe_experts: int = 0     # 0 = dense (alias: moe_num_experts)
    moe_num_experts: dataclasses.InitVar[int] = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01   # load-balance loss weight
    moe_z_weight: float = 1e-3     # router z-loss weight
    # fused residual-add+LN Pallas kernel between attention and FFN
    # (docs/gpt_perf_analysis.md: the XLA add/LN fusions pay carry-layout
    # conversions); jnp fallback off-TPU
    fused_add_ln: bool = True
    # memory / precision
    remat: bool = True
    # None = full per-block recompute; else a jax.checkpoint_policies
    # name (e.g. "dots_with_no_batch_dims_saveable") trading memory for
    # fewer recomputed FLOPs
    remat_policy: Any = None
    # sequence chunks for the vocab CE: the [B,S,V] fp32 logits are the
    # single largest buffer (6.6GB at B=32,S=1024,V=50k) — chunking the
    # head+CE over S with per-chunk remat caps it at 1/N of that
    ce_seq_chunks: int = 1
    # mp=1 fused softmax-CE custom vjp (bf16 logits, recomputed in bwd)
    fused_ce: bool = True
    # python-unrolled layer loop (static slice indices) instead of
    # lax.scan: trades compile time for removing the scan-backward's
    # stacked-gradient dynamic-update-slice traffic
    unroll_layers: bool = False
    # fused Pallas qkv projection (head-pair N=128 MXU tiles): measured
    # NEUTRAL in isolation (1.82 vs 1.85 ms/application) and slightly
    # negative in-model (848 vs 837 ms/step) — the einsum path's trace
    # attribution overstated its cost; kept opt-in for other shapes
    # (carry_2d / ffn_barrier experiment knobs from the same pass were
    # measured no-change and removed: docs/gpt_perf_analysis.md)
    qkv_kernel: bool = False
    # AMP-O2-style step: cast params to compute_dtype once up front and
    # differentiate wrt the bf16 copies — gradients (and the scan-bwd
    # stacked-grad DUS traffic) stay bf16; Adam still updates the f32
    # master params
    bf16_grads: bool = False
    compute_dtype: Any = jnp.bfloat16
    # bucketed + overlapped DP gradient reduction (ISSUE 7): grads are
    # computed per-device INSIDE shard_map, flattened into per-dtype
    # buckets of at most this many bytes, and reduced with ONE psum per
    # bucket — optimization_barrier-chained so XLA can neither combine
    # them back into a single giant all-reduce nor reorder them, which
    # is what lets the TPU async collective scheduler overlap bucket
    # k's wire time with the remaining backward compute. 0 = legacy
    # path (shard_map transpose inserts one psum per parameter leaf).
    # Pure dense-DP only (mp=pp=1, no MoE): other meshes have
    # non-replicated leaves whose grads must NOT be dp-summed.
    grad_bucket_bytes: int = 0
    # optimizer
    learning_rate: float = 1e-4
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.1
    grad_clip: float = 1.0
    zero_stage: int = 1      # 0: replicated adam; 1: states+update sharded
                             # over dp (stage-2: grads reduce-scattered too)

    def __post_init__(self, moe_num_experts):
        if self.d_ff == 0:
            self.d_ff = 4 * self.d_model
        assert self.n_layers % self.pp == 0
        assert self.n_heads % self.mp == 0
        assert self.d_model % self.n_heads == 0
        assert self.vocab_size % self.mp == 0
        # resolve the constructor alias; refuse two CONFLICTING
        # non-zero values (silently picking one would train the wrong
        # architecture)
        assert not (self.moe_experts and moe_num_experts
                    and self.moe_experts != moe_num_experts), \
            f"moe_experts={self.moe_experts} conflicts with " \
            f"moe_num_experts={moe_num_experts}"
        if moe_num_experts and not self.moe_experts:
            self.moe_experts = moe_num_experts
        if self.moe_experts:
            assert self.moe_experts % self.ep == 0, \
                "moe_experts must divide evenly over the ep axis"
            assert 1 <= self.moe_top_k <= self.moe_experts
        else:
            assert self.ep == 1, \
                "ep > 1 needs a MoE config (dense models scale over dp)"
        if self.sequence_parallel:
            assert self.seq_len % self.mp == 0
        if self.grad_bucket_bytes:
            assert self.mp == 1 and self.pp == 1 \
                and not self.moe_experts, \
                "grad_bucket_bytes needs the pure dense-DP config " \
                "(mp=pp=1, no MoE): only there is every grad leaf " \
                "replicated so a plain dp-psum per bucket is the " \
                "correct reduction"


# --------------------------------------------------------------- params


def init_params(cfg: GPTConfig, key) -> Dict[str, Any]:
    """Full logical parameters (sharding applied by the mesh specs)."""
    k = jax.random.split(key, 16)
    d, ff, L, V = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab_size
    std = 0.02
    proj_std = std / math.sqrt(2 * L)

    def nrm(key, shape, s=std):
        return (jax.random.normal(key, shape, jnp.float32) * s)

    params = {
        "tok_emb": nrm(k[0], (V, d)),
        "pos_emb": nrm(k[1], (cfg.seq_len, d)),
        "ln_f_w": jnp.ones((d,), jnp.float32),
        "ln_f_b": jnp.zeros((d,), jnp.float32),
        "head": nrm(k[2], (d, V)),
        "blocks": {
            "ln1_w": jnp.ones((L, d), jnp.float32),
            "ln1_b": jnp.zeros((L, d), jnp.float32),
            "w_qkv": nrm(k[3], (L, d, 3 * d)),
            "b_qkv": jnp.zeros((L, 3 * d), jnp.float32),
            "w_o": nrm(k[4], (L, d, d), proj_std),
            "b_o": jnp.zeros((L, d), jnp.float32),
            "ln2_w": jnp.ones((L, d), jnp.float32),
            "ln2_b": jnp.zeros((L, d), jnp.float32),
        },
    }
    if cfg.moe_experts:
        E = cfg.moe_experts
        params["blocks"]["gate"] = nrm(k[5], (L, d, E))
        params["blocks"]["w_fc1"] = nrm(k[6], (L, E, d, ff))
        params["blocks"]["b_fc1"] = jnp.zeros((L, E, ff), jnp.float32)
        params["blocks"]["w_fc2"] = nrm(k[7], (L, E, ff, d), proj_std)
        params["blocks"]["b_fc2"] = jnp.zeros((L, E, d), jnp.float32)
    else:
        params["blocks"]["w_fc1"] = nrm(k[6], (L, d, ff))
        params["blocks"]["b_fc1"] = jnp.zeros((L, ff), jnp.float32)
        params["blocks"]["w_fc2"] = nrm(k[7], (L, ff, d), proj_std)
        params["blocks"]["b_fc2"] = jnp.zeros((L, d), jnp.float32)
    return params


def param_specs(cfg: GPTConfig) -> Dict[str, Any]:
    """PartitionSpec per leaf: pp shards the stacked layer dim, mp shards
    head/ffn/vocab dims, everything else replicated (dp replicates params;
    ZeRO shards the *optimizer* state instead)."""
    moe = cfg.moe_experts > 0
    blocks = {
        "ln1_w": P("pp", None), "ln1_b": P("pp", None),
        "w_qkv": P("pp", None, "mp"), "b_qkv": P("pp", "mp"),
        "w_o": P("pp", "mp", None), "b_o": P("pp", None),
        "ln2_w": P("pp", None), "ln2_b": P("pp", None),
    }
    if moe:
        # experts sharded over the dedicated ep axis (gate is a SHARED
        # param: replicated over dp AND ep, so the shard_map transpose
        # psums its grad across both — the "like dp" contract)
        blocks.update({
            "gate": P("pp", None, None),
            "w_fc1": P("pp", "ep", None, "mp"),
            "b_fc1": P("pp", "ep", "mp"),
            "w_fc2": P("pp", "ep", "mp", None),
            "b_fc2": P("pp", "ep", None),
        })
    else:
        blocks.update({
            "w_fc1": P("pp", None, "mp"), "b_fc1": P("pp", "mp"),
            "w_fc2": P("pp", "mp", None), "b_fc2": P("pp", None),
        })
    return {
        "tok_emb": P("mp", None),
        "pos_emb": P(None, None),
        "ln_f_w": P(None), "ln_f_b": P(None),
        "head": P(None, "mp"),
        "blocks": blocks,
    }


# ----------------------------------------------------------- model math


def _layer_norm(x, w, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return ((xf - mu) / jnp.sqrt(var + eps) * w + b).astype(x.dtype)


def _attention(x, w_qkv, b_qkv, w_o, b_o, cfg: GPTConfig):
    """x [B, S, d] (full seq, mp-local heads). Causal self-attention.

    TPU: splash Pallas flash kernel (fwd + fused dkv/dq backward) —
    trace-measured 2.1x faster fwd+bwd than XLA's fused attention at
    [32,16,1024,64]; lifted the 350M single-chip headline 23.5k -> 33.9k
    tok/s (docs/gpt_perf_analysis.md). Off-TPU (CPU test mesh): XLA's
    fused attention, which never materializes the [S,S] probs either.
    """
    from ..ops.pallas.flash_attention import splash_mha
    from ..ops.pallas.qkv_proj import qkv_proj, qkv_proj_supported
    B, S, d = x.shape
    h_loc = cfg.n_heads // cfg.mp
    hd = cfg.d_model // cfg.n_heads
    cd = cfg.compute_dtype
    xc = x.astype(cd)
    if cfg.qkv_kernel and qkv_proj_supported(h_loc, S, h_loc * hd, d):
        # fused Pallas projection: head-PAIR (N=128) MXU tiles — the
        # direct-BHSD einsums below run at ~94 TF/s (half lanes) because
        # each head's output N-tile is 64 wide (r5 trace)
        q, k_, v = qkv_proj(xc, w_qkv.astype(cd), b_qkv.astype(cd), h_loc)
    else:
        # [B, H, S, Dh] straight out of three per-tensor projections
        # ("bsd,dhe->bhse"): r5 traces show the old plain-matmul +
        # transpose pattern no longer fuses (6x ~8-10ms relayout copies)
        wq, wk, wv = jnp.split(w_qkv.astype(cd), 3, axis=-1)
        bq, bk, bv = jnp.split(b_qkv.astype(cd), 3, axis=-1)

        def proj(w, b):
            out = jnp.einsum("bsd,dhe->bhse", xc, w.reshape(d, h_loc, hd))
            return out + b.reshape(h_loc, 1, hd)
        q, k_, v = proj(wq, bq), proj(wk, bk), proj(wv, bv)
    ctx = splash_mha(q, k_, v, causal=True, scale=1.0 / math.sqrt(hd),
                     save_residuals_for_remat=(
                         cfg.remat_policy == "save_splash_residuals"))
    out = jnp.einsum("bhse,hed->bsd", ctx.astype(cd),
                     w_o.astype(cd).reshape(h_loc, hd, d))
    # row-parallel: partial sums over mp; reduction by caller
    return out, b_o


def _dense_ffn(x, w1, b1, w2, b2, cfg: GPTConfig):
    cd = cfg.compute_dtype
    h = jnp.einsum("bsd,df->bsf", x.astype(cd), w1.astype(cd)) \
        + b1.astype(cd)
    h = jax.nn.gelu(h)
    out = jnp.einsum("bsf,fd->bsd", h, w2.astype(cd))
    return out, b2


def _moe_data_axes(cfg: GPTConfig):
    """Mesh axes the token batch is sharded over (None outside a
    multi-rank mesh): the axes MoE routing statistics must psum across
    for EP/DP-invariant aux losses and global expert counts."""
    axes = tuple(a for a, n in (("dp", cfg.dp), ("ep", cfg.ep)) if n > 1)
    return axes or None


def _zero_moe_stats(cfg: GPTConfig):
    """The per-block MoE stats pytree (dense blocks contribute zeros so
    the scan carry keeps one static structure)."""
    E = max(cfg.moe_experts, 1)
    return {"balance": jnp.zeros((), jnp.float32),
            "z": jnp.zeros((), jnp.float32),
            "counts": jnp.zeros((E,), jnp.float32),
            "dropped": jnp.zeros((), jnp.float32)}


def _moe_ffn(x, gate_w, w1, b1, w2, b2, cfg: GPTConfig):
    """Top-k capacity-factor MoE with expert parallelism over "ep".

    x [B, S, d] local tokens. Experts: E total, E/ep resident per ep
    rank (w1 local [E_loc, d, ff_loc]). Routing/dispatch/combine come
    from `parallel.moe_utils` (fixed one-hot einsums); the [E, C, d]
    dispatch tensor rides `lax.all_to_all` over "ep" to the expert
    owners and back (the compiled global_scatter/global_gather).
    Capacity-overflowed (token, choice) pairs contribute 0 — the
    block's residual connection is the drop path. Returns
    (out_partial_over_mp, stats) with stats per `_zero_moe_stats`
    (balance/z losses are psum'd over the data axes so they are
    invariant to the dp x ep token sharding)."""
    from . import moe_utils
    cd = cfg.compute_dtype
    B, S, d = x.shape
    T = B * S
    E = cfg.moe_experts
    ep = cfg.ep
    axes = _moe_data_axes(cfg)
    xt = x.reshape(T, d)
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                        gate_w.astype(jnp.float32))
    C = moe_utils.expert_capacity(T, E, cfg.moe_top_k,
                                  cfg.moe_capacity_factor)
    r = moe_utils.top_k_routing(logits, cfg.moe_top_k, C, axes=axes,
                                dtype=cd)
    dispatched = moe_utils.dispatch_tokens(xt.astype(cd), r.plan)
    if ep > 1:
        expert_in = moe_utils.all_to_all_dispatch(dispatched, "ep", ep)
    else:
        expert_in = dispatched                   # [E(=E_loc), C, d]
    h = jnp.einsum("ecd,edf->ecf", expert_in, w1.astype(cd)) \
        + b1[:, None, :].astype(cd)
    h = jax.nn.gelu(h)
    # b2 is replicated over mp while the matmul is a row-parallel
    # PARTIAL (w2 holds an ff/mp shard) that the caller psums: scale
    # the bias by 1/mp so the psum restores it exactly once — adding
    # it unscaled would count it mp times (it must ride inside the
    # expert buffer, not after the combine, because each token's bias
    # share is gate-weighted per selected expert)
    eout = jnp.einsum("ecf,efd->ecd", h, w2.astype(cd)) \
        + (b2[:, None, :] / cfg.mp).astype(cd)
    if ep > 1:
        eout = moe_utils.all_to_all_combine(eout, "ep", ep)
    out = moe_utils.combine_tokens(eout, r.plan)
    counts, dropped = r.plan.counts, r.plan.dropped
    if axes:
        counts = jax.lax.psum(counts, axes)
        dropped = jax.lax.psum(dropped, axes)
    stats = {"balance": r.balance_loss, "z": r.z_loss,
             "counts": counts, "dropped": dropped}
    return out.reshape(B, S, d), stats


def _block(x, lp, cfg: GPTConfig):
    """One transformer block on (possibly seq-sharded) activations.

    x: [B, S_loc, d] where S_loc = S/mp if sequence_parallel else S.
    Returns same shape. Partial row-parallel outputs are reduced with
    psum (dense) or psum_scatter (sequence parallel).
    """
    sp = cfg.sequence_parallel and cfg.mp > 1

    def reduce_mp(t):
        if cfg.mp == 1:
            return t
        if sp:
            return jax.lax.psum_scatter(t, "mp", scatter_dimension=1,
                                        tiled=True)
        return jax.lax.psum(t, "mp")

    def gather_sp(t):
        if sp:
            return jax.lax.all_gather(t, "mp", axis=1, tiled=True)
        return t

    h = _layer_norm(x, lp["ln1_w"], lp["ln1_b"])
    h = gather_sp(h)                      # full seq into attention
    attn, b_o = _attention(h, lp["w_qkv"], lp["b_qkv"], lp["w_o"],
                           lp["b_o"], cfg)
    attn = reduce_mp(attn) + b_o.astype(attn.dtype)
    if cfg.fused_add_ln:
        from ..ops.pallas.layer_norm import add_ln
        h2, x = add_ln(x, attn.astype(x.dtype), lp["ln2_w"],
                       lp["ln2_b"])
    else:
        x = x + attn.astype(x.dtype)
        h2 = _layer_norm(x, lp["ln2_w"], lp["ln2_b"])
    aux = _zero_moe_stats(cfg)
    if cfg.moe_experts:
        h2 = gather_sp(h2)
        ff, aux = _moe_ffn(h2, lp["gate"], lp["w_fc1"], lp["b_fc1"],
                           lp["w_fc2"], lp["b_fc2"], cfg)
        ff = reduce_mp(ff)
        bias = 0.0
    else:
        h2 = gather_sp(h2)
        ff, b2 = _dense_ffn(h2, lp["w_fc1"], lp["b_fc1"], lp["w_fc2"],
                            lp["b_fc2"], cfg)
        ff = reduce_mp(ff)
        bias = b2.astype(ff.dtype)
    # NOTE r5: a delayed-add carry variant (ff residual pending in the
    # carry, folded into the next block's fused add+LN) measured 37.0k
    # vs 39.5k tok/s -- the doubled remat carry outweighs the saved
    # residual-add fusions. Keep the plain add.
    x = x + (ff + bias).astype(x.dtype)
    return x, aux


def _stage_forward(x, blocks_local, cfg: GPTConfig):
    """Run this pp rank's layers (scan over the stacked layer dim)."""
    if cfg.remat:
        # default: full per-block remat — recompute the whole block in
        # backward. (The plain dots-saveable policy keeps the [B,H,S,S]
        # attention logits per layer — ~1GB/layer at S=1024 — and OOMs a
        # 16GB chip; fused attention hides its internals from the policy,
        # so named no-batch-dims policies are safe to try via
        # cfg.remat_policy.)
        policy = None
        if cfg.remat_policy == "save_splash_residuals":
            # keep the splash kernel's (out, logsumexp) residuals across
            # the backward: the block still fully remats (LN/FFN/matmuls
            # recompute) but the attention forward does NOT re-run — its
            # fused bwd kernel reads the saved residuals directly.
            # +~66MB/layer at [32,16,1024,64] bf16 for -1 splash fwd pass
            from ..ops.pallas.flash_attention import SPLASH_RESIDUAL_NAME
            policy = jax.checkpoint_policies.save_only_these_names(
                SPLASH_RESIDUAL_NAME)
        elif cfg.remat_policy is not None:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy)
        block_fn = jax.checkpoint(lambda c, p: _block(c, p, cfg),
                                  policy=policy)
    else:
        block_fn = lambda c, p: _block(c, p, cfg)  # noqa: E731

    if cfg.unroll_layers:
        n = jax.tree_util.tree_leaves(blocks_local)[0].shape[0]
        aux_tot = _zero_moe_stats(cfg)
        for i in range(n):
            lp = jax.tree_util.tree_map(lambda a: a[i], blocks_local)
            x, aux = block_fn(x, lp)
            aux_tot = jax.tree.map(jnp.add, aux_tot, aux)
        return x, aux_tot

    def body(carry, lp):
        y, aux = block_fn(carry, lp)
        return y, aux
    x, auxs = jax.lax.scan(body, x, blocks_local)
    return x, jax.tree.map(lambda a: jnp.sum(a, axis=0), auxs)


def _vocab_parallel_embed(tokens, tok_emb_local, cfg: GPTConfig):
    """c_embedding parity: rows sharded over mp; out-of-shard rows
    contribute 0 and psum assembles the full embedding."""
    V_loc = tok_emb_local.shape[0]
    if cfg.mp == 1:
        return jnp.take(tok_emb_local, tokens, axis=0)
    rank = jax.lax.axis_index("mp")
    start = rank * V_loc
    local = tokens - start
    ok = (local >= 0) & (local < V_loc)
    emb = jnp.take(tok_emb_local, jnp.clip(local, 0, V_loc - 1), axis=0)
    emb = jnp.where(ok[..., None], emb, 0.0)
    return jax.lax.psum(emb, "mp")


def _ce_sum_fused(y, head_local, labels, cfg: GPTConfig):
    """mp=1 fused softmax-CE (sum) with a custom vjp.

    The reference's `c_softmax_with_cross_entropy` / Megatron fused CE
    capability, TPU-style: logits stay in compute dtype (bf16) and are
    NEVER saved — the fp32 upcast feeds only the logsumexp/gather
    *reductions* (XLA fuses the convert into them, so no fp32 [B,S,V]
    buffer materialises), and the backward recomputes the bf16 logits
    from (y, head) with one extra head matmul. Residuals are just
    (yc, hc, lse, labels): the head's ~6.6GB fp32 logits highwater at
    [32,1024,50304] drops to a transient bf16 3.3GB, which is what buys
    the memory for the save_splash_residuals remat policy."""
    cd = cfg.compute_dtype
    y_dt, h_dt = y.dtype, head_local.dtype

    def _logits(yc, hc):
        return jnp.einsum("bsd,dv->bsv", yc, hc,
                          preferred_element_type=cd)

    @jax.custom_vjp
    def ce(y, head, labels):
        return _fwd(y, head, labels)[0]

    def _fwd(y, head, labels):
        logits = _logits(y.astype(cd), head.astype(cd))
        lf = logits.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(lf, axis=-1)
        tgt = jnp.take_along_axis(lf, labels[..., None], axis=-1)[..., 0]
        # residuals are (y, head, lse, labels): y and head are alive in
        # the caller anyway (no extra buffer), the bf16 casts + logits
        # recompute in _bwd
        return jnp.sum(lse - tgt), (y, head, lse, labels)

    def _bwd(res, g):
        y, head, lse, labels = res
        yc, hc = y.astype(cd), head.astype(cd)
        logits = _logits(yc, hc)
        # d/dlogits = softmax - onehot
        probs = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
        oh = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
        dlogits = (g * (probs - oh)).astype(cd)
        dy = jnp.einsum("bsv,dv->bsd", dlogits, hc,
                        preferred_element_type=jnp.float32)
        dw = jnp.einsum("bsd,bsv->dv", yc, dlogits,
                        preferred_element_type=jnp.float32)
        return (dy.astype(y_dt), dw.astype(h_dt),
                np.zeros(labels.shape, jax.dtypes.float0))

    ce.defvjp(_fwd, _bwd)
    return ce(y, head_local, labels)


def _ce_sum(y, head_local, labels, cfg: GPTConfig):
    """Sum (not mean) of token CE over y [B,S',d]."""
    V_loc = head_local.shape[1]
    if cfg.mp == 1 and cfg.fused_ce:
        return _ce_sum_fused(y, head_local, labels, cfg)
    logits = jnp.einsum("bsd,dv->bsv", y.astype(cfg.compute_dtype),
                        head_local.astype(cfg.compute_dtype),
                        preferred_element_type=jnp.float32)
    if cfg.mp == 1:
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, labels[..., None],
                                  axis=-1)[..., 0]
        return jnp.sum(lse - tgt)
    rank = jax.lax.axis_index("mp")
    start = rank * V_loc
    # stable global logsumexp
    local_max = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
    gmax = jax.lax.pmax(local_max, "mp")
    sumexp = jnp.sum(jnp.exp(logits - gmax[..., None]), axis=-1)
    Z = jax.lax.psum(sumexp, "mp")
    lse = jnp.log(Z) + gmax
    local_lab = labels - start
    ok = (local_lab >= 0) & (local_lab < V_loc)
    tgt_local = jnp.take_along_axis(
        logits, jnp.clip(local_lab, 0, V_loc - 1)[..., None], axis=-1)[..., 0]
    tgt = jax.lax.psum(jnp.where(ok, tgt_local, 0.0), "mp")
    return jnp.sum(lse - tgt)


def _vocab_parallel_ce(y, head_local, labels, cfg: GPTConfig):
    """c_softmax_with_cross_entropy parity. y [B,S,d] full seq; head_local
    [d, V/mp]; labels [B,S]. Returns mean loss (replicated over mp).

    ce_seq_chunks > 1 streams the head matmul + CE over sequence chunks
    (lax.map + per-chunk remat) so the fp32 [B,S,V] logits never fully
    materialise — the backward recomputes each chunk's logits."""
    B, S, _ = y.shape
    C = max(1, cfg.ce_seq_chunks)
    if C == 1 or S % C != 0:
        return _ce_sum(y, head_local, labels, cfg) / (B * S)
    Sc = S // C
    yc = jnp.swapaxes(y.reshape(B, C, Sc, -1), 0, 1)      # [C,B,Sc,d]
    lc = jnp.swapaxes(labels.reshape(B, C, Sc), 0, 1)     # [C,B,Sc]

    def chunk(args):
        yy, ll = args
        return _ce_sum(yy, head_local, ll, cfg)

    sums = jax.lax.map(jax.checkpoint(chunk), (yc, lc))
    return jnp.sum(sums) / (B * S)


# ------------------------------------------------------- pipeline + loss


def _loss_fn(params, tokens, labels, cfg: GPTConfig, dp_mean=True):
    """Per-device (inside shard_map) pipelined forward loss.

    tokens/labels: [B_local, S] (dp-sharded batch, full on this stage).
    GPipe schedule over cfg.micro_batches microbatches with ppermute.
    dp_mean=False returns the LOCAL shard's loss (no dp pmean) — the
    bucketed-grad path differentiates that per device and does the dp
    reduction itself, bucket by bucket.
    """
    pp, M = cfg.pp, cfg.micro_batches
    B_loc, S = tokens.shape
    assert B_loc % M == 0, "local batch must divide micro_batches"
    Bm = B_loc // M
    d = cfg.d_model
    sp = cfg.sequence_parallel and cfg.mp > 1
    S_loc = S // cfg.mp if sp else S
    cd = cfg.compute_dtype

    tok_m = tokens.reshape(M, Bm, S)
    lab_m = labels.reshape(M, Bm, S)
    T = M + pp - 1
    # tick t: stage0 consumes micro t (t < M); last stage finishes micro
    # t-(pp-1)
    pad_tok = jnp.zeros((T - M, Bm, S), tok_m.dtype)
    tok_sched = jnp.concatenate([tok_m, pad_tok], axis=0)
    pad_lab = jnp.zeros((T - M, Bm, S), lab_m.dtype)
    lab_sched = jnp.concatenate([jnp.zeros((pp - 1, Bm, S), lab_m.dtype),
                                 lab_m], axis=0)[:T]

    stage = jax.lax.axis_index("pp") if pp > 1 else 0
    is_first = stage == 0
    is_last = stage == pp - 1

    pos = params["pos_emb"][:S].astype(cd)

    def embed(tok):
        e = _vocab_parallel_embed(tok, params["tok_emb"], cfg).astype(cd)
        e = e + pos[None]
        if sp:
            rank = jax.lax.axis_index("mp")
            e = jax.lax.dynamic_slice_in_dim(e, rank * S_loc, S_loc, axis=1)
        return e

    def head_loss(y, lab_t):
        """Final LN + vocab head + CE — the O(B·S·d·V) matmul."""
        yl = _layer_norm(y, params["ln_f_w"], params["ln_f_b"])
        if sp:
            yl = jax.lax.all_gather(yl, "mp", axis=1, tiled=True)
        return _vocab_parallel_ce(yl, params["head"], lab_t, cfg)

    def tick(carry, xs):
        x_recv, loss_sum, aux_sum, n_done = carry
        tok_t, lab_t, t = xs
        if pp > 1:
            # lax.cond (not where): the embedding psum and especially the
            # [B,S,d]x[d,V] head matmul must only RUN on the stage that
            # needs them — at pp=4 and real vocab sizes the discarded head
            # matmuls would be a large pure-waste cost per tick. The
            # predicates are uniform across each mp group (same pp stage,
            # same tick), so the mp collectives inside the branches are
            # deadlock-free.
            x_in = jax.lax.cond(
                is_first, lambda: embed(tok_t).astype(x_recv.dtype),
                lambda: x_recv)
        else:
            x_in = embed(tok_t)
        y, aux = _stage_forward(x_in, params["blocks"], cfg)
        # this stage holds a REAL microbatch only for ticks in
        # [stage, stage+M); bubble ticks process padding and must not
        # contribute to the MoE losses or expert counts
        stage_valid = jnp.logical_and(t - stage >= 0, t - stage < M) \
            if pp > 1 else jnp.asarray(True)
        aux = jax.tree.map(
            lambda a: jnp.where(stage_valid, a, jnp.zeros_like(a)), aux)
        # pass activations down the pipe (circular; stage0's recv is unused)
        if pp > 1:
            x_next = jax.lax.ppermute(
                y, "pp", [(i, (i + 1) % pp) for i in range(pp)])
        else:
            x_next = y
        # last stage only: head + CE when a real micro has arrived
        if pp > 1:
            valid = jnp.logical_and(is_last, t >= pp - 1)
            loss_t = jax.lax.cond(
                valid, lambda: head_loss(y, lab_t),
                lambda: jnp.zeros((), jnp.float32))
        else:
            valid = t >= 0
            loss_t = head_loss(y, lab_t)
        loss_sum = loss_sum + jnp.where(valid, loss_t, 0.0)
        aux_sum = jax.tree.map(jnp.add, aux_sum, aux)
        n_done = n_done + jnp.where(valid, 1.0, 0.0)
        return (x_next, loss_sum, aux_sum, n_done), None

    x0 = jnp.zeros((Bm, S_loc, d), cd)
    (xf, loss_sum, aux_sum, n_done), _ = jax.lax.scan(
        tick, (x0, jnp.zeros((), jnp.float32), _zero_moe_stats(cfg),
               jnp.zeros((), jnp.float32)),
        (tok_sched, lab_sched, jnp.arange(T)))

    # average loss over microbatches; broadcast from last stage over pp
    loss = loss_sum / jnp.maximum(n_done, 1.0)
    if pp > 1:
        loss = jax.lax.psum(
            jnp.where(is_last, loss, 0.0), "pp")
    # MoE aux losses: each stage accumulated its local layers' stats
    # over its M valid ticks; psum over pp totals all layers. Balance/z
    # normalize to per-layer-per-micro; counts/dropped stay raw totals
    # for this step (already psum'd over the dp x ep token axes inside
    # `_moe_ffn`, so they are the GLOBAL step totals, replicated).
    stats = None
    if cfg.moe_experts:
        stats = aux_sum
        if pp > 1:
            stats = jax.lax.psum(stats, "pp")
        per = cfg.n_layers * max(M, 1)
        stats = dict(stats, balance=stats["balance"] / per,
                     z=stats["z"] / per)
        loss = loss + cfg.moe_aux_weight * stats["balance"] \
            + cfg.moe_z_weight * stats["z"]
    # mean over the data axes (each dp x ep rank computed its shard's
    # loss; the MoE stats are already axis-invariant)
    daxes = _moe_data_axes(cfg)
    if daxes and dp_mean:
        loss = jax.lax.pmean(loss, daxes)
    if cfg.moe_experts:
        return loss, stats
    return loss


# ------------------------------------------- bucketed DP grad reduction


def grad_bucket_count(params, bucket_bytes, grad_dtype=None):
    """Host-side mirror of `_bucketed_psum`'s bucket plan: per dtype,
    ceil(total_elems / elems_per_bucket). The overlap_smoke HLO contract
    checks the compiled step against exactly this number."""
    per_dtype = {}
    for leaf in jax.tree.leaves(params):
        dt = jnp.dtype(grad_dtype) if grad_dtype is not None \
            else jnp.dtype(leaf.dtype)
        if not jnp.issubdtype(dt, jnp.inexact):
            continue
        per_dtype[str(dt)] = per_dtype.get(str(dt), 0) + int(
            np.prod(leaf.shape))
    n = 0
    for dt, elems in per_dtype.items():
        per = max(1, int(bucket_bytes) // jnp.dtype(dt).itemsize)
        n += -(-elems // per)
    return n


def _bucketed_psum(grads, bucket_bytes, axis="dp"):
    """Reduce a pytree of per-device partial grads with ONE lax.psum per
    <= bucket_bytes flat bucket per dtype (instead of one per leaf).

    Bucket k+1's payload is optimization_barrier-chained on bucket k's
    result: XLA cannot re-combine the all-reduces into one op (which
    would undo the bucketing and its overlap) and must issue them in
    order — backward-completion order, since the flat layout follows
    the (reversed) leaf order. Returns (reduced_grads, n_buckets);
    n_buckets is static, = `grad_bucket_count`."""
    leaves, tree = jax.tree.flatten(grads)
    by_dtype = {}
    for i, g in enumerate(leaves):
        if jnp.issubdtype(g.dtype, jnp.inexact):
            by_dtype.setdefault(str(g.dtype), []).append(i)
    out = list(leaves)
    n_buckets = 0
    for dt, idxs in by_dtype.items():
        # reversed leaf order ~ backward completion order (the head /
        # late layers' grads retire first)
        idxs = list(reversed(idxs))
        flat = jnp.concatenate([leaves[i].ravel() for i in idxs]) \
            if len(idxs) > 1 else leaves[idxs[0]].ravel()
        per = max(1, int(bucket_bytes) // jnp.dtype(dt).itemsize)
        nb = -(-int(flat.shape[0]) // per)
        pieces, prev = [], None
        for k in range(nb):
            chunk = flat[k * per:(k + 1) * per]
            if prev is not None:
                chunk, _ = jax.lax.optimization_barrier((chunk, prev))
            red = jax.lax.psum(chunk, axis)
            pieces.append(red)
            prev = red
        n_buckets += nb
        red_flat = jnp.concatenate(pieces) if len(pieces) > 1 \
            else pieces[0]
        off = 0
        for i in idxs:
            sz = int(np.prod(leaves[i].shape))
            out[i] = red_flat[off:off + sz].reshape(leaves[i].shape)
            off += sz
    return jax.tree.unflatten(tree, out), n_buckets


# ------------------------------------------------------------ optimizer
#
# Gradients are taken OUTSIDE the loss shard_map (jax.value_and_grad of the
# shard_map'ed loss): shard_map's transpose machinery then inserts the
# correct cross-replica psums for every replicated leaf (verified: grads of
# replicated params used before column-parallel matmuls are WRONG if
# jax.grad runs inside shard_map with check_vma=False, and correct outside
# — see tests/test_hybrid_gpt.py). The optimizer update below therefore
# operates on full logical grads at the jit level; ZeRO sharding is
# expressed with GSPMD sharding constraints (the all-gather that
# group_sharded stage1/2 does by hand falls out of the constraint).


def _world_axes(cfg: GPTConfig):
    axes = []
    if cfg.dp > 1:
        axes.append("dp")
    if cfg.pp > 1:
        axes.append("pp")
    if cfg.mp > 1:
        axes.append("mp")
    if cfg.ep > 1:
        axes.append("ep")
    return tuple(axes)


def _zero_pad(cfg, n):
    from .zero import pad_len
    return pad_len(n, max(cfg.dp * cfg.pp * cfg.mp * cfg.ep, 1))


def init_opt_state(cfg: GPTConfig, params):
    """fp32 Adam moments. ZeRO (stage>=1): moments stored as a flat vector
    sharded over the whole device world (FSDP-style full sharding of
    optimizer state — the group_sharded stage1/2 capability)."""
    def per_leaf(p):
        if cfg.zero_stage >= 1:
            n = _zero_pad(cfg, p.size)
            return {"m": jnp.zeros((n,), jnp.float32),
                    "v": jnp.zeros((n,), jnp.float32)}
        return {"m": jnp.zeros(p.shape, jnp.float32),
                "v": jnp.zeros(p.shape, jnp.float32)}
    return jax.tree.map(per_leaf, params)


def opt_specs(cfg: GPTConfig, pspecs):
    def per_leaf(spec):
        if cfg.zero_stage >= 1:
            axes = _world_axes(cfg)
            # canonical form: P(), not P(None) — these leaves are
            # pinned as step out_shardings, where the two are
            # DIFFERENT jit-cache keys (analysis.specs, rule RH202)
            s = P(axes) if axes else P()
            return {"m": s, "v": s}
        return {"m": spec, "v": spec}
    return jax.tree.map(per_leaf, pspecs,
                        is_leaf=lambda x: isinstance(x, P))


def _adam_update(cfg, p, g, m, v, lr, t, wd):
    b1, b2 = cfg.beta1, cfg.beta2
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mhat = m / (1 - b1 ** t)
    vhat = v / (1 - b2 ** t)
    upd = mhat / (jnp.sqrt(vhat) + cfg.eps) + wd * p
    return p - lr * upd, m, v


def _apply_updates(cfg: GPTConfig, mesh, params, grads, opt_state, lr, t):
    """Logical-level Adam with optional ZeRO sharding constraints."""
    flat_p, tree = jax.tree.flatten(params)
    flat_g = jax.tree.leaves(grads)
    flat_s = jax.tree.leaves(
        opt_state, is_leaf=lambda x: isinstance(x, dict) and "m" in x)
    axes = _world_axes(cfg)
    zshard = NamedSharding(mesh, P(axes if axes else None))
    new_p, new_s = [], []
    for p, g, s in zip(flat_p, flat_g, flat_s):
        g = g.astype(jnp.float32)
        wd = 0.0 if p.ndim <= 1 else cfg.weight_decay
        if cfg.zero_stage >= 1:
            n = p.size
            npad = _zero_pad(cfg, n)
            pf = jnp.pad(p.astype(jnp.float32).reshape(-1), (0, npad - n))
            gf = jnp.pad(g.reshape(-1), (0, npad - n))
            # constrain the update to run sharded over the world: XLA
            # reduce-scatters grads in and all-gathers params out (ZeRO).
            pf = jax.lax.with_sharding_constraint(pf, zshard)
            gf = jax.lax.with_sharding_constraint(gf, zshard)
            p2, m, v = _adam_update(cfg, pf, gf, s["m"], s["v"], lr, t, wd)
            new_p.append(p2[:n].reshape(p.shape).astype(p.dtype))
            new_s.append({"m": m, "v": v})
        else:
            p2, m, v = _adam_update(cfg, p.astype(jnp.float32), g,
                                    s["m"], s["v"], lr, t, wd)
            new_p.append(p2.astype(p.dtype))
            new_s.append({"m": m, "v": v})
    return (jax.tree.unflatten(tree, new_p),
            jax.tree.unflatten(tree, new_s))


# --------------------------------------------------------------- driver


def collective_bytes_per_step(cfg: GPTConfig, batch: int):
    """Analytic LOGICAL payload bytes per train step for the collectives
    GSPMD/shard_map compiles into the hybrid step (the compiled path
    fuses them into the executable, so the eager accounting in
    parallel/collective.py never sees them). Returns {label: bytes};
    wire bytes differ by the usual ring factors (all-reduce moves
    ~2(n-1)/n of payload over ICI). Single-chip configs (dp=pp=mp=1,
    zero off) honestly report no collective traffic."""
    d, L, S, V = cfg.d_model, cfg.n_layers, cfg.seq_len, cfg.vocab_size
    act_bytes = jnp.dtype(cfg.compute_dtype).itemsize
    n_params = 12 * L * d * d + V * d + S * d
    out = {}
    if cfg.mp > 1:
        # fwd: embedding psum + 2 psums/layer (attn out, mlp out) +
        # vocab-parallel CE psums; bwd mirrors them (x2)
        fwd = (2 * L + 1) * batch * S * d * act_bytes \
            + 3 * batch * S * 4
        out["mp_psum_est"] = 2 * fwd
    if cfg.dp > 1:
        g_bytes = act_bytes if cfg.bf16_grads else 4
        out["dp_grad_allreduce_est"] = n_params * g_bytes
    if cfg.pp > 1:
        # per-tick activation ppermute over the pp ring, fwd + bwd
        Bm = max(batch // max(cfg.micro_batches, 1), 1)
        out["pp_ppermute_est"] = (2 * cfg.micro_batches * cfg.pp
                                  * Bm * S * d * act_bytes)
    if cfg.moe_experts and cfg.ep > 1:
        # per layer: dispatch + combine all_to_all of the [E, C, d]
        # capacity tensors, fwd + bwd (x2 each)
        from . import moe_utils
        T_loc = max(batch // max(cfg.dp * cfg.ep, 1), 1) * S \
            // max(cfg.micro_batches, 1)
        C = moe_utils.expert_capacity(T_loc, cfg.moe_experts,
                                      cfg.moe_top_k,
                                      cfg.moe_capacity_factor)
        out["ep_alltoall_est"] = (4 * cfg.n_layers * cfg.micro_batches
                                  * cfg.moe_experts * C * d * act_bytes)
    if cfg.zero_stage >= 1 and cfg.dp * cfg.pp * cfg.mp * cfg.ep > 1:
        # optimizer update: grads reduce-scatter in, params all-gather
        # out, fp32 flat buffers; a world of 1 shards nothing
        out["zero_shard_est"] = 2 * n_params * 4
    return out


def auto_parallel_config(cfg: GPTConfig, n_devices, global_batch=32,
                         cluster=None, measurements=None):
    """Run the measurement-driven placement search (`auto_tuner.tune`)
    for this model and return (configured GPTConfig, TunedResult).

    The hybrid step's internal pipeline is the GPipe tick loop in
    `_loss_fn`, so the search prices schedules=("gpipe",); the
    zero-bubble schedule applies to `CompiledPipeline` models. The
    tuner's bucket_size maps onto `grad_bucket_bytes` only when the
    chosen mesh is pure dense DP (the config contract above)."""
    from . import auto_tuner
    cd_bytes = jnp.dtype(cfg.compute_dtype).itemsize
    mspec = auto_tuner.ModelSpec(
        n_layers=cfg.n_layers, d_model=cfg.d_model, seq_len=cfg.seq_len,
        vocab_size=cfg.vocab_size, d_ff=cfg.d_ff,
        global_batch=int(global_batch), n_heads=cfg.n_heads,
        param_bytes=4, grad_bytes=cd_bytes if cfg.bf16_grads else 4,
        act_bytes=cd_bytes, remat=cfg.remat,
        moe_experts=cfg.moe_experts, moe_top_k=cfg.moe_top_k,
        moe_capacity_factor=cfg.moe_capacity_factor)
    # zero_stages limited to what GPTConfig executes (0/1): clamping a
    # zero>=2 winner after the fact would run a config the search's
    # HBM-feasibility gate never admitted
    plan = auto_tuner.tune(mspec, cluster=cluster, n_devices=n_devices,
                           measurements=measurements,
                           schedules=("gpipe",), zero_stages=(0, 1))
    s = plan.strategy
    # the search only admits bucket_size>0 on pure dense-DP (ep=1)
    # meshes, so the scored config IS the executed one
    cfg = dataclasses.replace(
        cfg, dp=s.dp, mp=s.mp, pp=s.pp, ep=s.ep,
        micro_batches=s.micro_batches, zero_stage=s.zero_stage,
        grad_bucket_bytes=s.bucket_size)
    return cfg, plan


class HybridGPT:
    """Builds the mesh + ONE compiled hybrid train step.

    Usage:
        trainer = HybridGPT(cfg)
        params, opt = trainer.init(jax.random.PRNGKey(0))
        params, opt, loss = trainer.train_step(params, opt, tokens, labels)

    strategy="auto" (opt-in) replaces cfg's parallel dims with the
    auto_tuner's measurement-calibrated pick for `global_batch` before
    building; the chosen plan (incl. predicted MFU) is kept on
    `.tuner_plan` so callers can record prediction next to measurement.
    """

    def __init__(self, cfg: GPTConfig, devices=None, strategy=None,
                 global_batch=None, cluster=None, measurements=None):
        devices = devices if devices is not None else jax.devices()
        self.tuner_plan = None
        if strategy == "auto":
            cfg, self.tuner_plan = auto_parallel_config(
                cfg, n_devices=len(devices),
                global_batch=global_batch or 32, cluster=cluster,
                measurements=measurements)
        elif strategy is not None:
            raise ValueError(f"unknown strategy {strategy!r} "
                             "(None or 'auto')")
        self.cfg = cfg
        self.last_moe_stats = None
        self._moe_stats_pending = None
        n = cfg.dp * cfg.pp * cfg.mp * cfg.ep
        assert len(devices) >= n, \
            f"need {n} devices, have {len(devices)}"
        moe = cfg.moe_experts > 0
        # MoE configs ride a 4th "ep" mesh axis (present even at ep=1
        # so expert param specs always resolve and EP=1/EP=2 compile
        # identical program structure); dense configs keep the exact
        # 3-axis mesh — no new axis, no new compile cost. Tokens are
        # data-sharded over (dp, ep) jointly under MoE.
        if moe:
            shape, axes = (cfg.dp, cfg.pp, cfg.mp, cfg.ep), \
                ("dp", "pp", "mp", "ep")
        else:
            shape, axes = (cfg.dp, cfg.pp, cfg.mp), ("dp", "pp", "mp")
        self.mesh = Mesh(np.array(devices[:n]).reshape(shape), axes)
        self.pspecs = param_specs(cfg)
        self.ospecs = opt_specs(cfg, self.pspecs)
        cfg_ref = cfg
        mesh = self.mesh
        data_spec = P(("dp", "ep"), None) if moe else P("dp", None)
        self._data_spec = data_spec

        stats_spec = jax.tree.map(lambda _: P(), _zero_moe_stats(cfg))
        loss_out = (P(), stats_spec) if moe else P()
        loss_sm = _shard_map(
            lambda p, tok, lab: _loss_fn(p, tok, lab, cfg_ref),
            mesh=mesh, in_specs=(self.pspecs, data_spec, data_spec),
            out_specs=loss_out, check_vma=False)

        use_buckets = cfg.grad_bucket_bytes > 0 and cfg.dp > 1
        self._use_buckets = use_buckets
        if use_buckets:
            # grads taken INSIDE shard_map are the per-device partials
            # (no transpose psum) — exactly what the bucketed reduction
            # wants. Correct only because every leaf is dp-replicated
            # here (the pure dense-DP contract enforced by GPTConfig):
            # psum(d local-loss grads / dp) == grad of the dp-mean loss.
            def grads_body(p, tok, lab):
                def local_loss(pp_):
                    return _loss_fn(pp_, tok, lab, cfg_ref,
                                    dp_mean=False) / cfg_ref.dp
                loss, grads = jax.value_and_grad(local_loss)(p)
                loss = jax.lax.psum(loss, "dp")
                grads, _ = _bucketed_psum(grads,
                                          cfg_ref.grad_bucket_bytes)
                return loss, grads

            grads_sm = _shard_map(
                grads_body, mesh=mesh,
                in_specs=(self.pspecs, data_spec, data_spec),
                out_specs=(P(), self.pspecs), check_vma=False)

        def step(params, opt_state, tokens, labels, lr, t):
            mstats = None
            if cfg_ref.bf16_grads:
                cd = cfg_ref.compute_dtype
                target = jax.tree.map(
                    lambda a: a.astype(cd)
                    if a.dtype == jnp.float32 else a, params)
            else:
                target = params
            if use_buckets:
                loss, grads = grads_sm(target, tokens, labels)
            elif moe:
                (loss, mstats), grads = jax.value_and_grad(
                    loss_sm, has_aux=True)(target, tokens, labels)
            else:
                loss, grads = jax.value_and_grad(loss_sm)(target, tokens,
                                                          labels)
            if cfg_ref.grad_clip > 0:
                sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in jax.tree.leaves(grads))
                gnorm = jnp.sqrt(sq)
                scale = jnp.minimum(1.0, cfg_ref.grad_clip / (gnorm + 1e-6))
                grads = jax.tree.map(
                    lambda g: (g.astype(jnp.float32) * scale).astype(
                        g.dtype), grads)
            params, opt_state = _apply_updates(cfg_ref, mesh, params,
                                               grads, opt_state, lr, t)
            if moe:
                return params, opt_state, loss, mstats
            return params, opt_state, loss

        # pin the step outputs to the canonical param/opt shardings:
        # GSPMD otherwise infers spec-different-but-placement-identical
        # shardings for some leaves (P('pp', None) vs P('pp', 'mp') at
        # mp=1), so the SECOND step — fed the first step's outputs —
        # missed the jit cache and every trainer paid a double compile.
        # Specs go through analysis.specs.canonicalize_spec — the one
        # normal form init()/shard_data() ALSO place with, so the
        # out-pin and the initial device_put can never disagree on
        # cache identity (the repeated PR 7/8/10 hand-normalizations,
        # single-sourced).
        cn = lambda s: canonical_sharding(mesh, s)  # noqa: E731
        is_spec = lambda x: isinstance(x, P)       # noqa: E731
        out_shard = (jax.tree.map(cn, self.pspecs, is_leaf=is_spec),
                     jax.tree.map(cn, self.ospecs, is_leaf=is_spec),
                     cn(P()))
        step_shard = out_shard if not moe else out_shard + (
            jax.tree.map(lambda _: cn(P()), _zero_moe_stats(cfg)),)
        self._step = instrumented_jit(step, "HybridGPT.train_step",
                                      donate_argnums=(0, 1),
                                      out_shardings=step_shard)
        self._loss_sm = loss_sm
        self._loss_jit = instrumented_jit(loss_sm, "HybridGPT.loss")

        def steps_k(params, opt_state, tokens, labels, lr, t0, k):
            """K training steps as ONE executable (lax.scan over the
            step body) — the hapi run_many grouping applied to the
            hybrid trainer: amortizes per-dispatch relay latency.
            MoE configs additionally stack the per-step routing stats
            as scan ys so train_many does not silently drop them."""
            def body(carry, i):
                p, o = carry
                res = step(p, o, tokens, labels, lr, t0 + i)
                ys = res[2] if not moe else (res[2], res[3])
                return (res[0], res[1]), ys
            (params, opt_state), ys = jax.lax.scan(
                body, (params, opt_state),
                jnp.arange(k, dtype=jnp.float32))
            if moe:
                losses, stats_k = ys
                return params, opt_state, losses, stats_k
            return params, opt_state, ys

        many_shard = out_shard if not moe else out_shard + (
            jax.tree.map(lambda _: cn(P()), _zero_moe_stats(cfg)),)
        self._steps_k = instrumented_jit(steps_k, "HybridGPT.train_many",
                                         static_argnums=(6,),
                                         donate_argnums=(0, 1),
                                         out_shardings=many_shard)

    def init(self, key):
        # Generate the full logical params UNSHARDED, then device_put
        # into the mesh. Jitting the threefry generation with GSPMD
        # out_shardings is NOT value-stable across mesh topologies on
        # jax 0.4.x (jax_threefry_partitionable=False): the same key
        # yielded different w_qkv/w_fc/tok_emb values on multi-axis
        # meshes (maxdiff ~0.1), which is what broke the combined-mesh
        # loss-parity tests — the divergence was in init, not in the
        # training reduction order. Materializing on one device first
        # costs a transient full-params footprint, acceptable until a
        # partitionable-threefry jax is the floor.
        p_specs = jax.tree.map(
            lambda s: canonical_sharding(self.mesh, s), self.pspecs,
            is_leaf=lambda x: isinstance(x, P))
        p_full = jax.jit(functools.partial(init_params, self.cfg))(key)
        p_init = jax.device_put(p_full, p_specs)
        with self.mesh:
            o_init = jax.jit(
                functools.partial(init_opt_state, self.cfg),
                out_shardings=jax.tree.map(
                    lambda s: canonical_sharding(self.mesh, s),
                    self.ospecs,
                    is_leaf=lambda x: isinstance(x, P)))(p_init)
        return p_init, o_init

    def shard_data(self, tokens, labels):
        ds = canonical_sharding(self.mesh, self._data_spec)
        return (jax.device_put(tokens, ds), jax.device_put(labels, ds))

    def loss(self, params, tokens, labels):
        out = self._loss_jit(params, tokens, labels)
        return out[0] if self.cfg.moe_experts else out

    def loss_and_moe_stats(self, params, tokens, labels):
        """(loss, stats) for MoE configs — stats per `_zero_moe_stats`
        (balance/z per-layer-per-micro means, global expert counts and
        dropped-token total for the batch)."""
        assert self.cfg.moe_experts, "dense config has no MoE stats"
        return self._loss_jit(params, tokens, labels)

    def collective_bytes_per_step(self, batch):
        return collective_bytes_per_step(self.cfg, batch)

    def _record_collectives(self, tokens, steps=1, params=None):
        batch = int(tokens.shape[0])
        for label, nbytes in self.collective_bytes_per_step(batch).items():
            _metrics.COLLECTIVE_CALLS.labels(label).inc(steps)
            _metrics.COLLECTIVE_BYTES.labels(label).inc(nbytes * steps)
        if self._use_buckets and params is not None:
            gd = self.cfg.compute_dtype if self.cfg.bf16_grads else None
            _metrics.GRAD_BUCKETS.labels("compiled").set(
                grad_bucket_count(params, self.cfg.grad_bucket_bytes,
                                  gd))

    def train_step(self, params, opt_state, tokens, labels, lr=None,
                   step_num=1):
        lr = jnp.asarray(lr if lr is not None else self.cfg.learning_rate,
                         jnp.float32)
        t = jnp.asarray(step_num, jnp.float32)
        if _metrics._enabled:
            self._record_collectives(tokens, params=params)
        res = self._step(params, opt_state, tokens, labels, lr, t)
        if self.cfg.moe_experts:
            params, opt_state, loss, mstats = res
            # device arrays; host fetch deferred to the accessor. With
            # metrics on, record the PREVIOUS step's stats — step N is
            # already enqueued, so the device_get of step N-1's
            # (finished) stats never stalls async dispatch; the gauges
            # lag one step
            self.last_moe_stats = mstats
            if _metrics._enabled:
                prev = self._moe_stats_pending
                self._moe_stats_pending = mstats
                if prev is not None:
                    self._record_moe_stats(prev)
            return params, opt_state, loss
        return res

    def _record_moe_stats(self, mstats):
        st = jax.device_get(mstats)
        _metrics.record_moe_stats("train", st["counts"], st["dropped"],
                                  st["balance"])

    def flush_moe_metrics(self):
        """Drain the one-step-lagged MoE metrics (train_step records
        step N when step N+1 dispatches): call after the LAST step of
        a metrics-enabled run so the final step's routing stats land
        in the registry too."""
        if self._moe_stats_pending is not None and _metrics._enabled:
            self._record_moe_stats(self._moe_stats_pending)
        self._moe_stats_pending = None

    def train_many(self, params, opt_state, tokens, labels, k, lr=None,
                   start_step=1):
        """Run k steps in one device dispatch; returns
        (params, opt_state, losses[k]). MoE configs keep their
        routing stats: `last_moe_stats` holds the FINAL step's and the
        metrics record the k-step aggregate."""
        lr = jnp.asarray(lr if lr is not None else self.cfg.learning_rate,
                         jnp.float32)
        t0 = jnp.asarray(start_step, jnp.float32)
        if _metrics._enabled:
            self._record_collectives(tokens, steps=int(k), params=params)
        res = self._steps_k(params, opt_state, tokens, labels, lr, t0,
                            int(k))
        if self.cfg.moe_experts:
            params, opt_state, losses, stats_k = res
            self.last_moe_stats = jax.tree.map(lambda a: a[-1], stats_k)
            if _metrics._enabled:
                st = jax.device_get(stats_k)
                _metrics.record_moe_stats(
                    "train", np.sum(st["counts"], axis=0),
                    float(np.sum(st["dropped"])),
                    float(st["balance"][-1]))
            return params, opt_state, losses
        return res
