"""Ring attention — context parallelism over the sequence axis.

The reference snapshot has NO sequence/context parallelism (SURVEY §5.7);
this is a first-class TPU-native extension: K/V blocks rotate around the
"cp" mesh axis via `lax.ppermute` (ICI neighbor hops) while each device
holds one query block, accumulating online-softmax partials — attention
memory O(S/cp) per device, compute fully overlapped around the ring
(Liu et al., Ring Attention; the blockwise core matches our pallas flash
kernel's math).

Layout: q/k/v [B, S, H, D] logically; sharded over cp on S. Causal is
handled by masking each (q_block, k_block) pair by their ring offset.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import shard_map as _shard_map


def _block_attn(q, k, v, scale, mask):
    """Online-softmax partials for one (q_block, k_block) pair.
    q [B,Sq,H,D], k/v [B,Sk,H,D]; mask [Sq,Sk] bool or None.
    Returns (acc [B,Sq,H,D] fp32, m [B,H,Sq], l [B,H,Sq])."""
    s = jnp.einsum("bshd,bthd->bhst", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, -1e30)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return acc, m, l


def _ring_attention_local(q, k, v, *, axis_name, cp, causal, scale):
    """Per-device body (inside shard_map). q/k/v local [B, S/cp, H, D]."""
    B, Sl, H, D = q.shape
    rank = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    neg_inf = jnp.full((B, H, Sl), -jnp.inf, jnp.float32)
    zero_l = jnp.zeros((B, H, Sl), jnp.float32)
    zero_acc = jnp.zeros((B, Sl, H, D), jnp.float32)

    def step(carry, i):
        k_cur, v_cur, m_prev, l_prev, acc_prev = carry
        # k_cur originated on rank (rank - i) mod cp
        src = (rank - i) % cp
        if causal:
            q_pos = rank * Sl + jnp.arange(Sl)
            k_pos = src * Sl + jnp.arange(Sl)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        acc_i, m_i, l_i = _block_attn(q, k_cur, v_cur, scale, mask)
        m_new = jnp.maximum(m_prev, m_i)
        a1 = jnp.exp(m_prev - m_new)
        a2 = jnp.exp(m_i - m_new)
        l_new = l_prev * a1 + l_i * a2
        acc_new = (acc_prev * jnp.transpose(a1, (0, 2, 1))[..., None]
                   + acc_i * jnp.transpose(a2, (0, 2, 1))[..., None])
        # rotate k/v to the next rank
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, acc_new), None

    (k_f, v_f, m, l, acc), _ = jax.lax.scan(
        step, (k, v, neg_inf, zero_l, zero_acc), jnp.arange(cp))
    l_t = jnp.transpose(jnp.maximum(l, 1e-30), (0, 2, 1))[..., None]
    return (acc / l_t).astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis_name="cp", causal=True,
                   scale=None):
    """q/k/v: [B, S, H, D] logical arrays (or sharded); returns same.

    When `mesh` is None builds a 1-D mesh over all devices. S must divide
    by the cp size.
    """
    if mesh is None:
        n = jax.device_count()
        mesh = Mesh(np.array(jax.devices()).reshape(n), (axis_name,))
    cp = mesh.shape[axis_name]
    B, S, H, D = q.shape
    assert S % cp == 0, f"seq {S} must divide cp {cp}"
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    body = functools.partial(_ring_attention_local, axis_name=axis_name,
                             cp=cp, causal=causal, scale=scale)
    spec = P(None, axis_name, None, None)
    fn = _shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
