"""DistributedStrategy.

Parity: `python/paddle/distributed/fleet/base/distributed_strategy.py:109`
backed by `framework/distributed_strategy.proto:305` (233 fields). Here a
plain dataclass-style object covering the fields the TPU engine consumes:
hybrid degrees, amp, recompute, sharding, gradient merge, moe/ep, sp.
"""
from __future__ import annotations


class DistributedStrategy:
    def __init__(self):
        # collective hybrid parallel (proto: hybrid_configs)
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,   # sequence/context parallel (TPU extension)
            "ep_degree": 1,    # expert parallel
        }
        # amp (proto: amp / amp_configs)
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0,
            "use_pure_bf16": True,
            "use_dynamic_loss_scaling": False,
            "custom_white_list": [],
            "custom_black_list": [],
        }
        # recompute (proto: recompute / recompute_configs)
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        # sharding (proto: sharding_configs)
        self.sharding = False
        self.sharding_configs = {
            "stage": 1,
            "degree": 1,
            "offload": False,
        }
        # gradient merge / accumulation
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        # pipeline
        self.pipeline = False
        self.pipeline_configs = {
            "accumulate_steps": 1,
            "micro_batch_size": 1,
            "schedule_mode": "1F1B",
        }
        # parameter server (a_sync etc.)
        self.a_sync = False
        self.a_sync_configs = {"k_steps": 0}
        # misc parity fields
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.without_graph_optimization = True

    def to_dict(self):
        return {k: v for k, v in self.__dict__.items()}

    def __repr__(self):
        lines = ["DistributedStrategy("]
        for k, v in self.__dict__.items():
            lines.append(f"  {k}={v},")
        return "\n".join(lines) + ")"
