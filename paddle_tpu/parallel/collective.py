"""Eager collective API over sharded arrays.

Parity: the ProcessGroup suite (`paddle/fluid/distributed/collective/
ProcessGroup.h:53` — AllReduce :99, Broadcast :117, AllGather :199,
AllToAll :234, Reduce, Scatter, Send/Recv) + python
`paddle.distributed.all_reduce/...` (`python/paddle/distributed/
communication/`).

TPU-native: there is no NCCL; a "collective" over the dp world on one host
is a `shard_map`-wrapped `jax.lax` collective compiled over ICI. The eager
API here operates on REPLICATED host-visible Tensors: each rank slot of a
sharded tensor is dim 0 of the array (the single-controller SPMD view).
These functions exist for API parity and for the eager DataParallel path;
the performance path fuses collectives inside jitted steps (pjit/GSPMD).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..ops._helpers import as_tensor
from . import env as dist_env


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """Communication group = a named axis over a sub-mesh.

    Parity: `paddle.distributed.collective.Group` /
    `ProcessGroup` (gid, ranks)."""

    def __init__(self, ranks=None, gid=0, name="dp"):
        all_n = dist_env.get_world_size()
        self.ranks = list(ranks) if ranks is not None else list(range(all_n))
        self.nranks = len(self.ranks)
        self.id = gid
        self.name = name

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1


_default_group = None
_group_counter = 0


def _get_group(group):
    global _default_group
    if group is not None:
        return group
    if _default_group is None:
        _default_group = Group()
    return _default_group


def new_group(ranks=None, backend=None, timeout=None):
    global _group_counter
    _group_counter += 1
    return Group(ranks, _group_counter)


def get_group(gid=0):
    return _get_group(None)


def _spmd(fn, x, n):
    """Run fn over a length-n leading 'rank' axis with an axis name."""
    mesh = dist_env.global_mesh({"r": n})
    return jax.shard_map(fn, mesh=mesh, in_specs=P("r"), out_specs=P("r"))(x)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In the single-controller SPMD view, an eager all_reduce over the
    device world is an identity on a replicated tensor; for tensors carrying
    a per-rank leading axis it reduces that axis. This matches how the
    eager DP path uses it (gradient reduction)."""
    t = as_tensor(tensor)
    g = _get_group(group)
    if g.nranks <= 1:
        return t
    red = {ReduceOp.SUM: jnp.sum, ReduceOp.MAX: jnp.max,
           ReduceOp.MIN: jnp.min, ReduceOp.PROD: jnp.prod,
           ReduceOp.AVG: jnp.mean}[op]
    if t.shape and t.shape[0] == g.nranks:
        out = Tensor(red(t._data, axis=0))
        tensor_obj = tensor if isinstance(tensor, Tensor) else t
        tensor_obj._data = jnp.broadcast_to(
            out._data[None], t._data.shape) if False else out._data
        return out
    return t


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    t = as_tensor(tensor)
    g = _get_group(group)
    for _ in range(g.nranks):
        tensor_list.append(t)
    return tensor_list


def broadcast(tensor, src=0, group=None, sync_op=True):
    return as_tensor(tensor)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        rank = dist_env.get_rank()
        tensor.set_value(tensor_list[rank if rank < len(tensor_list) else 0])
    return tensor


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    for t in in_tensor_list:
        out_tensor_list.append(as_tensor(t))
    return out_tensor_list


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv across processes requires the multi-host "
        "backend; within one host use pipeline_parallel (ppermute)")


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv across processes requires the multi-host "
        "backend; within one host use pipeline_parallel (ppermute)")


def wait(tensor, group=None, use_calc_stream=True):
    jax.block_until_ready(as_tensor(tensor)._data)


def split(x, num_or_sections, axis=0):
    from ..ops.manipulation import split as _split
    return _split(x, num_or_sections, axis)
