"""Eager collective API over sharded arrays.

Parity: the ProcessGroup suite (`paddle/fluid/distributed/collective/
ProcessGroup.h:53` — AllReduce :99, Broadcast :117, AllGather :199,
AllToAll :234, Reduce, Scatter, Send/Recv) + python
`paddle.distributed.all_reduce/...` (`python/paddle/distributed/
communication/`).

TPU-native: there is no NCCL; a "collective" over the dp world on one host
is a `shard_map`-wrapped `jax.lax` collective compiled over ICI. The eager
API here operates on REPLICATED host-visible Tensors: each rank slot of a
sharded tensor is dim 0 of the array (the single-controller SPMD view).
These functions exist for API parity and for the eager DataParallel path;
the performance path fuses collectives inside jitted steps (pjit/GSPMD).
"""
from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..ops._helpers import as_tensor
from ..profiler import metrics as _metrics
from . import env as dist_env
from . import shard_map as _shard_map


def _payload_nbytes(x):
    """Best-effort payload size of one collective argument."""
    if x is None:
        return 0
    if isinstance(x, Tensor):
        x = x._data
    if isinstance(x, (list, tuple)):
        return sum(_payload_nbytes(v) for v in x)
    size = getattr(x, "size", None)
    dtype = getattr(x, "dtype", None)
    if size is not None and dtype is not None:
        return int(size) * np.dtype(dtype).itemsize
    try:
        return np.asarray(x).nbytes
    except Exception:
        return 0


def _instrumented(kind, payload_arg=0, payload_kw="tensor",
                  count_bytes=True):
    """Count calls / payload bytes / wall seconds per collective when
    metrics are enabled; one branch per call when off. count_bytes=False
    for pure synchronization calls (wait) that move no data."""
    def deco(fn):
        @functools.wraps(fn)
        def wrap(*args, **kwargs):
            if not _metrics._enabled:
                return fn(*args, **kwargs)
            _metrics.COLLECTIVE_CALLS.labels(kind).inc()
            if count_bytes:
                payload = args[payload_arg] if len(args) > payload_arg \
                    else kwargs.get(payload_kw)
                _metrics.COLLECTIVE_BYTES.labels(kind).inc(
                    _payload_nbytes(payload))
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            _metrics.COLLECTIVE_SECONDS.labels(kind).observe(
                time.perf_counter() - t0)
            return out
        return wrap
    return deco


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """Communication group = a named axis over a sub-mesh.

    Parity: `paddle.distributed.collective.Group` /
    `ProcessGroup` (gid, ranks)."""

    def __init__(self, ranks=None, gid=0, name="dp"):
        all_n = dist_env.get_world_size()
        self.ranks = list(ranks) if ranks is not None else list(range(all_n))
        self.nranks = len(self.ranks)
        self.id = gid
        self.name = name

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1


_default_group = None
_group_counter = 0


def _get_group(group):
    global _default_group
    if group is not None:
        return group
    if _default_group is None:
        _default_group = Group()
    return _default_group


def new_group(ranks=None, backend=None, timeout=None):
    global _group_counter
    _group_counter += 1
    return Group(ranks, _group_counter)


def get_group(gid=0):
    return _get_group(None)


def _spmd(fn, x, n):
    """Run fn over a length-n leading 'rank' axis with an axis name."""
    mesh = dist_env.global_mesh({"r": n})
    return _shard_map(fn, mesh=mesh, in_specs=P("r"), out_specs=P("r"))(x)


# --------------------------------------------------------------------------
# multi-process backend: when this is one of several jax processes
# (jax.distributed initialised — the TestDistBase two-rank reality), the
# eager API runs REAL cross-process collectives: each process contributes
# its local tensor as one shard of a global array over a process mesh and
# a jitted XLA collective (gloo on CPU, ICI/DCN on TPU) produces the
# replicated result.
# --------------------------------------------------------------------------


def _multiproc():
    try:
        return jax.process_count() > 1
    except Exception:
        return False


_mp_mesh = None
_mp_jit_cache = {}


def _check_mp_group(group):
    """Multi-process collectives run over the FULL process world; a
    sub-group would silently compute over the wrong ranks."""
    if group is not None and group.nranks != dist_env.get_world_size():
        raise NotImplementedError(
            "multi-process eager collectives support only the default "
            f"(world) group; got a {group.nranks}-rank sub-group of "
            f"{dist_env.get_world_size()}")


def _process_mesh():
    global _mp_mesh
    if _mp_mesh is None:
        from jax.sharding import Mesh
        devs = jax.devices()
        n = jax.process_count()
        # one device per process keeps rank == process (eager contract)
        per = [None] * n
        for d in devs:
            if per[d.process_index] is None:
                per[d.process_index] = d
        _mp_mesh = Mesh(np.array(per), ("r",))
    return _mp_mesh


def _to_global(local_arr, mesh):
    from jax.sharding import NamedSharding
    shard = NamedSharding(mesh, P("r", *([None] * local_arr.ndim)))
    return jax.make_array_from_process_local_data(
        shard, np.asarray(local_arr)[None])


def _mp_collect(local_arr, kind, src=0):
    """Global [world, ...] array -> jitted collective -> replicated host
    value (every process receives the full result). Executables are
    memoized per (kind, src, shape, dtype) — a fresh jit per eager call
    would retrace every time."""
    from jax.sharding import NamedSharding
    mesh = _process_mesh()
    garr = _to_global(local_arr, mesh)
    key = (kind, src, local_arr.shape, str(local_arr.dtype))
    fn = _mp_jit_cache.get(key)
    if fn is None:
        red = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min,
               "prod": jnp.prod, "avg": jnp.mean}
        if kind in red:
            body = (lambda a, _r=red[kind]: _r(a, axis=0))
        elif kind == "gather":
            body = (lambda a: a)
        elif kind == "bcast":
            body = (lambda a: a[src])
        else:
            raise ValueError(kind)
        fn = jax.jit(body, out_shardings=NamedSharding(mesh, P()))
        _mp_jit_cache[key] = fn
    return np.asarray(jax.device_get(fn(garr)))


@_instrumented("all_reduce")
def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In the single-controller SPMD view, an eager all_reduce over the
    device world is an identity on a replicated tensor; for tensors carrying
    a per-rank leading axis it reduces that axis. This matches how the
    eager DP path uses it (gradient reduction)."""
    t = as_tensor(tensor)
    g = _get_group(group)
    red = {ReduceOp.SUM: jnp.sum, ReduceOp.MAX: jnp.max,
           ReduceOp.MIN: jnp.min, ReduceOp.PROD: jnp.prod,
           ReduceOp.AVG: jnp.mean}[op]
    if _multiproc():
        _check_mp_group(group)
        out = _mp_collect(np.asarray(t.numpy()), op)
        tensor_obj = tensor if isinstance(tensor, Tensor) else t
        tensor_obj._data = jnp.asarray(out)
        return tensor_obj
    if g.nranks <= 1:
        return t
    if t.shape and t.shape[0] == g.nranks:
        out = Tensor(red(t._data, axis=0))
        tensor_obj = tensor if isinstance(tensor, Tensor) else t
        tensor_obj._data = jnp.broadcast_to(
            out._data[None], t._data.shape) if False else out._data
        return out
    return t


@_instrumented("all_gather", payload_arg=1)
def all_gather(tensor_list, tensor, group=None, sync_op=True):
    t = as_tensor(tensor)
    g = _get_group(group)
    if _multiproc():
        _check_mp_group(group)
        stacked = _mp_collect(np.asarray(t.numpy()), "gather")
        for i in range(stacked.shape[0]):
            tensor_list.append(Tensor(jnp.asarray(stacked[i])))
        return tensor_list
    for _ in range(g.nranks):
        tensor_list.append(t)
    return tensor_list


@_instrumented("broadcast")
def broadcast(tensor, src=0, group=None, sync_op=True):
    t = as_tensor(tensor)
    if _multiproc():
        _check_mp_group(group)
        out = _mp_collect(np.asarray(t.numpy()), "bcast", src=src)
        tensor_obj = tensor if isinstance(tensor, Tensor) else t
        tensor_obj._data = jnp.asarray(out)
        return tensor_obj
    return t


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # not decorated: delegates to all_reduce, which does the accounting
    return all_reduce(tensor, op, group)


@_instrumented("scatter", payload_arg=1, payload_kw="tensor_list")
def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        rank = dist_env.get_rank()
        tensor.set_value(tensor_list[rank if rank < len(tensor_list) else 0])
    return tensor


@_instrumented("all_to_all", payload_arg=1, payload_kw="in_tensor_list")
def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    for t in in_tensor_list:
        out_tensor_list.append(as_tensor(t))
    return out_tensor_list


@_instrumented("all_reduce_coalesced", payload_kw="tensor_list")
def all_reduce_coalesced(tensor_list, op=ReduceOp.SUM, group=None,
                         sync_op=True):
    """ONE collective over many tensors: flatten every tensor in
    `tensor_list` (same dtype required) into a single 1-D payload,
    all-reduce it once, and scatter the reduced slices back into the
    input tensors in place. This is the wire primitive behind gradient
    bucketing (`fleet_utils.fused_allreduce_gradients`): n tensors cost
    one collective's latency instead of n.

    Like `all_reduce`, the single-controller path is an identity (grads
    of replicated params are already globally reduced inside the
    compiled step); the cross-process path moves one fused buffer."""
    tensors = [as_tensor(t) for t in tensor_list]
    if not tensors:
        return tensor_list
    dt = tensors[0]._data.dtype
    for t in tensors[1:]:
        if t._data.dtype != dt:
            raise ValueError(
                "all_reduce_coalesced needs one dtype per call; got "
                f"{dt} and {t._data.dtype} (bucket per dtype)")
    if not _multiproc():
        return tensor_list
    # one fused 1-D payload through the ordinary all_reduce (its
    # multi-process branch; the single-controller rank-axis heuristic
    # never sees this path), then scatter the reduced slices back
    flat = Tensor(jnp.concatenate([t._data.ravel() for t in tensors])) \
        if len(tensors) > 1 else Tensor(tensors[0]._data.ravel())
    all_reduce(flat, op, group)
    off = 0
    for t in tensors:
        n = int(t._data.size)
        t._data = flat._data[off:off + n].reshape(t._data.shape)
        off += n
    return tensor_list


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv across processes requires the multi-host "
        "backend; within one host use pipeline_parallel (ppermute)")


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv across processes requires the multi-host "
        "backend; within one host use pipeline_parallel (ppermute)")


@_instrumented("wait", count_bytes=False)
def wait(tensor, group=None, use_calc_stream=True):
    jax.block_until_ready(as_tensor(tensor)._data)


def split(x, num_or_sections, axis=0):
    from ..ops.manipulation import split as _split
    return _split(x, num_or_sections, axis)
