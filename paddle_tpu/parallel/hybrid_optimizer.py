"""HybridParallelOptimizer + DP meta-optimizer behaviors.

Parity: `python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:172` — wraps the user optimizer; in the
reference it fuses DP grad allreduce, sharding and a cross-axis global-norm
clip. TPU-native: grad reduction happens inside the compiled step (GSPMD /
shard_map transpose), so this wrapper mostly delegates; it keeps the fleet
API and carries the sharding (ZeRO) configuration into the compiled
trainers.

Consumed DistributedStrategy knobs (VERDICT r4 #6 — every declared field
either acts or raises):

* ``gradient_merge`` (`meta_optimizers/gradient_merge_optimizer.py:1`):
  k-step gradient accumulation — ``step()`` is a no-op (grads keep
  accumulating across ``backward()`` calls, paddle's default when
  ``clear_grad`` isn't called) until the k-th call, which scales by 1/k
  (``avg=True``) and runs the inner optimizer.
* ``localsgd`` (`meta_optimizers/localsgd_optimizer.py:1`): every rank
  steps locally; every ``k_steps`` the parameters are averaged across
  the data-parallel group with an all_reduce.
* ``lamb`` (`meta_optimizers/lamb_optimizer.py`): swaps a Momentum/SGD
  inner optimizer for Lamb at the same learning rate.
* ``dgc`` / ``lars``: raise — DGC is CUDA-comm-specific top-k gradient
  compression (a named non-goal); LARS has no TPU engine yet and
  silently ignoring it would change training semantics.
"""
from __future__ import annotations


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._hcg = hcg
        self._strategy = strategy
        if strategy is not None and getattr(strategy, "dgc", False):
            raise NotImplementedError(
                "DistributedStrategy.dgc (deep gradient compression) is "
                "CUDA-communication-specific and not supported on the "
                "TPU engine")
        if strategy is not None and getattr(strategy, "lars", False):
            raise NotImplementedError(
                "DistributedStrategy.lars is not implemented on the TPU "
                "engine; use lamb=True (layer-adaptive rates) instead")
        if strategy is not None and getattr(strategy, "lamb", False):
            from ..optimizer import Lamb, Momentum, SGD
            if isinstance(optimizer, (Momentum, SGD)):
                optimizer = Lamb(
                    learning_rate=optimizer._learning_rate,
                    parameters=optimizer._parameter_list,
                    grad_clip=optimizer._grad_clip)
        self._inner_opt = optimizer
        if strategy is not None and getattr(strategy, "sharding", False):
            optimizer._zero_stage = strategy.sharding_configs.get("stage", 1)
        gm = strategy is not None and getattr(strategy, "gradient_merge",
                                              False)
        cfg = strategy.gradient_merge_configs if gm else {}
        self._gm_k = int(cfg.get("k_steps", 1)) if gm else 1
        self._gm_avg = bool(cfg.get("avg", True))
        ls = strategy is not None and getattr(strategy, "localsgd", False)
        ls_cfg = getattr(strategy, "localsgd_configs", {}) if ls else {}
        self._localsgd_k = int(ls_cfg.get("k_steps", 1)) if ls else 0
        self._call_count = 0
        self._opt_steps = 0

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def _scale_grads(self, scale):
        params = self._inner_opt._parameter_list or []
        for p in params:
            if p.grad is not None:
                p.grad._data = p.grad._data * scale

    def _sync_params(self):
        """LocalSGD param averaging over the dp group."""
        from . import collective
        import paddle_tpu.parallel as dist
        world = getattr(dist, "get_world_size", lambda: 1)()
        if world <= 1:
            return
        for p in self._inner_opt._parameter_list or []:
            collective.all_reduce(p)
            p._data = p._data / world

    def step(self):
        self._call_count += 1
        if self._gm_k > 1:
            if self._call_count % self._gm_k != 0:
                # accumulation phase: keep grads, no update (the
                # reference's GradientMergeOptimizer zero-cond branch)
                return
            if self._gm_avg:
                self._scale_grads(1.0 / self._gm_k)
        self._inner_opt.step()
        self._opt_steps += 1
        if self._gm_k > 1:
            # post-update the merged grads are consumed
            self._inner_opt.clear_grad()
        if self._localsgd_k and self._opt_steps % self._localsgd_k == 0:
            self._sync_params()

    def minimize(self, loss, *a, **k):
        if self._gm_k > 1 or self._localsgd_k:
            if loss._grad_node is not None or not loss.stop_gradient:
                loss.backward()
            self.step()
            return None, None
        return self._inner_opt.minimize(loss, *a, **k)

    def clear_grad(self, *a, **k):
        if self._gm_k > 1 and self._call_count % self._gm_k != 0:
            # inside an accumulation window the merged grads must
            # survive the user's train-loop clear_grad()
            return
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
