"""fleet — the distributed facade.

Parity: `python/paddle/distributed/fleet/fleet.py:107` (`Fleet`: init,
distributed_model, distributed_optimizer, worker/server lifecycle) +
role_maker env parsing (`fleet/base/role_maker.py`).

TPU-native: `fleet.init` builds the hybrid topology/mesh; `distributed_model`
wraps per the parallel mode (DataParallel now; PipelineParallel in
parallel/pipeline.py); `distributed_optimizer` returns a
HybridParallelOptimizer that folds dp-grad reduction/sharding into the
compiled step. PS mode (init_server/init_worker) binds to the native PS
engine (paddle_tpu/ps).
"""
from __future__ import annotations

from . import env as dist_env
from .strategy import DistributedStrategy
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       set_hybrid_communicate_group)
from .data_parallel import DataParallel


class _RoleMakerStub:
    def __init__(self, is_collective=True):
        self._is_collective = is_collective


class PaddleCloudRoleMaker:
    """`fleet/base/role_maker.py:526` parity: derive this process's PS
    role from the PaddleCloud env contract (TRAINING_ROLE,
    PADDLE_PSERVERS_IP_PORT_LIST, PADDLE_TRAINER_ENDPOINTS,
    PADDLE_TRAINER_ID / POD_IP:PADDLE_PORT)."""

    def __init__(self, is_collective=False, **kwargs):
        import os
        self._is_collective = is_collective
        self._kwargs = kwargs
        self._role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._servers = [e for e in os.environ.get(
            "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e]
        self._workers = [e for e in os.environ.get(
            "PADDLE_TRAINER_ENDPOINTS", "").split(",") if e]
        if self._role == "PSERVER":
            me = (os.environ.get("POD_IP", "") + ":"
                  + os.environ.get("PADDLE_PORT", ""))
            self._cur = self._servers.index(me) if me in self._servers \
                else int(os.environ.get("PADDLE_TRAINER_ID", 0))
        else:
            self._cur = int(os.environ.get("PADDLE_TRAINER_ID", 0))

    def _is_worker(self):
        return self._role == "TRAINER"

    def _is_server(self):
        return self._role == "PSERVER"

    is_worker = _is_worker
    is_server = _is_server

    def is_first_worker(self):
        return self._is_worker() and self._cur == 0

    def worker_index(self):
        return self._cur if self._is_worker() else -1

    def server_index(self):
        return self._cur if self._is_server() else -1

    def worker_num(self):
        return max(len(self._workers),
                   int(__import__("os").environ.get(
                       "PADDLE_TRAINERS_NUM", 1)))

    def server_num(self):
        return len(self._servers)

    def get_trainer_endpoints(self):
        return list(self._workers)

    def get_pserver_endpoints(self):
        return list(self._servers)


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """`role_maker.py:1112` parity: explicit role wiring instead of env
    parsing — kwargs: current_id, role ('worker'/'server'),
    worker_num, server_endpoints."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
        self._kwargs = kwargs
        role = kwargs.get("role", "worker")
        self._role = ("PSERVER" if str(role).lower() in
                      ("server", "pserver", "2") else "TRAINER")
        self._cur = int(kwargs.get("current_id", 0))
        self._servers = list(kwargs.get("server_endpoints", []))
        n = int(kwargs.get("worker_num", 1))
        self._workers = list(kwargs.get("worker_endpoints",
                                        [""] * n if n else []))

    def worker_num(self):
        # explicit wiring must NOT be overridden by leaked launcher env
        # (PaddleCloudRoleMaker.worker_num consults PADDLE_TRAINERS_NUM)
        return len(self._workers)


class Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._is_collective = True
        self._role_maker = None
        self._user_defined_optimizer = None

    # ------------------------------------------------------------- init
    def init(self, role_maker=None, is_collective=False, strategy=None,
             log_level="INFO"):
        self._is_collective = is_collective or role_maker is None
        self._role_maker = role_maker or _RoleMakerStub(is_collective)
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        world = dist_env.get_world_size()
        dp = hc.get("dp_degree", 1)
        mp = hc.get("mp_degree", 1)
        pp = hc.get("pp_degree", 1)
        sh = hc.get("sharding_degree", 1)
        if dp * mp * pp * sh < world and dp == 1 and mp == 1 and pp == 1:
            dp = world // (mp * pp * sh)
            hc["dp_degree"] = dp
        topo = CommunicateTopology(dims=(dp, pp, sh, mp))
        self._hcg = HybridCommunicateGroup(topo)
        set_hybrid_communicate_group(self._hcg)
        dist_env.init_parallel_env()
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def worker_num(self):
        return dist_env.get_world_size()

    def worker_index(self):
        return dist_env.get_rank()

    def is_first_worker(self):
        return dist_env.get_rank() == 0

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def barrier_worker(self):
        dist_env.barrier()

    # ------------------------------------------------------ distributed
    def distributed_model(self, model):
        if self._hcg is None:
            self.init(is_collective=True)
        mode = self._hcg.get_parallel_mode()
        if mode == "data_parallel":
            return DataParallel(model)
        if self._hcg.get_pipe_parallel_world_size() > 1:
            from .pipeline import PipelineParallel
            return PipelineParallel(model, self._hcg, self._strategy)
        from .mp_layers import TensorParallel
        return TensorParallel(model, self._hcg, self._strategy)

    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        self._user_defined_optimizer = optimizer
        from .hybrid_optimizer import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)

    # --------------------------------------------------------------- PS
    def init_worker(self, scopes=None):
        from ..ps.runtime import get_ps_runtime
        get_ps_runtime().init_worker()

    def init_server(self, *args, **kwargs):
        from ..ps.runtime import get_ps_runtime
        get_ps_runtime().init_server()

    def run_server(self):
        from ..ps.runtime import get_ps_runtime
        get_ps_runtime().run_server()

    def stop_worker(self):
        from ..ps.runtime import get_ps_runtime
        get_ps_runtime().stop_worker()

    def save_persistables(self, executor=None, dirname=None, main_program=None,
                          mode=0):
        from ..ps.runtime import get_ps_runtime
        get_ps_runtime().save_persistables(dirname)

    # ------------------------------------------------------------- misc
    def all_reduce(self, input, mode="sum"):
        from .collective import all_reduce as ar
        return ar(input)


fleet = Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer


# fleet.utils namespace (`distributed/fleet/utils/`)
from . import fleet_utils as utils  # noqa: E402,F401

barrier_worker = fleet.barrier_worker
