"""paddle.signal parity: stft / istft.

Reference: `python/paddle/signal.py` (`stft` :134, `istft` :301) over the
`frame`/`overlap_add` + fft kernels (`paddle/phi/kernels/stft_kernel.h`).
TPU-native: framing is a gather, the FFT is XLA's, overlap-add is a
scatter-add — all differentiable and jit-compatible.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core import dispatch
from .ops._helpers import as_tensor


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames along the time axis. axis=-1 (default):
    [..., T] -> [..., frame_length, n_frames]; axis=0:
    [T, ...] -> [n_frames, frame_length, ...] (the reference's two
    layouts)."""
    x = as_tensor(x)

    def _fn(a):
        if axis == 0:
            a = jnp.moveaxis(a, 0, -1)                    # time last
        T = a.shape[-1]
        if T < frame_length:
            raise ValueError(
                f"frame: signal length {T} < frame_length "
                f"{frame_length} (the reference errors here too)")
        n = 1 + (T - frame_length) // hop_length
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(frame_length)[None, :])       # [n, L]
        f = a[..., idx]                                   # [..., n, L]
        if axis == 0:
            # [..., n, L] -> [n, L, ...]
            f = jnp.moveaxis(f, (-2, -1), (0, 1))
            return f
        return jnp.swapaxes(f, -1, -2)                    # [..., L, n]
    return dispatch.apply("frame", _fn, (x,))


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of `frame`: [..., L, n_frames] -> [..., T] via
    scatter-add with hop_length."""
    x = as_tensor(x)

    def _fn(a):
        if axis == 0:
            # [n, L, ...] -> [..., L, n]
            a = jnp.moveaxis(a, (0, 1), (-1, -2))
        L, n = a.shape[-2], a.shape[-1]
        T = L + hop_length * (n - 1)
        frames = jnp.swapaxes(a, -1, -2)                  # [..., n, L]
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(L)[None, :])                  # [n, L]
        out = jnp.zeros(a.shape[:-2] + (T,), a.dtype)
        out = out.at[..., idx].add(frames)
        if axis == 0:
            out = jnp.moveaxis(out, -1, 0)
        return out
    return dispatch.apply("overlap_add", _fn, (x,))


def _resolve_window(window, win_length, dtype=jnp.float32):
    if window is None:
        return jnp.ones((win_length,), dtype)
    w = np.asarray(getattr(window, "_data", window))
    if w.shape[-1] != win_length:
        raise ValueError(
            f"window length {w.shape[-1]} != win_length {win_length}")
    return jnp.asarray(w, dtype)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False,
         onesided=True, name=None):
    """x [B, T] (or [T]) real (complex supported when onesided=False).
    Returns complex [B, n_fft//2+1 (or n_fft), n_frames] — the
    reference's layout."""
    x = as_tensor(x)
    hop = hop_length if hop_length is not None else n_fft // 4
    wl = win_length if win_length is not None else n_fft
    win = _resolve_window(window, wl)
    if onesided and jnp.iscomplexobj(x._data):
        raise ValueError(
            "stft: onesided=True is undefined for complex input "
            "(the reference raises too); pass onesided=False")

    def _fn(a):
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None]
        w = win
        if wl < n_fft:
            lp = (n_fft - wl) // 2
            w = jnp.pad(w, (lp, n_fft - wl - lp))
        if center:
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(n_fft // 2,) * 2],
                        mode=pad_mode)
        T = a.shape[-1]
        if T < n_fft:
            raise ValueError(
                f"stft: signal length {T} (after centering) < n_fft "
                f"{n_fft}")
        n = 1 + (T - n_fft) // hop
        idx = (jnp.arange(n)[:, None] * hop
               + jnp.arange(n_fft)[None, :])
        frames = a[..., idx] * w[None, None, :]           # [B, n, n_fft]
        if onesided:
            spec = jnp.fft.rfft(frames, axis=-1)
        else:
            spec = jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        out = jnp.swapaxes(spec, -1, -2)                  # [B, bins, n]
        return out[0] if squeeze else out
    return dispatch.apply("stft", _fn, (x,))


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse stft with window-envelope-normalized overlap-add
    (the reference's NOLA reconstruction). A `length` beyond the
    reconstructable span is zero-padded (reference contract: the
    caller asked for that many samples, the frames simply end
    earlier)."""
    x = as_tensor(x)
    if return_complex and onesided:
        raise ValueError(
            "istft: return_complex=True requires onesided=False — a "
            "onesided spectrum is irfft'd to a REAL signal, so a "
            "complex return is undefined (the reference raises too)")
    hop = hop_length if hop_length is not None else n_fft // 4
    wl = win_length if win_length is not None else n_fft
    win = _resolve_window(window, wl)

    def _fn(spec):
        squeeze = spec.ndim == 2
        if squeeze:
            spec = spec[None]
        w = win
        if wl < n_fft:
            lp = (n_fft - wl) // 2
            w = jnp.pad(w, (lp, n_fft - wl - lp))
        sp = jnp.swapaxes(spec, -1, -2)                   # [B, n, bins]
        if normalized:
            sp = sp * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(sp, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(sp, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * w[None, None, :]
        n = frames.shape[-2]
        T = n_fft + hop * (n - 1)
        idx = (jnp.arange(n)[:, None] * hop
               + jnp.arange(n_fft)[None, :])
        out = jnp.zeros(frames.shape[:-2] + (T,), frames.dtype)
        out = out.at[..., idx].add(frames)
        env = jnp.zeros((T,), jnp.float32).at[
            idx.reshape(-1)].add(jnp.tile(w * w, (n,)))
        out = out / jnp.maximum(env, 1e-10)
        if center:
            out = out[..., n_fft // 2: T - n_fft // 2]
        if length is not None:
            have = out.shape[-1]
            if length > have:
                out = jnp.pad(out, [(0, 0)] * (out.ndim - 1)
                              + [(0, length - have)])
            else:
                out = out[..., :length]
        return out[0] if squeeze else out
    return dispatch.apply("istft", _fn, (x,))
