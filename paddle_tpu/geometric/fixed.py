"""geometric.fixed — jit-safe fixed-shape twins of the eager graph ops.

The eager API in `geometric/__init__` sizes its outputs from host reads
(`segment_ids.max()+1`, ragged reindex) — fine for eager parity, fatal
inside jit (every new graph would recompile). These twins take every
output size statically and carry validity MASKS instead of ragged
shapes, which is the contract the GraphEngine's `[B, fanout]` bundles
feed: masked slots are routed to a dropped out-of-range segment, and
empty segments produce 0 (paddle's vacant-row semantics, matching the
eager fixes).

Everything here is pure jax.numpy on raw arrays (no Tensor wrapper, no
host calls) so the SAGE stack can close over it inside ONE compiled
step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _masked_ids(segment_ids, num_segments, mask):
    """Masked entries get segment id `num_segments` — XLA drops
    out-of-range scatter indices, so they simply never land."""
    if mask is None:
        return segment_ids
    return jnp.where(mask, segment_ids, num_segments)


def masked_segment_sum(data, segment_ids, num_segments, mask=None):
    ids = _masked_ids(segment_ids, num_segments, mask)
    return jax.ops.segment_sum(data, ids, num_segments=num_segments)


def masked_segment_mean(data, segment_ids, num_segments, mask=None):
    """Mean over the VALID members of each segment; segments with no
    valid member are 0."""
    ids = _masked_ids(segment_ids, num_segments, mask)
    sums = jax.ops.segment_sum(data, ids, num_segments=num_segments)
    counts = jax.ops.segment_sum(
        jnp.ones((data.shape[0],), jnp.int32), ids,
        num_segments=num_segments)
    return sums / jnp.maximum(counts, 1).astype(sums.dtype).reshape(
        (-1,) + (1,) * (data.ndim - 1))


def masked_segment_max(data, segment_ids, num_segments, mask=None):
    """Max over the valid members; empty segments are 0, not -inf."""
    ids = _masked_ids(segment_ids, num_segments, mask)
    res = jax.ops.segment_max(data, ids, num_segments=num_segments)
    counts = jax.ops.segment_sum(
        jnp.ones((data.shape[0],), jnp.int32), ids,
        num_segments=num_segments)
    occupied = (counts > 0).reshape((-1,) + (1,) * (data.ndim - 1))
    return jnp.where(occupied, res, jnp.zeros((), res.dtype))


def unique_fixed(keys, size, fill_value=0):
    """Jit-safe reindex twin: `(uniq [size], inv [len(keys)])` with the
    output size STATIC (`jnp.unique(size=...)`); surplus uniq slots
    carry `fill_value`. `inv` maps every key to its compact local id —
    the same contract as `reindex_graph`, without the ragged output."""
    uniq, inv = jnp.unique(keys, return_inverse=True, size=size,
                           fill_value=fill_value)
    return uniq, inv.reshape(keys.shape)


def mask_from_counts(counts, fanout):
    """[N] valid-neighbor counts -> [N, fanout] bool slot mask (the
    fixed-shape sampler's mask contract: slot j valid iff j < count)."""
    return jnp.arange(fanout)[None, :] < counts[:, None]


def mean_aggregate(neigh_feats, mask):
    """[N, f, d] neighbor features + [N, f] mask -> [N, d] mean over
    valid slots (0 for isolated nodes) — the SAGE mean aggregator,
    phrased as a masked segment reduction over the flattened edges."""
    n, f, d = neigh_feats.shape
    seg = jnp.repeat(jnp.arange(n), f)
    return masked_segment_mean(neigh_feats.reshape(n * f, d), seg, n,
                               mask=mask.reshape(n * f))


def max_aggregate(neigh_feats, mask):
    """[N, f, d] + [N, f] -> [N, d] max over valid slots (0 when
    none) — the SAGE max-pool aggregator."""
    n, f, d = neigh_feats.shape
    seg = jnp.repeat(jnp.arange(n), f)
    return masked_segment_max(neigh_feats.reshape(n * f, d), seg, n,
                              mask=mask.reshape(n * f))
