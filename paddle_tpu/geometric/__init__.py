"""paddle_tpu.geometric — graph learning ops.

Parity: `python/paddle/geometric/` (segment_sum/mean/max/min,
send_u_recv message passing) over XLA segment ops — the compute core the
reference's GPU graph engine feeds (`paddle/phi/kernels/
segment_pool_kernel.h`, `graph_send_recv_kernel.h`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor
from ..ops._helpers import as_tensor


def _segment(name, jfn, data, segment_ids, fill_empty_zero=False):
    data, segment_ids = as_tensor(data), as_tensor(segment_ids)
    n_seg = int(np.asarray(segment_ids.numpy()).max()) + 1 \
        if segment_ids.size else 0

    def _fn(d, s):
        res = jfn(d, s, num_segments=n_seg)
        if fill_empty_zero:
            # paddle's segment_pool writes 0 for segments with no
            # members; jax's segment_max/min fill with -inf/+inf
            counts = jax.ops.segment_sum(
                jnp.ones((d.shape[0],), jnp.int32), s,
                num_segments=n_seg)
            occupied = (counts > 0).reshape(
                (-1,) + (1,) * (d.ndim - 1))
            res = jnp.where(occupied, res, jnp.zeros((), res.dtype))
        return res
    return dispatch.apply(name, _fn, (data, segment_ids))


def segment_sum(data, segment_ids, name=None):
    return _segment("segment_sum", jax.ops.segment_sum, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    data_t, seg_t = as_tensor(data), as_tensor(segment_ids)
    n_seg = int(np.asarray(seg_t.numpy()).max()) + 1 if seg_t.size else 0

    def _fn(d, s):
        sums = jax.ops.segment_sum(d, s, num_segments=n_seg)
        counts = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), s,
                                     num_segments=n_seg)
        return sums / jnp.maximum(counts, 1.0).reshape(
            (-1,) + (1,) * (d.ndim - 1))
    return dispatch.apply("segment_mean", _fn, (data_t, seg_t))


def segment_max(data, segment_ids, name=None):
    return _segment("segment_max", jax.ops.segment_max, data,
                    segment_ids, fill_empty_zero=True)


def segment_min(data, segment_ids, name=None):
    return _segment("segment_min", jax.ops.segment_min, data,
                    segment_ids, fill_empty_zero=True)


def _n_out(dst_index, out_size):
    """Output-row count for message passing: `out_size` wins; otherwise
    max(dst)+1 — and 0 for an empty edge list (the old host `max()`
    crashed on zero-size input). An `out_size` SMALLER than max(dst)+1
    drops the out-of-range messages (XLA scatter semantics, matching
    the reference kernel's bounds check)."""
    if out_size is not None:
        n = int(out_size)
        if n < 0:
            raise ValueError(f"out_size={n} must be >= 0")
        return n
    return int(np.asarray(dst_index.numpy()).max()) + 1 \
        if dst_index.size else 0


def _seg_reduce(msgs, dst, n_out, reduce_op):
    """Segment-reduce edge messages with paddle's vacant-row semantics:
    rows receiving no message are 0 (incl. max/min, where jax fills
    with -+inf)."""
    if reduce_op == "sum":
        return jax.ops.segment_sum(msgs, dst, num_segments=n_out)
    counts = jax.ops.segment_sum(
        jnp.ones((msgs.shape[0],), jnp.int32), dst,
        num_segments=n_out)
    shape = (-1,) + (1,) * (msgs.ndim - 1)
    if reduce_op == "mean":
        sums = jax.ops.segment_sum(msgs, dst, num_segments=n_out)
        return sums / jnp.maximum(counts, 1).astype(
            sums.dtype).reshape(shape)
    red = {"max": jax.ops.segment_max,
           "min": jax.ops.segment_min}[reduce_op]
    res = red(msgs, dst, num_segments=n_out)
    return jnp.where((counts > 0).reshape(shape), res,
                     jnp.zeros((), res.dtype))


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Message passing: gather x[src] and segment-reduce onto dst
    (graph_send_recv parity)."""
    x, src_index, dst_index = (as_tensor(x), as_tensor(src_index),
                               as_tensor(dst_index))
    n_out = _n_out(dst_index, out_size)

    def _fn(xa, src, dst):
        return _seg_reduce(jnp.take(xa, src, axis=0), dst, n_out,
                           reduce_op)
    return dispatch.apply("send_u_recv", _fn, (x, src_index, dst_index))


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Node+edge message passing (graph_send_ue_recv parity)."""
    x, y = as_tensor(x), as_tensor(y)
    src_index, dst_index = as_tensor(src_index), as_tensor(dst_index)
    n_out = _n_out(dst_index, out_size)

    def _fn(xa, ya, src, dst):
        msgs = jnp.take(xa, src, axis=0)
        if message_op == "add":
            msgs = msgs + ya
        elif message_op == "mul":
            msgs = msgs * ya
        if reduce_op in ("max", "min", "mean"):
            return _seg_reduce(msgs, dst, n_out, reduce_op)
        return jax.ops.segment_sum(msgs, dst, num_segments=n_out)
    return dispatch.apply("send_ue_recv", _fn,
                          (x, y, src_index, dst_index))


def send_uv(x, y, src_index, dst_index, compute_type="add", name=None):
    """`graph_send_uv_kernel.h` — per-edge message from both endpoints:
    out[e] = x[src[e]] OP y[dst[e]]."""
    x, y = as_tensor(x), as_tensor(y)
    src_index, dst_index = as_tensor(src_index), as_tensor(dst_index)
    ops_ = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
            "div": jnp.divide}
    op = ops_[compute_type]

    def _fn(xa, ya, src, dst):
        return op(jnp.take(xa, src, axis=0), jnp.take(ya, dst, axis=0))
    return dispatch.apply("graph_send_uv", _fn,
                          (x, y, src_index, dst_index))


def reindex_graph(x, neighbors, count, value_buffer=None,
                  index_buffer=None, name=None):
    """`graph_reindex_kernel.h` — compact node ids: unique over
    (x ++ neighbors), remap neighbors to local ids (host-side like the
    reference CPU kernel; ragged output sizes)."""
    xs = np.asarray(as_tensor(x).numpy()).reshape(-1)
    nb = np.asarray(as_tensor(neighbors).numpy()).reshape(-1)
    ct = np.asarray(as_tensor(count).numpy()).reshape(-1)
    keep = {}
    for v in xs.tolist():
        if v not in keep:
            keep[v] = len(keep)
    for v in nb.tolist():
        if v not in keep:
            keep[v] = len(keep)
    reindex_src = np.asarray([keep[v] for v in nb], np.int64)
    # dst of edge j is the center node whose count covers j
    reindex_dst = np.repeat(np.arange(len(ct)), ct).astype(np.int64)
    out_dtype = xs.dtype if xs.size else \
        (nb.dtype if nb.size else np.int64)
    out_nodes = np.asarray(list(keep.keys()), out_dtype)
    from ..core.tensor import Tensor as _T
    return (_T(jnp.asarray(reindex_src)), _T(jnp.asarray(reindex_dst)),
            _T(jnp.asarray(out_nodes)))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None, rng=None):
    """`graph_sample_neighbors_kernel.h` — uniform neighbor sampling
    from CSC (row, colptr) for the given nodes (host-side, like the
    reference's CPU path; the PS ShardedGraphTable covers the
    distributed case). `rng` injects a seeded `np.random.Generator`
    (or an int seed) for reproducible draws."""
    rows = np.asarray(as_tensor(row).numpy()).reshape(-1)
    cp = np.asarray(as_tensor(colptr).numpy()).reshape(-1)
    nodes = np.asarray(as_tensor(input_nodes).numpy()).reshape(-1)
    if not isinstance(rng, np.random.Generator):
        rng = np.random.default_rng(rng)
    out, cnt, oeids = [], [], []
    ei = np.asarray(as_tensor(eids).numpy()).reshape(-1) \
        if eids is not None else None
    for n in nodes.tolist():
        beg, end = int(cp[n]), int(cp[n + 1])
        neigh = rows[beg:end]
        idx = np.arange(beg, end)
        if 0 <= sample_size < len(neigh):
            pick = rng.choice(len(neigh), sample_size, replace=False)
            neigh, idx = neigh[pick], idx[pick]
        out.append(neigh)
        cnt.append(len(neigh))
        if ei is not None:
            oeids.append(ei[idx])
    from ..core.tensor import Tensor as _T
    res = (_T(jnp.asarray(np.concatenate(out) if out else
                          np.zeros(0, rows.dtype))),
           _T(jnp.asarray(np.asarray(cnt, np.int32))))
    if return_eids and ei is not None:
        return res + (_T(jnp.asarray(
            np.concatenate(oeids) if oeids else
            np.zeros(0, ei.dtype))),)
    return res
