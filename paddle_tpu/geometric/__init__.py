"""paddle_tpu.geometric — graph learning ops.

Parity: `python/paddle/geometric/` (segment_sum/mean/max/min,
send_u_recv message passing) over XLA segment ops — the compute core the
reference's GPU graph engine feeds (`paddle/phi/kernels/
segment_pool_kernel.h`, `graph_send_recv_kernel.h`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor
from ..ops._helpers import as_tensor


def _segment(name, jfn, data, segment_ids):
    data, segment_ids = as_tensor(data), as_tensor(segment_ids)
    n_seg = int(np.asarray(segment_ids.numpy()).max()) + 1 \
        if segment_ids.size else 0

    def _fn(d, s):
        return jfn(d, s, num_segments=n_seg)
    return dispatch.apply(name, _fn, (data, segment_ids))


def segment_sum(data, segment_ids, name=None):
    return _segment("segment_sum", jax.ops.segment_sum, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    data_t, seg_t = as_tensor(data), as_tensor(segment_ids)
    n_seg = int(np.asarray(seg_t.numpy()).max()) + 1 if seg_t.size else 0

    def _fn(d, s):
        sums = jax.ops.segment_sum(d, s, num_segments=n_seg)
        counts = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), s,
                                     num_segments=n_seg)
        return sums / jnp.maximum(counts, 1.0).reshape(
            (-1,) + (1,) * (d.ndim - 1))
    return dispatch.apply("segment_mean", _fn, (data_t, seg_t))


def segment_max(data, segment_ids, name=None):
    return _segment("segment_max", jax.ops.segment_max, data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _segment("segment_min", jax.ops.segment_min, data, segment_ids)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Message passing: gather x[src] and segment-reduce onto dst
    (graph_send_recv parity)."""
    x, src_index, dst_index = (as_tensor(x), as_tensor(src_index),
                               as_tensor(dst_index))
    n_out = int(out_size) if out_size is not None else \
        int(np.asarray(dst_index.numpy()).max()) + 1
    red = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}.get(reduce_op)

    def _fn(xa, src, dst):
        msgs = jnp.take(xa, src, axis=0)
        if reduce_op == "mean":
            sums = jax.ops.segment_sum(msgs, dst, num_segments=n_out)
            counts = jax.ops.segment_sum(
                jnp.ones((msgs.shape[0],), xa.dtype), dst,
                num_segments=n_out)
            return sums / jnp.maximum(counts, 1.0).reshape(
                (-1,) + (1,) * (xa.ndim - 1))
        return red(msgs, dst, num_segments=n_out)
    return dispatch.apply("send_u_recv", _fn, (x, src_index, dst_index))


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Node+edge message passing (graph_send_ue_recv parity)."""
    x, y = as_tensor(x), as_tensor(y)
    src_index, dst_index = as_tensor(src_index), as_tensor(dst_index)
    n_out = int(out_size) if out_size is not None else \
        int(np.asarray(dst_index.numpy()).max()) + 1

    def _fn(xa, ya, src, dst):
        msgs = jnp.take(xa, src, axis=0)
        if message_op == "add":
            msgs = msgs + ya
        elif message_op == "mul":
            msgs = msgs * ya
        if reduce_op == "sum":
            return jax.ops.segment_sum(msgs, dst, num_segments=n_out)
        if reduce_op == "max":
            return jax.ops.segment_max(msgs, dst, num_segments=n_out)
        if reduce_op == "min":
            return jax.ops.segment_min(msgs, dst, num_segments=n_out)
        sums = jax.ops.segment_sum(msgs, dst, num_segments=n_out)
        counts = jax.ops.segment_sum(
            jnp.ones((msgs.shape[0],), msgs.dtype), dst,
            num_segments=n_out)
        return sums / jnp.maximum(counts, 1.0).reshape(
            (-1,) + (1,) * (msgs.ndim - 1))
    return dispatch.apply("send_ue_recv", _fn,
                          (x, y, src_index, dst_index))


def send_uv(x, y, src_index, dst_index, compute_type="add", name=None):
    """`graph_send_uv_kernel.h` — per-edge message from both endpoints:
    out[e] = x[src[e]] OP y[dst[e]]."""
    x, y = as_tensor(x), as_tensor(y)
    src_index, dst_index = as_tensor(src_index), as_tensor(dst_index)
    ops_ = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
            "div": jnp.divide}
    op = ops_[compute_type]

    def _fn(xa, ya, src, dst):
        return op(jnp.take(xa, src, axis=0), jnp.take(ya, dst, axis=0))
    return dispatch.apply("graph_send_uv", _fn,
                          (x, y, src_index, dst_index))


def reindex_graph(x, neighbors, count, value_buffer=None,
                  index_buffer=None, name=None):
    """`graph_reindex_kernel.h` — compact node ids: unique over
    (x ++ neighbors), remap neighbors to local ids (host-side like the
    reference CPU kernel; ragged output sizes)."""
    xs = np.asarray(as_tensor(x).numpy()).reshape(-1)
    nb = np.asarray(as_tensor(neighbors).numpy()).reshape(-1)
    ct = np.asarray(as_tensor(count).numpy()).reshape(-1)
    keep = {}
    for v in xs.tolist():
        if v not in keep:
            keep[v] = len(keep)
    for v in nb.tolist():
        if v not in keep:
            keep[v] = len(keep)
    reindex_src = np.asarray([keep[v] for v in nb], np.int64)
    # dst of edge j is the center node whose count covers j
    reindex_dst = np.repeat(np.arange(len(ct)), ct).astype(np.int64)
    out_nodes = np.asarray(list(keep.keys()),
                           xs.dtype if xs.size else np.int64)
    from ..core.tensor import Tensor as _T
    return (_T(jnp.asarray(reindex_src)), _T(jnp.asarray(reindex_dst)),
            _T(jnp.asarray(out_nodes)))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """`graph_sample_neighbors_kernel.h` — uniform neighbor sampling
    from CSC (row, colptr) for the given nodes (host-side, like the
    reference's CPU path; the PS GraphTable covers the distributed
    case)."""
    rows = np.asarray(as_tensor(row).numpy()).reshape(-1)
    cp = np.asarray(as_tensor(colptr).numpy()).reshape(-1)
    nodes = np.asarray(as_tensor(input_nodes).numpy()).reshape(-1)
    rng = np.random.default_rng()
    out, cnt, oeids = [], [], []
    ei = np.asarray(as_tensor(eids).numpy()).reshape(-1) \
        if eids is not None else None
    for n in nodes.tolist():
        beg, end = int(cp[n]), int(cp[n + 1])
        neigh = rows[beg:end]
        idx = np.arange(beg, end)
        if 0 <= sample_size < len(neigh):
            pick = rng.choice(len(neigh), sample_size, replace=False)
            neigh, idx = neigh[pick], idx[pick]
        out.append(neigh)
        cnt.append(len(neigh))
        if ei is not None:
            oeids.append(ei[idx])
    from ..core.tensor import Tensor as _T
    res = (_T(jnp.asarray(np.concatenate(out) if out else
                          np.zeros(0, rows.dtype))),
           _T(jnp.asarray(np.asarray(cnt, np.int32))))
    if return_eids and ei is not None:
        return res + (_T(jnp.asarray(np.concatenate(oeids))),)
    return res
