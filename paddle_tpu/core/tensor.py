"""The user-facing Tensor: a Paddle-compatible facade over `jax.Array`.

Reference parity: `phi::DenseTensor` (`paddle/phi/core/dense_tensor.h:37`) +
the eager Tensor bound in pybind (`paddle/fluid/pybind/eager.cc`,
`eager_method.cc`) with its autograd meta (`eager/autograd_meta.h:61`) and the
Python-side method patches (`python/paddle/fluid/dygraph/math_op_patch.py`,
`varbase_patch_methods.py:206 backward`).

Storage is an on-device `jax.Array`; XLA owns device memory, so the
reference's allocator stack (`paddle/fluid/memory/`) maps to jax's PJRT
allocator + `device_put`.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from . import place as place_mod
from . import autograd


class Tensor:
    __array_priority__ = 100  # win over numpy in mixed expressions

    __slots__ = (
        "_data", "stop_gradient", "_grad", "_grad_node", "_out_slot",
        "name", "persistable", "_grad_hooks", "trainable", "dist_spec",
        "_layout",
    )

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 name=None):
        dt = dtype_mod.convert_dtype(dtype)
        if isinstance(data, Tensor):
            arr = data._data
            if dt is not None and arr.dtype != dt:
                arr = arr.astype(dt)
        elif isinstance(data, jax.Array):
            arr = data if dt is None or data.dtype == dt else data.astype(dt)
        else:
            if isinstance(data, (bool, int, float)) and dt is None:
                if isinstance(data, bool):
                    dt = dtype_mod.bool_
                elif isinstance(data, int):
                    dt = dtype_mod.convert_dtype("int64")
                else:
                    dt = dtype_mod.get_default_dtype()
            npa = np.asarray(data)
            if dt is None and npa.dtype == np.float64:
                dt = dtype_mod.get_default_dtype()
            arr = jnp.asarray(npa, dtype=dt)
        if place is not None and not isinstance(place, place_mod.Place):
            s = str(place).lower()
            place = (place_mod.CPUPlace(0) if s.startswith("cpu")
                     else place_mod.TPUPlace(0))
        if isinstance(place, place_mod.Place):
            arr = jax.device_put(arr, place.jax_device())
        self._data = arr
        # physical-layout tag (core/layout.py): None = logical layout;
        # "NHWC" = logically-NCHW image stored channels-last. Inherited
        # when wrapping another Tensor (same backing array).
        self._layout = data._layout if isinstance(data, Tensor) else None
        self.stop_gradient = bool(stop_gradient)
        self._grad = None
        self._grad_node = None
        self._out_slot = 0
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient
        self._grad_hooks = []
        self.dist_spec = None  # jax PartitionSpec for SPMD placement

    # -- basic metadata -------------------------------------------------
    @property
    def shape(self):
        if self._layout is not None:       # physical NHWC -> logical NCHW
            from . import layout as layout_mod
            s = self._data.shape
            return [s[i] for i in layout_mod.TO_NCHW_PERM]
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    def dim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(self._data.size)

    def numel(self):
        return int(self._data.size)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def place(self):
        try:
            dev = list(self._data.devices())[0]
            plat = place_mod._platform_of(dev)
        except Exception:
            plat = "cpu"
        cls = place_mod.TPUPlace if plat == "tpu" else place_mod.CPUPlace
        return cls(getattr(dev, "id", 0))

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    @property
    def T(self):
        from .. import ops
        perm = list(range(self.ndim))[::-1]
        return ops.transpose(self, perm)

    def real(self, name=None):
        # a METHOD, matching the reference Tensor.real(name=None) —
        # property-style `.real` (torch-ism) would break ported calls
        from ..ops.extras2 import real as _real
        return _real(self)

    def imag(self, name=None):
        from ..ops.extras2 import imag as _imag
        return _imag(self)

    # -- host interop ---------------------------------------------------
    def numpy(self):
        a = np.asarray(self._data)
        if self._layout is not None:       # hand back the logical layout
            from . import layout as layout_mod
            a = a.transpose(*layout_mod.TO_NCHW_PERM)
        if a.base is not None or not a.flags.owndata:
            # Paddle's Tensor.numpy() returns a SNAPSHOT (a writable
            # copy), but np.asarray of a CPU jax buffer is a read-only
            # zero-copy VIEW of the live device buffer. Handing that
            # view out is a correctness trap with buffer donation: a
            # donated executable may reuse the buffer in place and
            # silently rewrite the caller's "snapshot". Fresh-compiled
            # executables dodge it (PJRT sees the external reference
            # and copies instead of donating), but executables
            # DESERIALIZED from the persistent compilation cache skip
            # that protection on this jax — observed as hapi-trained
            # weights "never changing" because the pre-training
            # snapshot aliased the donated param buffer. Copy-on-view
            # only: backends whose device_get already materializes an
            # owning host array pay nothing.
            a = a.copy()
        return a

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def element_size(self):
        return self._data.dtype.itemsize

    # -- autograd -------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        # run_backward converts logical-NCHW cotangents for tagged roots
        autograd.run_backward([self], [grad_tensor], retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        self._grad_hooks.append(hook)

        class _Removable:
            def remove(_self):
                try:
                    self._grad_hooks.remove(hook)
                except ValueError:
                    pass
        return _Removable()

    def detach(self):
        t = Tensor(self._data, stop_gradient=True)
        t._layout = self._layout
        t.name = self.name
        return t

    def clone(self):
        from .. import ops
        return ops.assign(self)

    def set_value(self, value):
        """In-place data rebind (paddle Tensor.set_value)."""
        if isinstance(value, Tensor):
            arr = value._data
        else:
            arr = jnp.asarray(np.asarray(value))
        if arr.dtype != self._data.dtype:
            arr = arr.astype(self._data.dtype)
        if tuple(arr.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch {arr.shape} vs {self._data.shape}"
            )
        self._data = arr

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    # -- conversion / movement -----------------------------------------
    def astype(self, dt):
        from .. import ops
        return ops.cast(self, dt)

    def cast(self, dt):
        return self.astype(dt)

    def to(self, *args, **kwargs):
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, (str, place_mod.Place)):
                dev = a if isinstance(a, place_mod.Place) else None
                if dev is None:
                    s = str(a)
                    dev = (place_mod.CPUPlace(0) if s.startswith("cpu")
                           else place_mod.TPUPlace(0))
                out = Tensor(jax.device_put(t._data, dev.jax_device()),
                             stop_gradient=t.stop_gradient)
                out._grad_node, out._out_slot = t._grad_node, t._out_slot
                out._layout = t._layout
                t = out
            else:
                t = t.astype(a)
        return t

    def cpu(self):
        return self.to("cpu")

    def cuda(self, *a, **k):
        return self.to("tpu")

    def tpu(self):
        return self.to("tpu")

    def pin_memory(self):
        return self

    # -- python protocol ------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a multi-element Tensor is ambiguous"
            )
        return bool(self.numpy().reshape(()))

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __index__(self):
        return int(self.item())

    def __repr__(self):
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place}, stop_gradient={self.stop_gradient},\n"
            f"       {np.array2string(self.numpy(), prefix='       ')})"
        )

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    # -- indexing -------------------------------------------------------
    def __getitem__(self, idx):
        from .. import ops
        return ops.getitem(self, idx)

    def __setitem__(self, idx, value):
        from .. import ops
        out = ops.setitem(self, idx, value)
        # Paddle mutates in place; we rebind this wrapper to the new value
        # (version-counter semantics: downstream autograd uses the new node).
        self._data = out._data
        self._grad_node = out._grad_node
        self._out_slot = out._out_slot
        self.stop_gradient = out.stop_gradient
        self._layout = out._layout  # setitem materialized a tagged self


def _make_binop(opname, reverse=False):
    def fn(self, other):
        from .. import ops
        f = getattr(ops, opname)
        if reverse:
            return f(other, self)
        return f(self, other)
    return fn


for _name, _op in [
    ("__add__", "add"), ("__sub__", "subtract"), ("__mul__", "multiply"),
    ("__truediv__", "divide"), ("__floordiv__", "floor_divide"),
    ("__mod__", "remainder"), ("__pow__", "pow"), ("__matmul__", "matmul"),
    ("__eq__", "equal"), ("__ne__", "not_equal"), ("__lt__", "less_than"),
    ("__le__", "less_equal"), ("__gt__", "greater_than"),
    ("__ge__", "greater_equal"), ("__and__", "bitwise_and"),
    ("__or__", "bitwise_or"), ("__xor__", "bitwise_xor"),
]:
    setattr(Tensor, _name, _make_binop(_op))

for _name, _op in [
    ("__radd__", "add"), ("__rsub__", "subtract"), ("__rmul__", "multiply"),
    ("__rtruediv__", "divide"), ("__rpow__", "pow"),
    ("__rmatmul__", "matmul"),
]:
    setattr(Tensor, _name, _make_binop(_op, reverse=True))


def _neg(self):
    from .. import ops
    return ops.scale(self, -1.0)


def _invert(self):
    from .. import ops
    return ops.logical_not(self)


Tensor.__neg__ = _neg
Tensor.__invert__ = _invert


class Parameter(Tensor):
    """Trainable tensor — `framework::Parameter`
    (`python/paddle/fluid/framework.py:6893`) parity."""

    __slots__ = ("optimize_attr", "regularizer", "do_model_average",
                 "need_clip", "is_distributed")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True
        self.is_distributed = False

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()
