"""Define-by-run eager autograd engine.

Capability parity with the reference's eager autograd
(`paddle/fluid/eager/`): GradNode graph recorded at op execution
(`grad_node_info.h:168`), queue-based topological backward walk
(`backward.cc:394 egr::Backward`, `:105 RunBackward`), leaf accumulation
(`accumulation/accumulation_node.h:23`), grad hooks (`hooks.h`), and
`paddle.grad`-style partial backward (`general_grad.h`).

TPU-native twist: instead of hand-written per-op grad kernels, each GradNode's
vjp function comes from `jax.vjp` over the op's pure-jax forward — the
residuals it closes over play the role of the reference's `TensorWrapper`
saved tensors (`eager/tensor_wrapper.h`). Every vjp call is itself XLA-traced,
so grad compute runs on the TPU like any forward op.
"""
from __future__ import annotations

import contextlib
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np

_grad_enabled = [True]


def _zero_cotangent(shape, dtype):
    """Zero cotangent matching jax.vjp's expectations: float0 for integral
    outputs, ordinary zeros for inexact ones."""
    if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(
        dtype, jnp.complexfloating
    ):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def _is_float0(g) -> bool:
    return getattr(g, "dtype", None) == jax.dtypes.float0


def is_grad_enabled() -> bool:
    return _grad_enabled[-1]


@contextlib.contextmanager
def no_grad():
    """paddle.no_grad parity."""
    _grad_enabled.append(False)
    try:
        yield
    finally:
        _grad_enabled.pop()


@contextlib.contextmanager
def enable_grad():
    _grad_enabled.append(True)
    try:
        yield
    finally:
        _grad_enabled.pop()


class set_grad_enabled:
    """`paddle.set_grad_enabled` parity (`framework/framework.py:94`):
    usable both as a context manager and as an immediate toggle."""

    def __init__(self, mode):
        self._prev = _grad_enabled[-1]
        _grad_enabled[-1] = bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _grad_enabled[-1] = self._prev
        return False


class Edge:
    """Edge from a consumer GradNode input slot back to its producer.

    kind: 'node' -> (producer GradNode, output slot); 'leaf' -> leaf Tensor
    with stop_gradient=False; 'none' -> gradient is dropped.
    Mirrors `egr::Edge` (`paddle/fluid/eager/grad_node_info.h:50`).
    """

    __slots__ = ("kind", "node", "slot", "tensor")

    def __init__(self, kind, node=None, slot=0, tensor=None):
        self.kind = kind
        self.node = node
        self.slot = slot
        self.tensor = tensor


class GradNode:
    """One recorded op; calling it runs the op's vjp."""

    __slots__ = (
        "name", "vjp_fn", "edges", "n_outputs", "out_shapes", "out_dtypes",
    )

    def __init__(self, name, vjp_fn, edges, n_outputs, out_shapes, out_dtypes):
        self.name = name
        self.vjp_fn = vjp_fn
        self.edges = edges
        self.n_outputs = n_outputs
        self.out_shapes = out_shapes
        self.out_dtypes = out_dtypes

    def __repr__(self):
        return f"<GradNode {self.name}>"


def _accumulate_leaf(tensor, grad_array, leaf_targets=None):
    from .tensor import Tensor

    if tensor.stop_gradient:
        return
    if leaf_targets is not None and id(tensor) not in leaf_targets:
        # Partial backward (paddle.grad): only the requested inputs
        # accumulate — other parameters' .grad must stay untouched
        # (reference eager/general_grad.h restricts the same way).
        return
    g = grad_array
    if tensor.grad is None:
        tensor._grad = Tensor(g, stop_gradient=True)
        # grads arrive in the tensor's PHYSICAL layout; carry the tag so
        # .grad presents the same logical facade as the tensor itself
        tensor._grad._layout = tensor._layout
    else:
        tensor._grad._data = tensor._grad._data + g
    for hook in tensor._grad_hooks:
        out = hook(tensor._grad)
        if out is not None:
            tensor._grad = out


def _reachable_and_deps(root_nodes):
    """DFS the consumer->producer DAG; count in-edges per producer."""
    deps = defaultdict(int)
    seen = set()
    stack = list(root_nodes)
    for n in root_nodes:
        seen.add(id(n))
    nodes = {id(n): n for n in root_nodes}
    while stack:
        node = stack.pop()
        for e in node.edges:
            if e.kind == "node":
                deps[id(e.node)] += 1
                if id(e.node) not in seen:
                    seen.add(id(e.node))
                    nodes[id(e.node)] = e.node
                    stack.append(e.node)
    return nodes, deps


def run_backward(tensors, grad_tensors=None, retain_graph=False,
                 leaf_targets=None, capture=None):
    """Queue-based topological walk — `egr::RunBackward` parity.

    leaf_targets: optional set of id(Tensor); when given, only those leaves
    accumulate into .grad (paddle.grad partial backward).
    capture: optional dict keyed (id(GradNode), slot); filled with the total
    cotangent that arrived at that producer slot — used to read gradients of
    non-leaf tensors without touching .grad.
    """
    from .tensor import Tensor

    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    if not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # Seed cotangent buffers.
    buffers = defaultdict(dict)  # id(node) -> {slot: array}
    root_nodes = []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            if t._data.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs"
                )
            g_arr = jnp.ones_like(t._data)
        else:
            g_arr = g._data if isinstance(g, Tensor) else jnp.asarray(g)
            # align the cotangent's physical layout with the root's
            # (core/layout.py): seeds must enter in t's PHYSICAL layout
            g_tag = g._layout if isinstance(g, Tensor) else None
            if t._layout is not None and g_tag is None:
                from . import layout as _lay
                g_arr = jnp.transpose(g_arr, _lay.TO_NHWC_PERM)
            elif t._layout is None and g_tag is not None:
                from . import layout as _lay
                g_arr = jnp.transpose(g_arr, _lay.TO_NCHW_PERM)
        node = t._grad_node
        if node is None:
            _accumulate_leaf(t, g_arr, leaf_targets)
            continue
        slot = t._out_slot
        buf = buffers[id(node)]
        buf[slot] = buf[slot] + g_arr if slot in buf else g_arr
        root_nodes.append(node)

    if not root_nodes:
        return

    nodes, deps = _reachable_and_deps(root_nodes)
    ready = [n for nid, n in nodes.items() if deps[nid] == 0 and nid in buffers]

    processed = set()
    while ready:
        node = ready.pop()
        if id(node) in processed:
            continue
        processed.add(id(node))
        buf = buffers.pop(id(node), {})
        if capture is not None:
            for slot, g in buf.items():
                if (id(node), slot) in capture:
                    capture[(id(node), slot)] = g
        cotangents = []
        for i in range(node.n_outputs):
            if i in buf:
                cotangents.append(buf[i])
            else:
                cotangents.append(
                    _zero_cotangent(node.out_shapes[i], node.out_dtypes[i])
                )
        ct = tuple(cotangents) if node.n_outputs > 1 else cotangents[0]
        if node.vjp_fn is None:
            raise RuntimeError(
                f"grad graph for {node.name} was freed; pass "
                "retain_graph=True to backward() to reuse it"
            )
        in_grads = node.vjp_fn(ct)
        if not retain_graph:
            node.vjp_fn = None
        for e, g in zip(node.edges, in_grads):
            if e.kind == "none" or _is_float0(g):
                if e.kind == "node":
                    deps[id(e.node)] -= 1
                    if deps[id(e.node)] == 0:
                        ready.append(e.node)
                continue
            if e.kind == "leaf":
                _accumulate_leaf(e.tensor, g, leaf_targets)
                continue
            pnode = e.node
            buf2 = buffers[id(pnode)]
            buf2[e.slot] = buf2[e.slot] + g if e.slot in buf2 else g
            deps[id(pnode)] -= 1
            if deps[id(pnode)] == 0:
                ready.append(pnode)

    # Diamond-free remainder: producers whose consumers were unreachable from
    # the roots keep positive deps; flush any that already hold cotangents.
    for nid, node in nodes.items():
        if nid in buffers and nid not in processed and deps[nid] >= 0:
            # Unreached due to consumers outside the backward subgraph.
            ready.append(node)
            deps[nid] = 0
    while ready:
        node = ready.pop()
        if id(node) in processed or id(node) not in buffers:
            continue
        processed.add(id(node))
        buf = buffers.pop(id(node))
        if capture is not None:
            for slot, g in buf.items():
                if (id(node), slot) in capture:
                    capture[(id(node), slot)] = g
        cotangents = []
        for i in range(node.n_outputs):
            cotangents.append(
                buf.get(i, _zero_cotangent(node.out_shapes[i],
                                           node.out_dtypes[i]))
            )
        ct = tuple(cotangents) if node.n_outputs > 1 else cotangents[0]
        if node.vjp_fn is None:
            continue
        in_grads = node.vjp_fn(ct)
        if not retain_graph:
            node.vjp_fn = None
        for e, g in zip(node.edges, in_grads):
            if _is_float0(g):
                continue
            if e.kind == "leaf":
                _accumulate_leaf(e.tensor, g, leaf_targets)
            elif e.kind == "node":
                buf2 = buffers[id(e.node)]
                buf2[e.slot] = buf2[e.slot] + g if e.slot in buf2 else g
                deps[id(e.node)] -= 1
                if deps[id(e.node)] <= 0:
                    ready.append(e.node)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """paddle.grad parity (`eager/general_grad.h` capability).

    Runs a backward pass and collects grads for `inputs` without writing
    their `.grad` attributes.
    """
    from .tensor import Tensor as _T

    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if create_graph:
        raise NotImplementedError(
            "paddle.grad(create_graph=True) (double backward) is not "
            "supported yet; use paddle.incubate.autograd jvp/vjp "
            "transforms for higher-order derivatives")
    if retain_graph is None:
        retain_graph = create_graph

    # Leaf inputs accumulate via .grad (stashed + restricted so no other
    # parameter's .grad is touched); non-leaf inputs are read from the
    # cotangent buffer of their producer slot.
    leaf_inputs = [t for t in inputs if t._grad_node is None]
    leaf_targets = {id(t) for t in leaf_inputs}
    capture = {}
    for t in inputs:
        if t._grad_node is not None:
            capture[(id(t._grad_node), t._out_slot)] = None

    stash = [t._grad for t in leaf_inputs]
    for t in leaf_inputs:
        t._grad = None
    prev_sg = [t.stop_gradient for t in leaf_inputs]
    for t in leaf_inputs:
        t.stop_gradient = False
    try:
        run_backward(outputs, grad_outputs, retain_graph=retain_graph,
                     leaf_targets=leaf_targets, capture=capture)
        results = []
        for t in inputs:
            if t._grad_node is not None:
                g = capture.get((id(t._grad_node), t._out_slot))
                got = None if g is None else _T(g, stop_gradient=True)
                if got is not None:
                    # captured cotangent is in t's PHYSICAL layout — tag
                    # it so .shape/.numpy() present the logical facade
                    got._layout = t._layout
            else:
                got = t._grad
            if got is None:
                if not allow_unused:
                    raise RuntimeError(
                        "one of the input tensors received no gradient; "
                        "pass allow_unused=True to get None instead"
                    )
                results.append(None)
            else:
                results.append(got)
        return results
    finally:
        for t, g, sg in zip(leaf_inputs, stash, prev_sg):
            t._grad = g
            t.stop_gradient = sg
