"""Op dispatch: the eager hot path.

Reference parity: this is the collapsed TPU-native form of the reference's
dygraph call chain (SURVEY §3.1) — pybind `<op>_ad_func`
(`eager/auto_code_generator/generator/eager_gen.py:1109`) → PHI API kernel
selection (`paddle/phi/api/yaml/generator/api_base.py:373`) →
`KernelFactory::SelectKernelOrThrowError` (`paddle/phi/core/kernel_factory.h:277`).

Here every op is a pure-jax function over raw arrays; XLA is the kernel
library and the per-(op, shape, dtype) compilation cache replaces the kernel
registry. Autograd recording (the `eager_gen.py` grad-node wiring) happens in
`apply()` via `jax.vjp`.
"""
from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from . import layout as _layout
from .autograd import Edge, GradNode
from ..profiler import metrics as _metrics


def _nan_inf_callback(x, op_name):
    if not np.isfinite(np.asarray(x)).all():
        if _metrics._enabled:
            _metrics.NAN_INF_EVENTS.labels(op_name).inc()
        raise FloatingPointError(
            f"NaN/Inf detected in output of op '{op_name}' "
            f"(shape {getattr(x, 'shape', ())}) inside a compiled step")


def _edge_for(t):
    if t._grad_node is not None:
        return Edge("node", node=t._grad_node, slot=t._out_slot)
    if not t.stop_gradient:
        return Edge("leaf", tensor=t)
    return Edge("none")


def _is_float(dtype) -> bool:
    return jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(
        dtype, jnp.complexfloating
    )


# ---- memoized jitted backward -------------------------------------------
#
# The deferred `jax.vjp` trace costs ~1.4 ms per node (the dominant
# eager-training overhead, docs/eager_dispatch_analysis.md). Training
# loops replay the same (op, shapes) every step, so the linearized
# backward is memoized as a JITTED function keyed on the op's code
# object + scalar closure constants + input/cotangent avals + the
# flags/amp snapshot. Steps 2+ skip tracing entirely and dispatch a
# compiled executable. Ops whose closures capture non-scalar state
# (arrays, objects) safely fall back to the per-node trace.
#
# INVARIANT (every op fn passed to apply() must obey): the fingerprint
# hashes the code object, closure cells, defaults, and the FLAGS/amp
# snapshot — it does NOT hash anything the fn reads from its
# `__globals__`. An op fn that reads a *mutable* module global inside
# its body would replay a stale compiled backward after that global
# changes. All per-call variability must therefore flow through closure
# variables, defaults, functools.partial args, or the paddle
# FLAGS/amp-state snapshot (which IS part of the cache key). The repo's
# op library follows this convention everywhere (e.g. conv closes over
# strides/pad/dimension-spec booleans); tests/test_pass_cache.py
# asserts it for a representative op and demonstrates the aliasing that
# motivates the rule.

_VJP_JIT_CACHE = {}
_VJP_JIT_CACHE_MAX = 1024


def _scalar_const(v):
    """Hashable fingerprint for a closure constant, or raise TypeError."""
    if v is None or isinstance(v, (int, float, bool, str, bytes)):
        return v
    if isinstance(v, (tuple, frozenset)):
        return tuple(_scalar_const(x) for x in v)
    if isinstance(v, jnp.dtype) or (isinstance(v, type)
                                    and issubclass(v, jnp.generic)):
        return str(v)
    if callable(v):
        fp = _fn_fingerprint(v)
        if fp is not None:
            return fp
    raise TypeError


def _fn_fingerprint(fn):
    """Hashable identity of fn's code + captured constants, or None when
    the closure holds anything we can't safely key on.

    GUARD: values fn reads from `__globals__` are deliberately NOT part
    of the fingerprint (hashing a module dict per dispatch would cost
    more than the trace it saves) — see the INVARIANT note above. Keep
    op fns free of mutable-global reads."""
    try:
        if isinstance(fn, functools.partial):
            sub = _fn_fingerprint(fn.func)
            if sub is None:
                return None
            # args and kwargs tagged separately: partial(f, ('axis', 0))
            # must not alias partial(f, axis=0)
            return ("partial", sub, _scalar_const(tuple(fn.args)),
                    _scalar_const(tuple(sorted(fn.keywords.items()))))
        if getattr(fn, "__self__", None) is not None:
            # bound method: __code__/__closure__ proxy the underlying
            # function and would alias instances with different state
            return None
        code = getattr(fn, "__code__", None)
        if code is None:
            return None
        # the code object itself (hashable) — id() could be reused
        # after GC and alias two different ops to one cache entry
        parts = [code]
        for cell in fn.__closure__ or ():
            parts.append(_scalar_const(cell.cell_contents))
        for d in fn.__defaults__ or ():
            parts.append(_scalar_const(d))
        return ("fn", tuple(parts))
    except (TypeError, ValueError):
        return None


def _aval_sig(tree):
    return tuple(
        (getattr(x, "shape", ()), str(getattr(x, "dtype", type(x))))
        for x in jax.tree.leaves(tree))


class _LazyVjp:
    """Deferred vjp: the eager forward runs fn directly (one jax eager
    dispatch, ~50us) and the `jax.vjp` LINEARIZATION — measured ~1.4 ms
    of tracing per op on CPU, the dominant eager-dispatch cost
    (docs/eager_dispatch_analysis.md) — happens only if backward
    actually reaches this node. Ops are pure (randomness enters as
    explicit key inputs/closures), so the deferred re-trace reproduces
    the forward exactly; this is the remat trade the reference makes in
    `fleet/recompute` applied to the eager tape.

    Mutable GLOBAL config an op might read inside fn (paddle flags, the
    amp auto_cast state) is snapshotted at record time and restored
    around the deferred trace, so a `set_flags`/amp-context change
    between forward and .backward() cannot silently linearize a
    different computation than the one that ran (ADVICE r4 #5)."""

    __slots__ = ("fn", "arrays", "_vjp", "_flags", "_amp", "_mode")

    def __init__(self, fn, arrays):
        self.fn = fn
        self.arrays = arrays
        self._vjp = None
        self._mode = "replay"   # repeat calls replay the kept vjp
        from .. import flags as _flags
        from ..amp.auto_cast import _state as _amp_state
        self._flags = dict(_flags._FLAGS)
        self._amp = dict(_amp_state)

    def __call__(self, ct):
        if not _metrics._enabled:
            return self._run(ct)
        t0 = time.perf_counter()
        out = self._run(ct)
        _metrics.VJP_BACKWARD_SECONDS.labels(self._mode).observe(
            time.perf_counter() - t0)
        return out

    def _run(self, ct):
        if self._vjp is not None:
            self._mode = "replay"
            return self._vjp(ct)
        if self.fn is not None:
            fp = _fn_fingerprint(self.fn)
            if fp is not None:
                key = (fp, _aval_sig(self.arrays), _aval_sig(ct),
                       tuple(sorted(self._flags.items())),
                       tuple(sorted(self._amp.items())))
                try:
                    jitted = _VJP_JIT_CACHE.get(key)
                except TypeError:      # unhashable flag/amp value
                    jitted = key = None
                if key is not None:
                    if jitted is None:
                        self._mode = "trace"
                        if _metrics._enabled:
                            _metrics.VJP_CACHE.labels("miss").inc()
                        if len(_VJP_JIT_CACHE) >= _VJP_JIT_CACHE_MAX:
                            # full flush on overflow (a per-entry LRU
                            # would need an ordered dict walk per hit);
                            # the eviction counter makes a thrashing
                            # cache visible instead of silent
                            evicted = len(_VJP_JIT_CACHE)
                            _VJP_JIT_CACHE.clear()
                            if _metrics._enabled:
                                _metrics.VJP_CACHE.labels(
                                    "eviction").inc(evicted)
                        fn = self.fn
                        jitted = jax.jit(
                            lambda arrays, ct:
                            jax.vjp(fn, *arrays)[1](ct))
                        _VJP_JIT_CACHE[key] = jitted
                    else:
                        self._mode = "replay"
                        if _metrics._enabled:
                            _metrics.VJP_CACHE.labels("hit").inc()
                    # keep a reusable vjp (retain_graph contract): the
                    # closure holds the arrays the jitted call replays
                    arrays = self.arrays
                    self._vjp = lambda c: self._with_snapshot(
                        jitted, arrays, c)
                    self.fn = self.arrays = None
                    return self._vjp(ct)
        if self._vjp is None:
            self._mode = "fallback"
            if _metrics._enabled:
                _metrics.VJP_CACHE.labels("fallback").inc()
            _, self._vjp = self._with_snapshot(jax.vjp, self.fn,
                                               *self.arrays)
            self.fn = self.arrays = None  # free after tracing
        return self._vjp(ct)

    def _with_snapshot(self, f, *args):
        """Run f under the record-time flags/amp snapshot (tracing must
        see the state the forward saw; cheap dict swaps otherwise)."""
        from .. import flags as _flags
        from ..amp.auto_cast import _state as _amp_state
        cur_flags = dict(_flags._FLAGS)
        cur_amp = dict(_amp_state)
        _flags._FLAGS.update(self._flags)
        _amp_state.update(self._amp)
        try:
            return f(*args)
        finally:
            _flags._FLAGS.clear()
            _flags._FLAGS.update(cur_flags)
            _amp_state.clear()
            _amp_state.update(cur_amp)


def apply(name, fn, inputs, differentiable=True):
    """Run op `fn` over the raw arrays of `inputs` (Tensors), recording a
    GradNode when grad is enabled and any input requires grad."""
    from .tensor import Tensor

    if _metrics._enabled:
        _metrics.DISPATCH_OPS.labels(name).inc()

    # ---- layout funnel (core/layout.py) --------------------------------
    # Tagged (physically-NHWC) inputs: layout-AWARE ops pass through
    # untouched (their functional built fn for the tag), TRANSPARENT
    # elementwise ops run physically and propagate the tag, everything
    # else materializes back to the logical layout first — correctness
    # never depends on an op being layout-aware.
    out_tag = None
    for t in inputs:
        if t._layout is not None:
            if name in _layout.AWARE_OPS:
                break
            if name in _layout.TRANSPARENT_OPS and \
                    _layout._transparent_ok(inputs):
                out_tag = _layout.NHWC
                break
            inputs = tuple(_layout.materialize(i) for i in inputs)
            break

    arrays = tuple(t._data for t in inputs)
    need_grad = (
        differentiable
        and autograd.is_grad_enabled()
        and any(not t.stop_gradient for t in inputs)
    )
    outs = fn(*arrays)
    vjp_fn = _LazyVjp(fn, arrays) if need_grad else None

    multi = isinstance(outs, (tuple, list))
    outs_t = tuple(outs) if multi else (outs,)

    node = None
    if need_grad:
        # Ops whose every output is integral can't carry grad.
        if not any(_is_float(o.dtype) for o in outs_t):
            need_grad = False
        else:
            node = GradNode(
                name,
                vjp_fn,
                [_edge_for(t) for t in inputs],
                len(outs_t),
                [o.shape for o in outs_t],
                [o.dtype for o in outs_t],
            )

    # FLAGS_check_nan_inf parity (`framework/details/nan_inf_utils_detail`):
    # scan every float output when the debug flag is on. Eager values are
    # checked synchronously; traced values (ops being compiled into a jit
    # step, e.g. the whole-step trainer) get a `jax.debug.callback` baked
    # into the executable so the scan runs at execution time with the op
    # name attributed — the reference wraps every kernel launch the same
    # way.
    from ..flags import check_nan_inf_enabled
    if check_nan_inf_enabled():
        for o in outs_t:
            if not _is_float(o.dtype):
                continue
            if isinstance(o, jax.core.Tracer):
                jax.debug.callback(
                    functools.partial(_nan_inf_callback, op_name=name), o)
            elif not bool(jnp.isfinite(o).all()):
                if _metrics._enabled:
                    _metrics.NAN_INF_EVENTS.labels(name).inc()
                raise FloatingPointError(
                    f"NaN/Inf detected in output of op '{name}' "
                    f"(shape {o.shape}, dtype {o.dtype})")

    results = []
    for i, o in enumerate(outs_t):
        t = Tensor(o, stop_gradient=not (need_grad and _is_float(o.dtype)))
        if need_grad and _is_float(o.dtype):
            t._grad_node = node
            t._out_slot = i
        if out_tag is not None and o.ndim == 4:
            t._layout = out_tag    # transparent op: tag rides through
        results.append(t)
    return tuple(results) if multi else results[0]
