"""Stateful RNG facade over jax PRNG keys.

The reference exposes a global stateful generator (`paddle/phi/core/generator.h`,
`paddle.seed`). JAX is functional, so we keep a stack of RNG states: the base
state is a concrete key advanced by splitting; `functional_rng(key)` pushes a
state bound to a traced key so random layers (dropout etc.) stay correct inside
`jax.jit`-traced training steps — the caller supplies a fresh key per step.

Also provides the TP rng-state tracker capability
(`python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py`:
``get_rng_state_tracker`` — named local/global seeds so e.g. dropout masks are
replicated or varied across model-parallel ranks as required).
"""
from __future__ import annotations

import contextlib
import os

import jax
import numpy as np


def _use_rbg() -> bool:
    """TPU default: the hardware RngBitGenerator PRNG ('rbg') instead of
    threefry. Threefry is a software counter-based PRNG that costs real
    compute on TPU (measured 11.3 ms/step of a 65 ms BERT-base AMP
    train step just for dropout masks); rbg lowers to the on-chip RNG
    and is effectively free. Same design choice as T5X/MaxText.
    Opt out: PADDLE_TPU_RBG_RANDOM=0. Off-TPU keeps threefry (bitwise
    reproducibility of existing CPU tests)."""
    if os.environ.get("PADDLE_TPU_RBG_RANDOM", "1") != "1":
        return False
    from .place import on_tpu_backend
    return on_tpu_backend()


def make_key(s: int):
    """Seed -> PRNG key with the platform-appropriate implementation."""
    if _use_rbg():
        return jax.random.key(int(s), impl="rbg")
    return jax.random.PRNGKey(int(s))


class RNGState:
    def __init__(self, key):
        self.key = key

    def next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub


_stack = [RNGState(make_key(0))]


def seed(s: int):
    """paddle.seed parity."""
    _stack[0] = RNGState(make_key(int(s)))
    return _stack[0]


def next_key():
    return _stack[-1].next_key()


def get_rng_state():
    return _stack[-1].key


def set_rng_state(key):
    _stack[-1].key = key


@contextlib.contextmanager
def functional_rng(key):
    """Bind the RNG to a (possibly traced) key for the duration of a trace."""
    _stack.append(RNGState(key))
    try:
        yield
    finally:
        _stack.pop()


class RNGStatesTracker:
    """Named rng states for tensor parallelism.

    Parity: fleet's ``RNGStatesTracker``
    (meta_parallel/parallel_layers/random.py) — 'global_seed' states are
    identical on all mp ranks, 'local_seed' states differ per rank so dropout
    inside column/row-parallel regions decorrelates.
    """

    def __init__(self):
        self.states_ = {}

    def add(self, name, s):
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.states_[name] = RNGState(make_key(int(s)))

    def reset(self):
        self.states_ = {}

    @contextlib.contextmanager
    def rng_state(self, name="model_parallel_rng"):
        if name not in self.states_:
            raise ValueError(f"state {name} not added")
        _stack.append(self.states_[name])
        try:
            yield
        finally:
            _stack.pop()


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


def model_parallel_random_seed(seed_: int, mp_rank: int = 0):
    global_seed = 100003 + seed_
    local_seed = seed_ + 2718 + mp_rank * 1024
    _tracker.reset()
    _tracker.add("global_seed", global_seed)
    _tracker.add("local_seed", local_seed)


def np_rng() -> np.random.Generator:
    """Host-side numpy generator for data pipelines."""
    return np.random.default_rng()
