"""Device placement.

Parity with the reference's Place hierarchy (`paddle/phi/common/place.h`) and
`paddle.device.set_device` (`python/paddle/device/__init__.py`), mapped onto
jax devices. The TPU place is first-class; CPU is the host fallback.
"""
from __future__ import annotations

import jax


class Place:
    """Base place. Equality is by (kind, device_id)."""

    kind = "undefined"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.kind, self.device_id))

    def __repr__(self):
        return f"Place({self.kind}:{self.device_id})"

    def jax_device(self):
        devs = [d for d in jax.devices() if _platform_of(d) == self.kind]
        if not devs:
            # fall back to host cpu devices
            devs = jax.devices("cpu")
        return devs[min(self.device_id, len(devs) - 1)]


def _platform_of(dev) -> str:
    p = dev.platform
    return {"cpu": "cpu", "tpu": "tpu", "axon": "tpu"}.get(p, p)


def on_tpu_backend() -> bool:
    """True when the default jax backend is a TPU (incl. the axon
    relay).  The single shared predicate for TPU-only fast paths
    (Pallas kernels, rbg RNG); extend the platform set here, not at
    call sites."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


class CPUPlace(Place):
    kind = "cpu"


class TPUPlace(Place):
    kind = "tpu"


# paddle calls its accelerator place CUDAPlace; we keep an alias so ported
# user code keeps working, but it resolves to the TPU.
CUDAPlace = TPUPlace

_current_place: Place | None = None


def _default_place() -> Place:
    try:
        plat = _platform_of(jax.devices()[0])
    except Exception:
        plat = "cpu"
    return TPUPlace(0) if plat == "tpu" else CPUPlace(0)


def get_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = _default_place()
    return _current_place


def set_device(device) -> Place:
    """paddle.device.set_device('tpu:0' | 'cpu' | 'gpu:0') parity."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return _current_place
    s = str(device).lower()
    dev_id = 0
    if ":" in s:
        s, idx = s.split(":", 1)
        dev_id = int(idx)
    if s in ("tpu", "gpu", "cuda", "xpu", "npu"):
        _current_place = TPUPlace(dev_id)
    elif s == "cpu":
        _current_place = CPUPlace(dev_id)
    else:
        raise ValueError(f"unknown device {device!r}")
    return _current_place


def get_device() -> str:
    p = get_place()
    return f"{p.kind}:{p.device_id}"


def is_compiled_with_cuda() -> bool:  # parity shim; we are TPU-native
    return False


def is_compiled_with_tpu() -> bool:
    try:
        return _platform_of(jax.devices()[0]) == "tpu"
    except Exception:
        return False
