"""Channels-last layout propagation (layout autotune).

Parity: the reference's imperative layout-autotune pass
(`paddle/fluid/imperative/layout_autotune.cc` + `layout_transformer.h`):
vision models are written channel-first (NCHW) but matrix-unit hardware
wants channels-last (NHWC), so the framework rewrites the *interior* of
the graph to NHWC and keeps the public API/checkpoints NCHW.

TPU translation: instead of a graph pass, the layout is a physical tag
carried on the eager `Tensor` wrapper and resolved at dispatch time —
which also covers jit tracing, because the compiled train step traces
the model through the same `dispatch.apply` funnel.

  - A tensor with ``_layout == "NHWC"`` is *logically* NCHW but its
    backing array is stored as NHWC (axes permuted by `TO_NHWC_PERM`).
    Tags are only ever applied to 4-D image tensors.
  - Layout-AWARE ops (conv2d, batch_norm, pooling, interpolate, pad)
    consume and produce tagged tensors natively — no edge transposes.
  - Layout-TRANSPARENT ops (elementwise: relu/add/mul/cast/...) run
    directly on the tagged physical array and propagate the tag.
  - Every other op hits the default policy in `dispatch.apply`:
    `materialize()` back to logical NCHW first. Correctness never
    depends on an op knowing about layouts.

Net effect: an NCHW user model runs its whole conv/BN/pool interior in
NHWC with exactly one transpose at each graph edge (the first conv's
input, and the materialize at the pool->flatten/fc boundary).

Gate: ``PADDLE_TPU_LAYOUT_AUTOTUNE`` (default ON; ``=0`` restores the
per-op edge transposes bit-for-bit for A/B). The optional
space-to-depth ResNet stem rewrite is gated by ``PADDLE_TPU_S2D_STEM``
(default OFF; see nn/functional/conv.py).
"""
from __future__ import annotations

import os

NHWC = "NHWC"              # the only physical tag (4-D logical-NCHW only)
TO_NHWC_PERM = (0, 2, 3, 1)
TO_NCHW_PERM = (0, 3, 1, 2)


def enabled() -> bool:
    v = os.environ.get("PADDLE_TPU_LAYOUT_AUTOTUNE", "1")
    return v.lower() not in ("0", "false", "off", "no")


def s2d_stem_enabled() -> bool:
    v = os.environ.get("PADDLE_TPU_S2D_STEM", "0")
    return v.lower() in ("1", "true", "on", "yes")


# Ops that handle tags themselves (their functional inspects input tags
# and builds the right fn): dispatch.apply must pass tagged inputs
# through untouched. The layout_to_* transposes are here too — they ARE
# the materialization, recursing would never terminate.
AWARE_OPS = frozenset({
    "conv2d", "batch_norm_train", "batch_norm_infer", "pool",
    "adaptive_pool", "interpolate", "pad",
    "layout_to_nchw", "layout_to_nhwc",
})

# Shape-preserving elementwise ops where physical layout is irrelevant:
# run on the raw NHWC array and keep the tag. An op may only live here
# if its semantics carry NO axis meaning (a reduction, an axis= arg, or
# broadcasting against a non-scalar untagged operand all disqualify —
# see _transparent_ok for the runtime guard on operands).
TRANSPARENT_OPS = frozenset({
    # activations (nn/functional/activation.py)
    "relu", "relu6", "sigmoid", "tanh", "gelu", "silu", "swish", "mish",
    "leaky_relu", "elu", "selu", "celu", "hardswish", "hardsigmoid",
    "hardtanh", "hardshrink", "softshrink", "softplus", "softsign",
    "tanhshrink", "swiglu",
    # elementwise math (ops/math.py)
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "pow", "scale", "clip", "nan_to_num", "abs", "square", "sqrt",
    "rsqrt", "exp", "expm1", "log", "log1p", "sign", "floor", "ceil",
    "round", "heaviside", "logaddexp",
    # plumbing ("dropout" here is the axis=None form only — the
    # functional materializes first when axis= is given)
    "cast", "assign", "dropout", "dropout_scale",
})


def _transparent_ok(inputs) -> bool:
    """A transparent op may run physically only when every operand is
    either tagged (4-D, consistently permuted) or layout-free (scalar /
    single element, which broadcasts identically under any axis
    permutation). An untagged multi-element operand would broadcast
    against the wrong trailing axis — fall back to materialization."""
    for t in inputs:
        if t._layout is None and t._data.ndim != 0 and t._data.size != 1:
            return False
    return True


def materialize(t):
    """Return `t` in its logical (untagged, NCHW) layout, recording the
    transpose on the autograd tape / trace like any other op."""
    if t._layout is None:
        return t
    from . import dispatch
    import jax.numpy as jnp
    out = dispatch.apply("layout_to_nchw",
                         lambda a: jnp.transpose(a, TO_NCHW_PERM), (t,))
    return out


def to_nhwc(t):
    """Tagged (physically NHWC) view of a logically-NCHW tensor."""
    if t._layout == NHWC:
        return t
    from . import dispatch
    import jax.numpy as jnp
    out = dispatch.apply("layout_to_nhwc",
                         lambda a: jnp.transpose(a, TO_NHWC_PERM), (t,))
    out._layout = NHWC
    return out
