"""Vision ops — parity: `python/paddle/vision/ops.py` (nms, roi_align,
box ops; deform_conv planned)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops._helpers import as_tensor
from ..core import dispatch


def _nms_single(b, s, iou_threshold):
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.maximum(0, xx2 - xx1) * np.maximum(0, yy2 - yy1)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-9)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (host loop — eager-only like the reference's CPU path).
    boxes [N,4] (x1,y1,x2,y2); per-category when category_idxs given.
    Returns kept indices sorted by score."""
    b = as_tensor(boxes).numpy()
    s = as_tensor(scores).numpy() if scores is not None else \
        np.arange(len(b), 0, -1, dtype=np.float32)
    if category_idxs is not None:
        cats = as_tensor(category_idxs).numpy()
        cat_list = (as_tensor(categories).numpy().tolist()
                    if categories is not None else np.unique(cats).tolist())
        keep = []
        for c in cat_list:
            idx = np.where(cats == c)[0]
            if idx.size == 0:
                continue
            kept = _nms_single(b[idx], s[idx], iou_threshold)
            keep.extend(idx[kept].tolist())
    else:
        keep = _nms_single(b, s, iou_threshold)
    keep = np.asarray(sorted(keep, key=lambda i: -s[i]), np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def box_area(boxes):
    boxes = as_tensor(boxes)

    def _fn(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return dispatch.apply("box_area", _fn, (boxes,))


def box_iou(boxes1, boxes2):
    boxes1, boxes2 = as_tensor(boxes1), as_tensor(boxes2)

    def _fn(b1, b2):
        a1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        a2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(a1[:, None] + a2[None, :] - inter,
                                   1e-9)
    return dispatch.apply("box_iou", _fn, (boxes1, boxes2))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign via bilinear grid sampling (XLA gather).
    x [N,C,H,W]; boxes [R,4]; boxes_num [N]."""
    x, boxes = as_tensor(x), as_tensor(boxes)
    boxes_num = as_tensor(boxes_num)
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    def _fn(img, bxs, bn):
        R = bxs.shape[0]
        C, H, W = img.shape[1], img.shape[2], img.shape[3]
        # map each roi to its batch image
        batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                               total_repeat_length=R)
        off = 0.5 if aligned else 0.0
        x1 = bxs[:, 0] * spatial_scale - off
        y1 = bxs[:, 1] * spatial_scale - off
        x2 = bxs[:, 2] * spatial_scale - off
        y2 = bxs[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        ys = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * \
            (rh[:, None] / oh)                       # [R, oh]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * \
            (rw[:, None] / ow)                       # [R, ow]

        def bilinear(r):
            im = img[batch_idx[r]]                   # [C,H,W]
            yy = jnp.clip(ys[r], 0, H - 1)
            xx = jnp.clip(xs[r], 0, W - 1)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, H - 1)
            x1_ = jnp.minimum(x0 + 1, W - 1)
            wy = yy - y0
            wx = xx - x0
            # gather 4 corners: [C, oh, ow]
            def g(yi, xi):
                return im[:, yi][:, :, xi]
            out = (g(y0, x0) * (1 - wy)[None, :, None]
                   * (1 - wx)[None, None, :]
                   + g(y1_, x0) * wy[None, :, None]
                   * (1 - wx)[None, None, :]
                   + g(y0, x1_) * (1 - wy)[None, :, None]
                   * wx[None, None, :]
                   + g(y1_, x1_) * wy[None, :, None]
                   * wx[None, None, :])
            return out
        return jax.vmap(bilinear)(jnp.arange(R))
    return dispatch.apply("roi_align", _fn, (x, boxes, boxes_num))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """`roi_pool_kernel.h` — max pooling inside each RoI bin."""
    x, boxes = as_tensor(x), as_tensor(boxes)
    boxes_num = as_tensor(boxes_num)
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    def _fn(img, bxs, bn):
        R = bxs.shape[0]
        H, W = img.shape[2], img.shape[3]
        batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                               total_repeat_length=R)
        b = jnp.round(bxs * spatial_scale).astype(jnp.int32)

        def one(r):
            im = img[batch_idx[r]]               # [C, H, W]
            x1, y1, x2, y2 = b[r, 0], b[r, 1], b[r, 2], b[r, 3]
            rw = jnp.maximum(x2 - x1 + 1, 1)
            rh = jnp.maximum(y2 - y1 + 1, 1)
            # bin edges (ceil/floor like the reference kernel)
            ys = y1 + (jnp.arange(oh + 1) * rh) // oh
            xs = x1 + (jnp.arange(ow + 1) * rw) // ow
            yy = jnp.clip(jnp.arange(H), 0, H - 1)
            # mask-based max per bin (static shapes: mask full image)
            gy = jnp.arange(H)[None, :]
            gx = jnp.arange(W)[None, :]
            ymask = (gy >= ys[:-1, None]) & (gy < jnp.maximum(
                ys[1:, None], ys[:-1, None] + 1))     # [oh, H]
            xmask = (gx >= xs[:-1, None]) & (gx < jnp.maximum(
                xs[1:, None], xs[:-1, None] + 1))     # [ow, W]
            m = ymask[:, None, :, None] & xmask[None, :, None, :]
            big = jnp.where(m[None], im[:, None, None, :, :],
                            -jnp.inf)
            return jnp.max(big, axis=(-2, -1))        # [C, oh, ow]
        return jax.vmap(one)(jnp.arange(R))
    return dispatch.apply("roi_pool", _fn, (x, boxes, boxes_num))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """`psroi_pool_kernel.h` — position-sensitive RoI average pooling:
    channel group (i,j) pools bin (i,j)."""
    x, boxes = as_tensor(x), as_tensor(boxes)
    boxes_num = as_tensor(boxes_num)
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    def _fn(img, bxs, bn):
        R = bxs.shape[0]
        C, H, W = img.shape[1], img.shape[2], img.shape[3]
        Co = C // (oh * ow)
        batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                               total_repeat_length=R)
        bs = bxs * spatial_scale

        def one(r):
            im = img[batch_idx[r]].reshape(Co, oh, ow, H, W)
            x1, y1, x2, y2 = bs[r, 0], bs[r, 1], bs[r, 2], bs[r, 3]
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            ys = y1 + jnp.arange(oh + 1) * (rh / oh)
            xs = x1 + jnp.arange(ow + 1) * (rw / ow)
            gy = jnp.arange(H)[None, :]
            gx = jnp.arange(W)[None, :]
            ymask = ((gy + 0.5 >= ys[:-1, None])
                     & (gy + 0.5 < ys[1:, None])).astype(img.dtype)
            xmask = ((gx + 0.5 >= xs[:-1, None])
                     & (gx + 0.5 < xs[1:, None])).astype(img.dtype)
            m = ymask[:, None, :, None] * xmask[None, :, None, :]
            s = jnp.einsum("cijhw,ijhw->cij", im, m)
            cnt = jnp.maximum(jnp.sum(m, axis=(-2, -1)), 1.0)
            return s / cnt
        return jax.vmap(one)(jnp.arange(R))
    return dispatch.apply("psroi_pool", _fn, (x, boxes, boxes_num))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """`box_coder_kernel.h` — encode/decode boxes against priors."""
    pb = as_tensor(prior_box)
    tb = as_tensor(target_box)
    pbv = as_tensor(prior_box_var) if prior_box_var is not None else None
    inputs = (pb, tb) if pbv is None else (pb, tb, pbv)

    def _fn(p, t, *rest):
        v = rest[0] if rest else None
        norm = 0.0 if box_normalized else 1.0
        pw = p[:, 2] - p[:, 0] + norm
        ph = p[:, 3] - p[:, 1] + norm
        pcx = p[:, 0] + pw * 0.5
        pcy = p[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = t[:, 2] - t[:, 0] + norm
            th = t[:, 3] - t[:, 1] + norm
            tcx = t[:, 0] + tw * 0.5
            tcy = t[:, 1] + th * 0.5
            out = jnp.stack([(tcx[:, None] - pcx[None, :]) / pw[None, :],
                             (tcy[:, None] - pcy[None, :]) / ph[None, :],
                             jnp.log(tw[:, None] / pw[None, :]),
                             jnp.log(th[:, None] / ph[None, :])], -1)
            if v is not None:
                out = out / v[None, :, :]
            return out
        # decode_center_size: t [N, M, 4] deltas against M priors
        if axis == 1:
            pw, ph, pcx, pcy = (pw[None, :], ph[None, :],
                                pcx[None, :], pcy[None, :])
        else:
            pw, ph, pcx, pcy = (pw[:, None], ph[:, None],
                                pcx[:, None], pcy[:, None])
        d = t if v is None else t * (v[None] if v.ndim == 2 else v)
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], -1)
    return dispatch.apply("box_coder", _fn, inputs)


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """`prior_box_kernel.h` — SSD prior (anchor) boxes + variances."""
    input, image = as_tensor(input), as_tensor(image)

    def _fn(feat, img):
        fh, fw = feat.shape[2], feat.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        sw = steps[0] or iw / fw
        sh = steps[1] or ih / fh
        ars = [1.0]
        for ar in aspect_ratios:
            if all(abs(ar - a) > 1e-6 for a in ars):
                ars.append(float(ar))
                if flip:
                    ars.append(1.0 / float(ar))
        whs = []
        for ms in min_sizes:
            if min_max_aspect_ratios_order:
                whs.append((ms, ms))
                if max_sizes:
                    mx = max_sizes[len(whs) // (len(ars) + 1)] \
                        if False else max_sizes[0]
                    whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            else:
                for ar in ars:
                    whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
                if max_sizes:
                    mx = max_sizes[min_sizes.index(ms)]
                    whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
        whs = jnp.asarray(whs, jnp.float32)           # [K, 2]
        cx = (jnp.arange(fw) + offset) * sw
        cy = (jnp.arange(fh) + offset) * sh
        gx, gy = jnp.meshgrid(cx, cy)                 # [fh, fw]
        c = jnp.stack([gx, gy], -1)[:, :, None, :]    # [fh,fw,1,2]
        half = whs[None, None, :, :] * 0.5
        mins = (c - half) / jnp.asarray([iw, ih], jnp.float32)
        maxs = (c + half) / jnp.asarray([iw, ih], jnp.float32)
        boxes = jnp.concatenate([mins, maxs], -1)     # [fh,fw,K,4]
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               boxes.shape)
        return boxes, var
    return dispatch.apply("prior_box", _fn, (input, image))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """`yolo_box_kernel.h` — decode YOLOv3 head to boxes + scores."""
    x, img_size = as_tensor(x), as_tensor(img_size)
    na = len(anchors) // 2

    def _fn(p, imsz):
        N, C, H, W = p.shape
        an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
        p = p.reshape(N, na, -1, H, W)                # [N,a,5+cls,H,W]
        gx = jnp.arange(W, dtype=jnp.float32)
        gy = jnp.arange(H, dtype=jnp.float32)
        sx = jax.nn.sigmoid(p[:, :, 0]) * scale_x_y \
            - (scale_x_y - 1.0) / 2.0
        sy = jax.nn.sigmoid(p[:, :, 1]) * scale_x_y \
            - (scale_x_y - 1.0) / 2.0
        bx = (gx[None, None, None, :] + sx) / W
        by = (gy[None, None, :, None] + sy) / H
        bw = jnp.exp(p[:, :, 2]) * an[None, :, 0, None, None] \
            / (W * downsample_ratio)
        bh = jnp.exp(p[:, :, 3]) * an[None, :, 1, None, None] \
            / (H * downsample_ratio)
        obj = jax.nn.sigmoid(p[:, :, 4])
        cls = jax.nn.sigmoid(p[:, :, 5:])
        score = obj[:, :, None] * cls                 # [N,a,cls,H,W]
        imh = imsz[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imsz[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(N, -1, 4)
        keep = (obj > conf_thresh).astype(score.dtype)
        scores = (score * keep[:, :, None]).transpose(0, 1, 3, 4, 2) \
            .reshape(N, -1, cls.shape[2])
        return boxes, scores
    return dispatch.apply("yolo_box", _fn, (x, img_size),
                          differentiable=False)


def decode_jpeg(x, mode="unchanged", name=None):
    """`decode_jpeg_kernel.h` — host-side JPEG decode (the reference
    runs nvjpeg; TPU has no device decoder, so decode on host like its
    CPU path)."""
    import io
    from PIL import Image
    data = bytes(np.asarray(as_tensor(x).numpy(), np.uint8).tobytes())
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """`deformable_conv_kernel.h` — DCNv1/v2: per-position learned
    sampling offsets (+ optional modulation mask), realised as a
    bilinear gather (im2col on deformed locations) + matmul on the MXU.
    x [N,Ci,H,W]; offset [N, 2*dg*kh*kw, Ho, Wo]; weight [Co,Ci/g,kh,kw];
    mask [N, dg*kh*kw, Ho, Wo]."""
    x, offset, weight = as_tensor(x), as_tensor(offset), as_tensor(weight)
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    inputs = [x, offset, weight]
    if mask is not None:
        inputs.append(as_tensor(mask))
    if bias is not None:
        inputs.append(as_tensor(bias))
    has_mask = mask is not None
    has_bias = bias is not None

    def _fn(xa, off, w, *rest):
        m = rest[0] if has_mask else None
        b = rest[-1] if has_bias else None
        N, Ci, H, W = xa.shape
        Co, Cig, kh, kw = w.shape
        Ho = (H + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
        Wo = (W + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
        dg = off.shape[1] // (2 * kh * kw)
        off = off.reshape(N, dg, kh * kw, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * s[0] - p[0])[:, None]   # [Ho, 1]
        base_x = (jnp.arange(Wo) * s[1] - p[1])[None, :]   # [1, Wo]
        tap_y = jnp.repeat(jnp.arange(kh) * d[0], kw)      # [khkw]
        tap_x = jnp.tile(jnp.arange(kw) * d[1], kh)        # [khkw]
        # sampling locations [N, dg, kh*kw, Ho, Wo]
        sy = (tap_y[:, None, None] + base_y[None])[None, None] \
            + off[:, :, :, 0]
        sx = (tap_x[:, None, None] + base_x[None])[None, None] \
            + off[:, :, :, 1]

        def bilin(img, yy, xx):
            # img [Cd,H,W]; yy/xx [khkw,Ho,Wo] -> [Cd,khkw,Ho,Wo]
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            wy = yy - y0
            wx = xx - x0

            def g(yi, xi):
                ok = ((yi >= 0) & (yi < H) & (xi >= 0) & (xi < W))
                v = img[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
                return v * ok[None].astype(img.dtype)
            return (g(y0, x0) * ((1 - wy) * (1 - wx))[None]
                    + g(y0, x0 + 1) * ((1 - wy) * wx)[None]
                    + g(y0 + 1, x0) * (wy * (1 - wx))[None]
                    + g(y0 + 1, x0 + 1) * (wy * wx)[None])

        Cd = Ci // dg

        def per_img(img, syi, sxi, mi):
            cols = jax.vmap(
                lambda gidx: bilin(
                    jax.lax.dynamic_slice_in_dim(img, gidx * Cd, Cd, 0),
                    syi[gidx], sxi[gidx]))(jnp.arange(dg))
            # cols [dg, Cd, khkw, Ho, Wo]; DCNv2 modulation per
            # (deform-group, tap) broadcast over the group's channels
            if mi is not None:
                cols = cols * mi[:, None]
            return cols.reshape(Ci, kh * kw, Ho, Wo)
        mm = (m.reshape(N, dg, kh * kw, Ho, Wo) if m is not None
              else None)
        if mm is None:
            cols = jax.vmap(lambda img, syi, sxi: per_img(
                img, syi, sxi, None))(xa, sy, sx)
        else:
            cols = jax.vmap(per_img)(xa, sy, sx, mm)
        # grouped conv as matmul: [N,Ci,khkw,Ho,Wo] x [Co,Cig,khkw]
        wf = w.reshape(groups, Co // groups, Cig * kh * kw)
        cols = cols.reshape(N, groups, Cig, kh * kw, Ho, Wo) \
            .reshape(N, groups, Cig * kh * kw, Ho * Wo)
        out = jnp.einsum("ngkp,gok->ngop", cols, wf)
        out = out.reshape(N, Co, Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out
    return dispatch.apply("deform_conv2d", _fn, tuple(inputs))


def distribute_fpn_proposals(fpn_rois, min_level, max_level,
                             refer_level, refer_scale,
                             pixel_offset=False, rois_num=None,
                             name=None):
    """`distribute_fpn_proposals_kernel.h` — route RoIs to FPN levels
    by scale (host-side like the reference CPU kernel: ragged outputs)."""
    rois = as_tensor(fpn_rois).numpy()
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.maximum(
        (rois[:, 2] - rois[:, 0] + off)
        * (rois[:, 3] - rois[:, 1] + off), 0.0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, idxs = [], []
    for lv in range(min_level, max_level + 1):
        sel = np.where(lvl == lv)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        idxs.append(sel)
    order = np.concatenate(idxs) if idxs else np.zeros((0,), np.int64)
    restore = np.argsort(order).astype(np.int32)
    nums = [Tensor(jnp.asarray(np.asarray([len(i)], np.int32)))
            for i in idxs]
    return outs, Tensor(jnp.asarray(restore.reshape(-1, 1))), nums


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0,
               normalized=True, return_index=False, name=None):
    """`matrix_nms_kernel.h` — parallel (matrix) soft-NMS: decay each
    box by the max IoU with any higher-scored same-class box. Fully
    vectorized (the TPU-friendly NMS variant the reference runs on GPU).
    bboxes [N,M,4], scores [N,C,M]."""
    bboxes, scores = as_tensor(bboxes), as_tensor(scores)

    def _fn(bx, sc):
        N, C, M = sc.shape

        def one(b, s):
            # flatten class/box pairs, drop background
            cls_ids = jnp.arange(C)
            valid_cls = (cls_ids != background_label)[:, None]
            s = jnp.where(valid_cls & (s > score_threshold), s, 0.0)
            flat_s = s.reshape(-1)                     # [C*M]
            k = min(nms_top_k if nms_top_k > 0 else C * M, C * M)
            top_s, top_i = jax.lax.top_k(flat_s, k)
            top_c = top_i // M
            top_b = b[top_i % M]                       # [k,4]
            area = jnp.maximum(top_b[:, 2] - top_b[:, 0], 0) \
                * jnp.maximum(top_b[:, 3] - top_b[:, 1], 0)
            lt = jnp.maximum(top_b[:, None, :2], top_b[None, :, :2])
            rb = jnp.minimum(top_b[:, None, 2:], top_b[None, :, 2:])
            wh = jnp.clip(rb - lt, 0)
            inter = wh[..., 0] * wh[..., 1]
            iou = inter / jnp.maximum(area[:, None] + area[None, :]
                                      - inter, 1e-9)
            same = (top_c[:, None] == top_c[None, :])
            higher = jnp.arange(k)[None, :] < jnp.arange(k)[:, None]
            ious = jnp.where(same & higher, iou, 0.0)  # [k, k]
            max_iou = jnp.max(ious, axis=1)
            comp = jnp.max(jnp.where(same & higher,
                                     jnp.max(ious, axis=1)[None, :]
                                     * 0 + ious, 0.0), axis=1)
            if use_gaussian:
                decay = jnp.exp(-(max_iou ** 2 - 0.0)
                                / gaussian_sigma)
            else:
                decay = (1.0 - max_iou) / 1.0
            dec_s = top_s * decay
            keep = dec_s > post_threshold
            dec_s = jnp.where(keep, dec_s, 0.0)
            kk = min(keep_top_k if keep_top_k > 0 else k, k)
            fin_s, fin_i = jax.lax.top_k(dec_s, kk)
            out = jnp.concatenate(
                [top_c[fin_i].astype(b.dtype)[:, None],
                 fin_s[:, None], top_b[fin_i]], axis=1)  # [kk, 6]
            return out, top_i[fin_i], jnp.sum(fin_s > 0)
        outs, idxs, nums = jax.vmap(one)(bx, sc)
        return outs, idxs, nums
    out, idx, nums = _fn(bboxes._data, scores._data)
    if return_index:
        return Tensor(out), Tensor(idx), Tensor(nums)
    return Tensor(out), Tensor(nums)


def generate_proposals(scores, bbox_deltas, img_size, anchors,
                       variances, pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """`generate_proposals_v2_kernel.h` — RPN proposal generation:
    decode anchors, clip, filter small, NMS (host NMS like the
    reference CPU path)."""
    sc = as_tensor(scores).numpy()          # [N, A, H, W]
    bd = as_tensor(bbox_deltas).numpy()     # [N, 4A, H, W]
    ims = as_tensor(img_size).numpy()       # [N, 2]
    an = as_tensor(anchors).numpy().reshape(-1, 4)
    va = as_tensor(variances).numpy().reshape(-1, 4)
    N = sc.shape[0]
    off = 1.0 if pixel_offset else 0.0
    rois, roi_probs, nums = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)
        d = bd[n].reshape(-1, 4, sc.shape[2], sc.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], an[order % len(an)], \
            va[order % len(va)]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        acx = a[:, 0] + aw * 0.5
        acy = a[:, 1] + ah * 0.5
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        w = np.exp(np.minimum(v[:, 2] * d[:, 2], 10.0)) * aw
        h = np.exp(np.minimum(v[:, 3] * d[:, 3], 10.0)) * ah
        box = np.stack([cx - w * 0.5, cy - h * 0.5,
                        cx + w * 0.5 - off, cy + h * 0.5 - off], -1)
        ih, iw = ims[n, 0], ims[n, 1]
        box[:, 0::2] = np.clip(box[:, 0::2], 0, iw - off)
        box[:, 1::2] = np.clip(box[:, 1::2], 0, ih - off)
        ok = ((box[:, 2] - box[:, 0] + off >= min_size)
              & (box[:, 3] - box[:, 1] + off >= min_size))
        box, s = box[ok], s[ok]
        keep = _nms_single(box, s, nms_thresh)[:post_nms_top_n]
        rois.append(box[keep])
        roi_probs.append(s[keep])
        nums.append(len(keep))
    rois = Tensor(jnp.asarray(np.concatenate(rois, 0)))
    probs = Tensor(jnp.asarray(np.concatenate(roi_probs, 0)[:, None]))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(nums,
                                                          np.int32)))
    return rois, probs


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """`yolov3_loss` capability — YOLOv3 training loss (grid-cell
    responsibility assignment + box/obj/cls terms). Faithful structure,
    vectorized assignment; the reference's exact ignore-mask via best
    IoU over predictions is included."""
    x, gt_box, gt_label = as_tensor(x), as_tensor(gt_box), \
        as_tensor(gt_label)
    inputs = [x, gt_box, gt_label]
    if gt_score is not None:
        inputs.append(as_tensor(gt_score))
    na = len(anchor_mask)
    an_all = np.asarray(anchors, np.float32).reshape(-1, 2)

    def _fn(p, gb, gl, *rest):
        N, C, H, W = p.shape
        p = p.reshape(N, na, 5 + class_num, H, W)
        an = jnp.asarray(an_all[np.asarray(anchor_mask)], jnp.float32)
        stride = downsample_ratio
        # decode predictions (grid units)
        sx = jax.nn.sigmoid(p[:, :, 0])
        sy = jax.nn.sigmoid(p[:, :, 1])
        pw = jnp.exp(jnp.clip(p[:, :, 2], -10, 10)) \
            * an[None, :, 0, None, None] / (W * stride)
        ph = jnp.exp(jnp.clip(p[:, :, 3], -10, 10)) \
            * an[None, :, 1, None, None] / (H * stride)
        px = (jnp.arange(W)[None, None, None, :] + sx) / W
        py = (jnp.arange(H)[None, None, :, None] + sy) / H
        # gt: [N, B, 4] normalized cx cy w h; label [N, B]
        B = gb.shape[1]
        gw = gb[:, :, 2]
        gh = gb[:, :, 3]
        gi = jnp.clip((gb[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gb[:, :, 1] * H).astype(jnp.int32), 0, H - 1)
        # best anchor (over the FULL anchor set, like the reference)
        aw = jnp.asarray(an_all[:, 0]) / (W * stride)
        ah = jnp.asarray(an_all[:, 1]) / (H * stride)
        inter = jnp.minimum(gw[..., None], aw) \
            * jnp.minimum(gh[..., None], ah)
        iou_a = inter / (gw[..., None] * gh[..., None]
                         + aw * ah - inter + 1e-9)
        best = jnp.argmax(iou_a, axis=-1)              # [N, B]
        mask_ids = jnp.asarray(np.asarray(anchor_mask))
        resp = (best[..., None] == mask_ids)           # [N, B, na]
        valid = (gw > 1e-6)                            # real gt
        obj_t = jnp.zeros((N, na, H, W))
        tx = jnp.zeros((N, na, H, W))
        ty = jnp.zeros_like(tx)
        tw = jnp.zeros_like(tx)
        th = jnp.zeros_like(tx)
        tcls = jnp.zeros((N, na, class_num, H, W))
        bscale = jnp.zeros_like(tx)
        bidx = jnp.arange(N)[:, None, None]
        a_idx = jnp.broadcast_to(jnp.arange(na)[None, None, :],
                                 (N, B, na))
        gi_b = jnp.broadcast_to(gi[..., None], (N, B, na))
        gj_b = jnp.broadcast_to(gj[..., None], (N, B, na))
        sel = (resp & valid[..., None]).astype(jnp.float32)
        score = rest[0] if rest else jnp.ones((N, B))
        obj_t = obj_t.at[bidx, a_idx, gj_b, gi_b].max(
            sel * score[..., None])
        txv = gb[:, :, 0] * W - gi
        tyv = gb[:, :, 1] * H - gj
        twv = jnp.log(jnp.clip(
            gw[..., None] / (aw[mask_ids] + 1e-9), 1e-9, 1e9))
        thv = jnp.log(jnp.clip(
            gh[..., None] / (ah[mask_ids] + 1e-9), 1e-9, 1e9))
        scl = (2.0 - gw * gh)
        tx = tx.at[bidx, a_idx, gj_b, gi_b].max(sel * txv[..., None])
        ty = ty.at[bidx, a_idx, gj_b, gi_b].max(sel * tyv[..., None])
        tw = tw.at[bidx, a_idx, gj_b, gi_b].max(sel * twv)
        th = th.at[bidx, a_idx, gj_b, gi_b].max(sel * thv)
        bscale = bscale.at[bidx, a_idx, gj_b, gi_b].max(
            sel * scl[..., None])
        cls_oh = jax.nn.one_hot(gl, class_num)          # [N,B,cls]
        if use_label_smooth:
            delta = 1.0 / max(class_num, 1)
            cls_oh = cls_oh * (1.0 - delta) + delta / class_num
        tcls = tcls.at[bidx, a_idx[..., None].repeat(1, -1),
                       jnp.arange(class_num)[None, None, None, :],
                       gj_b[..., None], gi_b[..., None]].max(
            sel[..., None] * cls_oh[:, :, None, :])
        # ignore mask: predictions overlapping any gt above thresh
        px1 = px - pw / 2
        py1 = py - ph / 2
        px2 = px + pw / 2
        py2 = py + ph / 2
        gx1 = gb[:, :, 0] - gw / 2
        gy1 = gb[:, :, 1] - gh / 2
        gx2 = gb[:, :, 0] + gw / 2
        gy2 = gb[:, :, 1] + gh / 2
        ix1 = jnp.maximum(px1[:, :, :, :, None],
                          gx1[:, None, None, None, :])
        iy1 = jnp.maximum(py1[:, :, :, :, None],
                          gy1[:, None, None, None, :])
        ix2 = jnp.minimum(px2[:, :, :, :, None],
                          gx2[:, None, None, None, :])
        iy2 = jnp.minimum(py2[:, :, :, :, None],
                          gy2[:, None, None, None, :])
        iw_ = jnp.clip(ix2 - ix1, 0)
        ih_ = jnp.clip(iy2 - iy1, 0)
        inter = iw_ * ih_
        pa = pw * ph
        ga = (gw * gh)[:, None, None, None, :]
        iou = inter / jnp.maximum(pa[..., None] + ga - inter, 1e-9)
        iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
        best_iou = jnp.max(iou, axis=-1)
        noobj = (best_iou < ignore_thresh).astype(jnp.float32) \
            * (1.0 - obj_t)

        def bce(logit, t):
            return jnp.maximum(logit, 0) - logit * t \
                + jnp.log1p(jnp.exp(-jnp.abs(logit)))
        lx = bce(p[:, :, 0], tx) * bscale * obj_t
        ly = bce(p[:, :, 1], ty) * bscale * obj_t
        lw = jnp.abs(p[:, :, 2] - tw) * bscale * obj_t
        lh = jnp.abs(p[:, :, 3] - th) * bscale * obj_t
        lobj = bce(p[:, :, 4], obj_t) * (obj_t + noobj)
        lcls = jnp.sum(bce(p[:, :, 5:], tcls), axis=2) * obj_t
        per_img = jnp.sum(lx + ly + lw + lh + lobj + lcls,
                          axis=(1, 2, 3))
        return per_img
    return dispatch.apply("yolo_loss", _fn, tuple(inputs))
