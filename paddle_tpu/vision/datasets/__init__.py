"""Vision datasets — parity: `python/paddle/vision/datasets/`.

Zero-egress environment: when the on-disk dataset files exist (same paths
paddle uses: ~/.cache/paddle/dataset/...), they are parsed; otherwise a
deterministic synthetic dataset with the right shapes/classes is generated
so training pipelines (Model.fit, benchmarks) run unchanged.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset


class MNIST(Dataset):
    """`python/paddle/vision/datasets/mnist.py` parity."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None,
                 synthetic_size=None):
        self.mode = mode
        self.transform = transform
        base = os.path.expanduser("~/.cache/paddle/dataset/mnist")
        tag = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(
            base, f"{tag}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(
            base, f"{tag}-labels-idx1-ubyte.gz")
        if os.path.exists(image_path) and os.path.exists(label_path):
            with gzip.open(image_path, "rb") as f:
                _, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(
                    f.read(), dtype=np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                self.labels = np.frombuffer(f.read(), dtype=np.uint8)
        else:
            n = synthetic_size or (6000 if mode == "train" else 1000)
            rng = np.random.RandomState(42 if mode == "train" else 43)
            self.labels = rng.randint(0, 10, n).astype(np.uint8)
            # class-dependent blobs so a real model can actually learn
            self.images = np.zeros((n, 28, 28), np.uint8)
            for i, lab in enumerate(self.labels):
                img = rng.rand(28, 28) * 64
                r, c = divmod(int(lab), 4)
                img[r * 7:(r + 1) * 7 + 7, c * 7:(c + 1) * 7] += 160
                self.images[i] = np.clip(img, 0, 255).astype(np.uint8)
        self.images = self.images.astype(np.float32) / 255.0
        self.labels = self.labels.astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx][None]  # [1, 28, 28]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        self.transform = transform
        n = synthetic_size or (5000 if mode == "train" else 1000)
        rng = np.random.RandomState(7 if mode == "train" else 8)
        self.labels = rng.randint(0, 10, n).astype(np.int64)
        self.images = (rng.rand(n, 3, 32, 32) * 255).astype(np.uint8)
        for i, lab in enumerate(self.labels):
            self.images[i, lab % 3, :, :] = np.clip(
                self.images[i, lab % 3].astype(np.int32) + 60, 0, 255)
        self.images = self.images.astype(np.float32) / 255.0

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        super().__init__(data_file, mode, transform, download, backend,
                         synthetic_size)
        rng = np.random.RandomState(9)
        self.labels = rng.randint(0, 100, len(self.images)).astype(np.int64)


class FakeImageNet(Dataset):
    """Synthetic ImageNet-shaped dataset for the ResNet-50 benchmark."""

    def __init__(self, size=1024, image_shape=(3, 224, 224),
                 num_classes=1000, mode="train"):
        rng = np.random.RandomState(0)
        self.size = size
        self.image_shape = image_shape
        self.num_classes = num_classes
        self._base = rng.rand(64, *image_shape).astype(np.float32)
        self._labels = rng.randint(0, num_classes, size).astype(np.int64)

    def __getitem__(self, idx):
        return (self._base[idx % 64],
                np.array([self._labels[idx]], dtype=np.int64))

    def __len__(self):
        return self.size


class DatasetFolder(Dataset):
    """`paddle.vision.datasets.DatasetFolder`: class-per-subdirectory
    sample tree (`python/paddle/vision/datasets/folder.py`). `loader`
    defaults to a numpy image reader (PIL if importable, else raw
    `np.load`/byte-shape heuristics kept simple)."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.loader = loader or self._default_loader
        self.samples = [
            (p, self.class_to_idx[c]) for c in classes
            for p in self._scan(os.path.join(root, c), extensions,
                                is_valid_file)]
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError as e:
            raise RuntimeError(
                "DatasetFolder default loader needs PIL for image "
                "files; pass loader= or use .npy samples") from e

    @staticmethod
    def _scan(root, extensions, is_valid_file):
        import os
        exts = tuple(e.lower() for e in (
            extensions or (".jpg", ".jpeg", ".png", ".bmp", ".npy")))
        for dirpath, _, names in sorted(os.walk(root)):
            for n in sorted(names):
                p = os.path.join(dirpath, n)
                ok = (is_valid_file(p) if is_valid_file
                      else n.lower().endswith(exts))
                if ok:
                    yield p

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([target], np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """`paddle.vision.datasets.ImageFolder`: flat/recursive image list
    WITHOUT labels (samples are just images)."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        self.samples = list(DatasetFolder._scan(root, extensions,
                                                is_valid_file))
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return (img,)

    def __len__(self):
        return len(self.samples)
