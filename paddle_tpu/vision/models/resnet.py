"""ResNet family — parity: `python/paddle/vision/models/resnet.py`
(ResNet-18/34/50/101/152, wide variants, resnext). BASELINE config 2.

Layout: the model is written NCHW; under PADDLE_TPU_LAYOUT_AUTOTUNE
(default on) the whole conv/BN/pool interior runs physically NHWC via
the tag-propagation pass in core/layout.py — no model changes needed,
one transpose per graph edge. PADDLE_TPU_S2D_STEM=1 additionally
rewrites conv1 (3-channel 7x7/s2, ~3% MXU utilization at C=3) into an
equivalent space-to-depth 12-channel 4x4/s1 conv inside the traced
step (docs/layout_analysis.md); checkpoint layout is unchanged.
"""
from __future__ import annotations

from ... import nn


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        self.conv1 = nn.Conv2D(inplanes, planes, 3, padding=1,
                               stride=stride, bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1,
                               bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups,
                               dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self.inplanes = 64
        self.dilation = 1
        self.conv1 = nn.Conv2D(3, self.inplanes, kernel_size=7, stride=2,
                               padding=3, bias_attr=False)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1, dilate=False):
        norm_layer = self._norm_layer
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                norm_layer(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width, self.dilation,
                        norm_layer)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width,
                                norm_layer=norm_layer))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def _resnet(block, depth, **kwargs):
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 50, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 101, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    kwargs["groups"] = 32
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, 50, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    kwargs["groups"] = 32
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, 101, **kwargs)
