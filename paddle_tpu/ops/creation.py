"""Tensor creation ops.

Parity: `python/paddle/tensor/creation.py` (to_tensor, zeros, ones, full,
arange, linspace, eye, tril/triu, meshgrid, assign, …) backed by PHI
full/arange kernels (`paddle/phi/kernels/full_kernel.h`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor
from ._helpers import as_tensor, unary


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        shape = [shape]
    return [int(s) for s in shape]


def zeros(shape, dtype=None):
    dt = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
    return Tensor(jnp.zeros(_shape_list(shape), dt))


def ones(shape, dtype=None):
    dt = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
    return Tensor(jnp.ones(_shape_list(shape), dt))


def full(shape, fill_value, dtype=None):
    dt = dtype_mod.convert_dtype(dtype)
    if dt is None:
        if isinstance(fill_value, bool):
            dt = dtype_mod.bool_
        elif isinstance(fill_value, int):
            dt = dtype_mod.convert_dtype("int64")
        else:
            dt = dtype_mod.get_default_dtype()
    return Tensor(jnp.full(_shape_list(shape), fill_value, dt))


def empty(shape, dtype=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None):
    x = as_tensor(x)
    dt = dtype_mod.convert_dtype(dtype) or x.dtype
    return Tensor(jnp.zeros(x._data.shape, dt))


def ones_like(x, dtype=None):
    x = as_tensor(x)
    dt = dtype_mod.convert_dtype(dtype) or x.dtype
    return Tensor(jnp.ones(x._data.shape, dt))


def full_like(x, fill_value, dtype=None):
    x = as_tensor(x)
    dt = dtype_mod.convert_dtype(dtype) or x.dtype
    return Tensor(jnp.full(x._data.shape, fill_value, dt))


empty_like = zeros_like


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, Tensor):
            v = v.item()
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    dt = dtype_mod.convert_dtype(dtype)
    if dt is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dt = dtype_mod.convert_dtype("int64")
        else:
            dt = dtype_mod.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None):
    dt = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    return Tensor(jnp.linspace(start, stop, int(num), dtype=dt))


def eye(num_rows, num_columns=None, dtype=None):
    dt = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
    return Tensor(jnp.eye(int(num_rows), num_columns and int(num_columns),
                          dtype=dt))


def diag(x, offset=0, padding_value=0):
    x = as_tensor(x)

    def _fn(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.eye(*out.shape, k=offset, dtype=bool)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(a, offset=offset)
    return unary("diag", _fn, x)


def diagonal(x, offset=0, axis1=0, axis2=1):
    return unary("diagonal",
                 lambda a: jnp.diagonal(a, offset, axis1, axis2),
                 as_tensor(x))


def tril(x, diagonal=0):
    return unary("tril", lambda a: jnp.tril(a, diagonal), as_tensor(x))


def triu(x, diagonal=0):
    return unary("triu", lambda a: jnp.triu(a, diagonal), as_tensor(x))


def meshgrid(*args):
    args = [as_tensor(a) for a in (args[0] if len(args) == 1 and
            isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*[a._data for a in args], indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    x = as_tensor(x)
    out = unary("assign", lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.number) else a, x)
    if output is not None:
        output._data = out._data
        output._grad_node = out._grad_node
        output._out_slot = out._out_slot
        output.stop_gradient = out.stop_gradient
        output._layout = out._layout
        return output
    return out


def clone(x):
    return assign(x)


def numel(x):
    return Tensor(np.int64(as_tensor(x).size))
