"""Shape / layout / gather-scatter manipulation ops.

Parity: `python/paddle/tensor/manipulation.py` over PHI kernels
(`paddle/phi/kernels/reshape_kernel.h`, `transpose_kernel.h`,
`concat_kernel.h`, `gather_kernel.h`, `scatter_kernel.h`, …). All lower to
XLA reshape/transpose/gather/scatter HLOs.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

_py_slice = builtins.slice

from ..core import dispatch
from ..core import dtype as dtype_mod
from ..core.tensor import Tensor
from ._helpers import as_tensor, unary, norm_axis


def cast(x, dtype):
    x = as_tensor(x)
    dt = dtype_mod.convert_dtype(dtype)
    if x.dtype == dt:
        return x
    return unary("cast", lambda a: a.astype(dt), x)


def reshape(x, shape, name=None):
    x = as_tensor(x)
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    shape = [int(s) for s in shape]
    return unary("reshape", lambda a: jnp.reshape(a, shape), x)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = as_tensor(x)
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    ea = stop_axis % nd if nd else 0

    def _fn(a):
        new_shape = (list(a.shape[:sa]) + [-1] + list(a.shape[ea + 1:]))
        return jnp.reshape(a, new_shape)
    return unary("flatten", _fn, x)


def squeeze(x, axis=None, name=None):
    x = as_tensor(x)
    ax = norm_axis(axis)

    def _fn(a):
        if ax is None:
            return jnp.squeeze(a)
        axes = ax if isinstance(ax, tuple) else (ax,)
        axes = tuple(a_ % a.ndim for a_ in axes)
        axes = tuple(i for i in axes if a.shape[i] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a
    return unary("squeeze", _fn, x)


def unsqueeze(x, axis, name=None):
    x = as_tensor(x)
    ax = norm_axis(axis)
    axes = ax if isinstance(ax, tuple) else (ax,)
    return unary("unsqueeze", lambda a: jnp.expand_dims(a, axes), x)


def transpose(x, perm, name=None):
    x = as_tensor(x)
    perm = [int(p) for p in perm]
    return unary("transpose", lambda a: jnp.transpose(a, perm), x)


def moveaxis(x, source, destination, name=None):
    return unary("moveaxis",
                 lambda a: jnp.moveaxis(a, source, destination), as_tensor(x))


def swapaxes(x, axis0, axis1, name=None):
    return unary("swapaxes",
                 lambda a: jnp.swapaxes(a, axis0, axis1), as_tensor(x))


def concat(x, axis=0, name=None):
    ts = [as_tensor(t) for t in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return dispatch.apply(
        "concat", lambda *arrs: jnp.concatenate(arrs, axis=axis), tuple(ts))


def stack(x, axis=0, name=None):
    ts = [as_tensor(t) for t in x]
    return dispatch.apply(
        "stack", lambda *arrs: jnp.stack(arrs, axis=axis), tuple(ts))


def split(x, num_or_sections, axis=0, name=None):
    x = as_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        n = num_or_sections
        sizes = [dim // n] * n
    else:
        sizes = [int(s) if not isinstance(s, Tensor) else int(s.item())
                 for s in num_or_sections]
        neg = [i for i, s in enumerate(sizes) if s < 0]
        if neg:
            sizes[neg[0]] = dim - builtins.sum(s for s in sizes if s >= 0)
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def _fn(a):
        return tuple(
            jax.lax.slice_in_dim(a, off, off + sz, axis=axis)
            for off, sz in zip(offsets, sizes)
        )
    out = dispatch.apply("split", _fn, (x,))
    return list(out)


def builtins_sum(it):
    total = 0
    for v in it:
        total += v
    return total


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    x = as_tensor(x)
    n = x.shape[axis]
    outs = split(x, n, axis)
    return [squeeze(o, axis) for o in outs]


def tile(x, repeat_times, name=None):
    x = as_tensor(x)
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.tolist()
    reps = [int(r) for r in repeat_times]
    return unary("tile", lambda a: jnp.tile(a, reps), x)


def expand(x, shape, name=None):
    x = as_tensor(x)
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    tgt = [int(s) for s in shape]

    def _fn(a):
        shp = list(a.shape)
        full = list(tgt)
        # paddle: -1 means keep original dim
        pad = len(full) - len(shp)
        for i, s in enumerate(full):
            if s == -1:
                full[i] = shp[i - pad] if i >= pad else 1
        return jnp.broadcast_to(a, full)
    return unary("expand", _fn, x)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def expand_as(x, y, name=None):
    return expand(x, as_tensor(y).shape)


def broadcast_tensors(inputs, name=None):
    ts = [as_tensor(t) for t in inputs]
    out = dispatch.apply(
        "broadcast_tensors",
        lambda *arrs: tuple(jnp.broadcast_arrays(*arrs)), tuple(ts))
    return list(out)


def flip(x, axis, name=None):
    ax = norm_axis(axis)
    return unary("flip", lambda a: jnp.flip(a, axis=ax), as_tensor(x))


def roll(x, shifts, axis=None, name=None):
    ax = norm_axis(axis)
    return unary("roll", lambda a: jnp.roll(a, shifts, axis=ax), as_tensor(x))


def rot90(x, k=1, axes=(0, 1), name=None):
    return unary("rot90", lambda a: jnp.rot90(a, k, axes), as_tensor(x))


# ----------------------------------------------------------- gather family


def gather(x, index, axis=0, name=None):
    """paddle.gather: select rows of `axis` by 1-D index."""
    x, index = as_tensor(x), as_tensor(index)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    ax = int(axis)

    def _fn(a, idx):
        return jnp.take(a, idx, axis=ax)
    return dispatch.apply("gather", _fn, (x, index))


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def gather_nd(x, index, name=None):
    x, index = as_tensor(x), as_tensor(index)

    def _fn(a, idx):
        return a[tuple(jnp.moveaxis(idx, -1, 0))]
    return dispatch.apply("gather_nd", _fn, (x, index))


def take_along_axis(arr, indices, axis, name=None):
    arr, indices = as_tensor(arr), as_tensor(indices)

    def _fn(a, idx):
        return jnp.take_along_axis(a, idx, axis=axis)
    return dispatch.apply("take_along_axis", _fn, (arr, indices))


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    arr, indices = as_tensor(arr), as_tensor(indices)
    values = as_tensor(values, dtype=arr.dtype)

    def _fn(a, idx, v):
        v = jnp.broadcast_to(v, idx.shape)
        if reduce == "assign":
            return jnp.put_along_axis(a, idx, v, axis=axis, inplace=False)
        # add/multiply via scatter
        dims = list(range(a.ndim))
        idx_full = [jnp.broadcast_to(
            jnp.arange(a.shape[d]).reshape(
                [-1 if i == d else 1 for i in dims]), idx.shape)
            for d in dims]
        idx_full[axis] = idx
        flat_idx = tuple(idx_full)
        if reduce == "add":
            return a.at[flat_idx].add(v)
        if reduce in ("multiply", "mul"):
            return a.at[flat_idx].multiply(v)
        raise ValueError(f"unknown reduce {reduce}")
    return dispatch.apply("put_along_axis", _fn, (arr, indices, values))


def scatter(x, index, updates, overwrite=True, name=None):
    """paddle.scatter: write rows of `updates` at `index` (1-D)."""
    x, index, updates = as_tensor(x), as_tensor(index), as_tensor(updates)

    def _fn(a, idx, upd):
        if overwrite:
            return a.at[idx].set(upd)
        return a.at[idx].add(upd)
    return dispatch.apply("scatter", _fn, (x, index, updates))


def scatter_nd_add(x, index, updates, name=None):
    x, index, updates = as_tensor(x), as_tensor(index), as_tensor(updates)

    def _fn(a, idx, upd):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return dispatch.apply("scatter_nd_add", _fn, (x, index, updates))


def scatter_nd(index, updates, shape, name=None):
    index, updates = as_tensor(index), as_tensor(updates)
    shape = [int(s) for s in (shape.tolist() if isinstance(shape, Tensor)
                              else shape)]

    def _fn(idx, upd):
        zeros = jnp.zeros(shape, upd.dtype)
        return zeros.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return dispatch.apply("scatter_nd", _fn, (index, updates))


def slice(x, axes, starts, ends, name=None):
    x = as_tensor(x)
    axes = [int(a) for a in axes]
    starts = [int(s.item()) if isinstance(s, Tensor) else int(s)
              for s in starts]
    ends = [int(e.item()) if isinstance(e, Tensor) else int(e) for e in ends]

    def _fn(a):
        idx = [_py_slice(None)] * a.ndim
        for ax, st, en in zip(axes, starts, ends):
            idx[ax] = _py_slice(st, en)
        return a[tuple(idx)]
    return unary("slice", _fn, x)


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = as_tensor(x)

    def _fn(a):
        idx = [_py_slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[int(ax)] = _py_slice(int(st), int(en), int(sd))
        return a[tuple(idx)]
    return unary("strided_slice", _fn, x)


# -------------------------------------------------------------- searching


def where(condition, x=None, y=None, name=None):
    condition = as_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    x, y = as_tensor(x), as_tensor(y)

    def _fn(c, a, b):
        return jnp.where(c, a, b)
    return dispatch.apply("where", _fn, (condition, x, y))


def nonzero(x, as_tuple=False, name=None):
    x = as_tensor(x)
    idx = jnp.nonzero(x._data)  # dynamic shape: eager-only
    if as_tuple:
        return tuple(Tensor(i.reshape(-1, 1)) for i in idx)
    return Tensor(jnp.stack(idx, axis=1).astype(dtype_mod.convert_dtype("int64")))


def masked_select(x, mask, name=None):
    x, mask = as_tensor(x), as_tensor(mask)
    return Tensor(x._data[mask._data])


def masked_fill(x, mask, value, name=None):
    x, mask = as_tensor(x), as_tensor(mask)
    v = float(value.item()) if isinstance(value, Tensor) else value

    def _fn(a, m):
        return jnp.where(m, jnp.asarray(v, a.dtype), a)
    return dispatch.apply("masked_fill", _fn, (x, mask))


def sort(x, axis=-1, descending=False, name=None):
    x = as_tensor(x)

    def _fn(a):
        out = jnp.sort(a, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out
    return unary("sort", _fn, x)


def argsort(x, axis=-1, descending=False, name=None):
    x = as_tensor(x)

    def _fn(a):
        out = jnp.argsort(a, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out.astype(dtype_mod.convert_dtype("int64"))
    return unary("argsort", _fn, x, differentiable=False)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = as_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())

    def _fn(a):
        ax = axis % a.ndim
        am = jnp.moveaxis(a, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(am, k)
        else:
            vals, idx = jax.lax.top_k(-am, k)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx, -1, ax).astype(dtype_mod.convert_dtype("int64")))
    return dispatch.apply("topk", _fn, (x,))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    x = as_tensor(x)
    res = jnp.unique(x._data, return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def repeat_interleave(x, repeats, axis=None, name=None):
    x = as_tensor(x)
    if isinstance(repeats, Tensor):
        repeats = repeats._data

    def _fn(a):
        return jnp.repeat(a, repeats, axis=axis)
    return unary("repeat_interleave", _fn, x)


def index_sample(x, index):
    x, index = as_tensor(x), as_tensor(index)

    def _fn(a, idx):
        return jnp.take_along_axis(a, idx, axis=1)
    return dispatch.apply("index_sample", _fn, (x, index))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    input = as_tensor(input)
    size = index_num // nshards

    def _fn(a):
        shard = a // size
        return jnp.where(shard == shard_id, a % size, ignore_value)
    return unary("shard_index", _fn, input, differentiable=False)


# ------------------------------------------------------------- indexing


def _conv_index(i):
    if isinstance(i, Tensor):
        return i._data
    if isinstance(i, (list, np.ndarray)):
        return jnp.asarray(i)
    return i


def getitem(x, idx):
    x = as_tensor(x)
    if isinstance(idx, tuple):
        jidx = tuple(_conv_index(i) for i in idx)
    else:
        jidx = _conv_index(idx)
    has_dyn = isinstance(jidx, jax.Array) and jidx.dtype == jnp.bool_ or (
        isinstance(jidx, tuple)
        and any(isinstance(i, jax.Array) and i.dtype == jnp.bool_
                for i in jidx))
    if has_dyn:
        # boolean masks produce dynamic shapes: eager-only, no grad.
        # This path reads x._data directly, so it must materialize a
        # tagged (physically-NHWC) tensor itself — the mask is logical
        from ..core import layout as _layout
        x = _layout.materialize(x)
        return Tensor(x._data[jidx])
    return unary("getitem", lambda a: a[jidx], x)


def setitem(x, idx, value):
    x = as_tensor(x)
    value = as_tensor(value, dtype=x.dtype) if not np.isscalar(value) \
        else value
    if isinstance(idx, tuple):
        jidx = tuple(_conv_index(i) for i in idx)
    else:
        jidx = _conv_index(idx)
    if np.isscalar(value):
        return unary("setitem", lambda a: a.at[jidx].set(value), x)

    def _fn(a, v):
        return a.at[jidx].set(v.astype(a.dtype))
    return dispatch.apply("setitem", _fn, (x, value))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """paddle.nn.functional.pad semantics (PHI pad kernels)."""
    from ..core import layout as _layout
    x = as_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim
    # layout propagation: pad the tagged (physically NHWC) array in
    # place — widths are computed in logical NCHW terms, then permuted
    tagged = (x._layout is not None and data_format == "NCHW"
              and _layout.enabled())
    if x._layout is not None and not tagged:
        x = _layout.materialize(x)

    def _fn(a):
        if len(pad) == 2 * nd:
            # paddle "pad" op layout: per-dim (before, after), dim order 0..n
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # partial spec applies to last dims (torch-style), respecting
            # data_format for 3/4/5-D inputs
            n_spec = len(pad) // 2
            widths = [(0, 0)] * nd
            if data_format.startswith("N") and data_format.endswith("C"):
                dims = list(range(1, 1 + n_spec))
            else:
                dims = list(range(nd - n_spec, nd))
            for j, d in enumerate(reversed(dims)):
                widths[d] = (pad[2 * j], pad[2 * j + 1])
        if tagged:
            widths = [widths[i] for i in _layout.TO_NHWC_PERM]
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, widths, mode=jmode, constant_values=value)
        return jnp.pad(a, widths, mode=jmode)
    out = unary("pad", _fn, x)
    if tagged:
        out._layout = _layout.NHWC
    return out


def shape(x):
    return Tensor(np.array(as_tensor(x).shape, dtype=np.int32))


def one_hot(x, num_classes, name=None):
    x = as_tensor(x)
    return unary("one_hot",
                 lambda a: jax.nn.one_hot(a, num_classes,
                                          dtype=jnp.float32), x,
                 differentiable=False)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    sorted_sequence, values = as_tensor(sorted_sequence), as_tensor(values)
    side = "right" if right else "left"
    dt = jnp.int32 if out_int32 else dtype_mod.convert_dtype("int64")

    def _fn(s, v):
        if s.ndim == 1:
            return jnp.searchsorted(s, v, side=side).astype(dt)
        # batched: apply along last dim
        return jax.vmap(
            lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(
                s.reshape(-1, s.shape[-1]),
                v.reshape(-1, v.shape[-1])).reshape(v.shape).astype(dt)
    return dispatch.apply("searchsorted", _fn,
                          (sorted_sequence, values))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def histogram(input, bins=100, min=0, max=0, name=None):
    input = as_tensor(input)
    arr = input._data
    lo, hi = (float(min), float(max))
    if lo == 0 and hi == 0:
        lo = float(jnp.min(arr))
        hi = float(jnp.max(arr))
    hist, _ = jnp.histogram(arr, bins=bins, range=(lo, hi))
    return Tensor(hist.astype(dtype_mod.convert_dtype("int64")))


def bincount(x, weights=None, minlength=0, name=None):
    x = as_tensor(x)
    arr = x._data
    if arr.size and int(jnp.min(arr)) < 0:
        raise ValueError("bincount requires non-negative inputs "
                         "(reference semantics)")
    n = builtins.max(int(jnp.max(arr)) + 1 if arr.size else 0,
                     int(minlength))
    w = as_tensor(weights)._data if weights is not None else None
    return Tensor(jnp.bincount(arr, weights=w, length=n))


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    x = as_tensor(x)
    idt = dtype_mod.convert_dtype(dtype)
    arr = np.asarray(x.numpy())
    if axis is None:
        arr = arr.reshape(-1)
    else:
        arr = np.moveaxis(arr, int(axis), 0)
    keep = np.ones(len(arr), bool)
    keep[1:] = arr[1:] != arr[:-1] if arr.ndim == 1 else \
        (arr[1:] != arr[:-1]).any(axis=tuple(range(1, arr.ndim)))
    uniq = arr[keep]
    if axis is not None:
        uniq = np.moveaxis(uniq, 0, int(axis))
    out = [Tensor(uniq)]
    if return_inverse:
        out.append(Tensor((np.cumsum(keep) - 1).astype(idt)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, len(arr)))
        out.append(Tensor(counts.astype(idt)))
    return out[0] if len(out) == 1 else tuple(out)


def as_strided(x, shape, stride, offset=0, name=None):
    raise NotImplementedError(
        "as_strided has no XLA equivalent; use reshape/slice/gather")
