"""paddle_tpu.ops — the functional op library (PHI-kernel-layer parity).

Every op is a thin pure-jax function dispatched through
`paddle_tpu.core.dispatch.apply`, which records eager autograd nodes. This
package plays the role of the reference's PHI kernel library
(`paddle/phi/kernels/`) + generated C++ API (`paddle/phi/api/`): XLA is the
actual kernel backend.
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .random_ops import *  # noqa: F401,F403
from .extras2 import *  # noqa: F401,F403

from . import creation, math, logic, manipulation, linalg, random_ops  # noqa


def _bind_tensor_methods():
    """Attach op functions as Tensor methods (parity:
    `python/paddle/tensor/__init__.py` method-patching of the pybind Tensor
    via `math_op_patch.py` / monkey_patch_math_varbase)."""
    import inspect
    from ..core.tensor import Tensor

    skip = {"to_tensor", "zeros", "ones", "full", "arange", "linspace",
            "eye", "meshgrid", "rand", "randn", "randint", "randperm",
            "uniform", "normal", "standard_normal", "empty", "einsum",
            "assign"}
    first_arg_names = {"x", "input", "arr", "tensor", "x1", "condition"}
    for mod in (math, logic, manipulation, linalg, creation, random_ops):
        for name, fn in vars(mod).items():
            if name.startswith("_") or not callable(fn) or name in skip:
                continue
            if not inspect.isfunction(fn):
                continue
            try:
                params = list(inspect.signature(fn).parameters)
            except (TypeError, ValueError):
                continue
            if not params or params[0] not in first_arg_names:
                continue
            if hasattr(Tensor, name):
                continue
            setattr(Tensor, name, fn)


_bind_tensor_methods()
from .extras3 import *  # noqa: F401,F403
