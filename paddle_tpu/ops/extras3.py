"""Third batch of tensor-namespace ops (round-5 kernel-family coverage).

Parity: `paddle/phi/kernels/{diag_embed,frame,overlap_add,edit_distance,
accuracy,fill_diagonal,uniform_random_inplace}_kernel.h` — pure-jax
programs; signal ops (frame/overlap_add) are strided gathers/scatter-adds
XLA vectorizes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core import dispatch
from ._helpers import as_tensor


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """`diag_embed_kernel.h` — last-dim vectors -> diagonal planes."""
    x = as_tensor(input)

    def f(a):
        n = a.shape[-1]
        size = n + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (size, size), a.dtype)
        idx = jnp.arange(n)
        r = idx + max(0, -offset)
        c = idx + max(0, offset)
        out = out.at[..., r, c].set(a)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        order = []
        src = iter(perm)
        for i in range(nd):
            if i == d1:
                order.append(nd - 2)
            elif i == d2:
                order.append(nd - 1)
            else:
                order.append(next(src))
        return out.transpose(order)
    return dispatch.apply("diag_embed", f, (x,))


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """`frame_kernel.h` — sliding windows over the signal axis.
    axis=-1: [..., T] -> [..., frame_length, n_frames]."""
    x = as_tensor(x)

    def f(a):
        T = a.shape[axis]
        n = 1 + (T - frame_length) // hop_length
        starts = jnp.arange(n) * hop_length
        offs = jnp.arange(frame_length)
        gather = starts[None, :] + offs[:, None]       # [fl, n]
        if axis in (-1, a.ndim - 1):
            return a[..., gather]
        # axis 0: [T, ...] -> [fl, n, ...] per reference layout
        return jnp.moveaxis(a[gather.T], (0, 1), (1, 0))
    return dispatch.apply("frame", f, (x,))


def overlap_add(x, hop_length, axis=-1, name=None):
    """`overlap_add_kernel.h` — inverse of frame (scatter-add)."""
    x = as_tensor(x)

    def f(a):
        if axis in (-1, a.ndim - 1):
            fl, n = a.shape[-2], a.shape[-1]
            T = (n - 1) * hop_length + fl
            out = jnp.zeros(a.shape[:-2] + (T,), a.dtype)
            pos = (jnp.arange(n) * hop_length)[None, :] \
                + jnp.arange(fl)[:, None]
            return out.at[..., pos].add(a)
        fl, n = a.shape[0], a.shape[1]
        T = (n - 1) * hop_length + fl
        out = jnp.zeros((T,) + a.shape[2:], a.dtype)
        pos = (jnp.arange(n) * hop_length)[None, :] \
            + jnp.arange(fl)[:, None]
        return out.at[pos].add(a)
    return dispatch.apply("overlap_add", f, (x,))


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """`edit_distance_kernel.h` — batched Levenshtein distance via a
    wavefront lax.scan DP (static shapes; lengths mask the tails).
    Returns (distance [B,1] f32, sequence_num [1])."""
    inp, lab = as_tensor(input), as_tensor(label)
    args = [inp, lab]
    if input_length is not None:
        args.append(as_tensor(input_length))
    if label_length is not None:
        args.append(as_tensor(label_length))

    def f(a, b, *lens):
        B, N = a.shape
        M = b.shape[1]
        alen = lens[0].reshape(-1) if lens else jnp.full((B,), N)
        blen = (lens[1].reshape(-1) if len(lens) > 1
                else jnp.full((B,), M))

        def one_full(av, bv, an, bn):
            # full DP table (N+1 rows) so we can read D[an, bn]
            row0 = jnp.arange(M + 1, dtype=jnp.float32)

            def step(prev, i):
                ai = av[i]

                def inner(carry, j):
                    left = carry
                    sub = prev[j] + jnp.where(ai == bv[j], 0.0, 1.0)
                    cur = jnp.minimum(jnp.minimum(prev[j + 1] + 1.0,
                                                  left + 1.0), sub)
                    return cur, cur
                _, rest = jax.lax.scan(inner, i + 1.0, jnp.arange(M))
                row = jnp.concatenate([jnp.array([i + 1.0]), rest])
                return row, row
            _, rows = jax.lax.scan(step, row0, jnp.arange(N))
            table = jnp.concatenate([row0[None], rows])  # [N+1, M+1]
            return table[an, bn]

        d = jax.vmap(one_full)(a, b, alen, blen)
        if normalized:
            d = d / jnp.maximum(blen.astype(jnp.float32), 1.0)
        return d.reshape(B, 1), jnp.array([B], jnp.int32)
    return dispatch.apply("edit_distance", f, tuple(args),
                          differentiable=False)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """`accuracy_kernel.h` — top-k accuracy over a batch."""
    inp, lab = as_tensor(input), as_tensor(label)

    def f(p, y):
        topk = jnp.argsort(-p, axis=-1)[:, :k]
        hit = jnp.any(topk == y.reshape(-1, 1), axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    return dispatch.apply("accuracy", f, (inp, lab),
                          differentiable=False)


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    """`uniform_random_inplace_kernel.h` — Tensor.uniform_()."""
    from ..core import random as rng
    key = jax.random.key(seed) if seed else rng.next_key()
    x._data = jax.random.uniform(key, x._data.shape,
                                 jnp.float32 if x._data.dtype
                                 not in (jnp.float64,) else x._data.dtype,
                                 min, max).astype(x._data.dtype)
    return x


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    """`fill_diagonal_kernel.h` — in-place diagonal fill."""
    a = x._data
    n = min(a.shape[-2], a.shape[-1])
    idx = jnp.arange(n - abs(offset))
    r = idx + max(0, -offset)
    c = idx + max(0, offset)
    x._data = a.at[..., r, c].set(value)
    return x


def identity_loss(x, reduction="none", name=None):
    """`identity_loss_kernel.h` (IPU-origin marker op): reduce or pass
    through the input as the loss value."""
    x = as_tensor(x)
    red = {0, "sum"}, {1, "mean"}
    if reduction in red[1]:
        return dispatch.apply("identity_loss", jnp.mean, (x,))
    if reduction in red[0]:
        return dispatch.apply("identity_loss", jnp.sum, (x,))
    return dispatch.apply("identity_loss", lambda a: a, (x,))


Tensor.uniform_ = uniform_
Tensor.fill_diagonal_ = fill_diagonal_
