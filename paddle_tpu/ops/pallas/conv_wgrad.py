"""Pallas split-K conv weight-gradient kernel — a measured NEGATIVE result.

Context (VERDICT r4 #3, docs/resnet50_perf_analysis.md): ResNet-50's
weight-grad convs run at 37% MXU under XLA's conv emitter. The 1x1-conv
weight grads are the largest class (5.7 of 11.6 ms/step at B=128): they
are tall-skinny split-K matmuls — dW[Ci,Co] = x[N,Ci]^T @ dy[N,Co] with
N = B*H*W up to 401k and outputs as small as 256x64, a shape where a
single output tile serializes the whole contraction.

This module implements the obvious TPU answer — a Pallas split-K kernel
(grid over N-chunks, f32 accumulator revisited across sequential grid
steps) — and it LOSES to XLA's own dot_general at equal layouts:

    [N=401408, Ci=256, Co=64] bf16 (v5e, r5):
      XLA dot_general (standalone)   278 us   (~bandwidth floor: 312 us)
      pallas split-K, Nc=2048        373 us
      pallas split-K, Nc=4096        362 us
      pallas split-K, Nc=8192        363 us
      in-model wgrad fusion          615 us

XLA's standalone matmul is already AT the HBM roofline for this shape;
the in-model 2.2x gap comes from the channel-minor NCHW feature layouts
({1,0,3,2}) the rest of the net prefers — the wgrad fusion pays an
internal relayout, which a custom kernel cannot avoid either (it would
just move the copy in front of the kernel; forcing NHWC model-wide was
measured flat in r3, docs/resnet50_perf_analysis.md "channels-last").

The kernel is kept (a) as the committed artifact of the experiment and
(b) because the split-K pattern is the right building block if a future
XLA version regresses; `wgrad_1x1` is correct and tested (interpret
mode) but NOT wired into the conv backward path — XLA wins.

Reference for what the CUDA side does about the same problem:
`paddle/phi/kernels/gpudnn/conv_kernel.cu:1` (exhaustive cudnn algo
search over precomputed workspaces).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def wgrad_1x1(x, dy, *, chunk=4096, interpret=False):
    """dW[Ci,Co] (f32) = x[N,Ci]^T @ dy[N,Co] via split-K Pallas.

    N must be divisible by `chunk`. Sequential grid steps revisit the
    single output block, accumulating partial [Ci,Co] products in f32.
    """
    N, Ci = x.shape
    _, Co = dy.shape
    if N % chunk != 0:
        raise ValueError(f"N={N} not divisible by chunk={chunk}")

    def kern(x_ref, dy_ref, o_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            o_ref[...] = jnp.zeros_like(o_ref)
        o_ref[...] += jax.lax.dot_general(
            x_ref[...], dy_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((Ci, Co), jnp.float32),
        grid=(N // chunk,),
        in_specs=[pl.BlockSpec((chunk, Ci), lambda i: (i, 0)),
                  pl.BlockSpec((chunk, Co), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((Ci, Co), lambda i: (0, 0)),
        interpret=interpret)(x, dy)
