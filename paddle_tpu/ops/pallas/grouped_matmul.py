"""Grouped-expert matmul Pallas kernel for the MoE capacity buffers.

The MoE serving hot path (ISSUE 10) runs every expert's FFN on its
fixed `[C, d]` capacity buffer. The XLA path expresses the whole block
as one-hot einsums (`moe_utils.dispatch_tokens` / `combine_tokens` +
`einsum("ecd,edf->ecf")`), which materializes `[T, k, C]`/`[T, k, E]`
masks and leaves the per-expert matmuls to the compiler's batching.
This kernel grids DIRECTLY over (expert, C-tile, F-tile) with a
sequential d-reduction axis, so each expert's capacity buffer hits the
MXU as dense tiles:

* grid `(E, C/bc, F/bf, D/bd)` — the leading three axes are
  embarrassingly parallel (`dimension_semantics`), the trailing
  reduction axis carries a VMEM fp32 accumulator;
* int8 weight-only experts dequantize INSIDE the kernel: the
  per-(expert, out-channel) scale tile rides the same (e, f) index
  map as the weight tile and multiplies it right after the load —
  the weight is read from HBM as int8, exactly like `_mm`'s fused
  dequant on the dense path;
* tile sizes `(block_c, block_f, block_d)` are TUNABLE
  (`ops.pallas.autotune`, kernel name ``grouped_matmul``) — the
  einsum path stays the CPU oracle and the fallback for shapes the
  gate refuses.

The companion index-based dispatch/combine (no one-hot
materialization) lives in `parallel.moe_utils`
(`dispatch_tokens_indexed` / `combine_tokens_indexed`); together they
form the grouped MoE path `incubate.nn.fused_transformer` dispatches
to on TPU (or under kernel-test interpret mode).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import autotune

# Set by tests to run the kernel in interpret mode on the CPU mesh.
_INTERPRET = False


def _on_tpu_backend() -> bool:
    from ...core.place import on_tpu_backend
    return on_tpu_backend()


def grouped_matmul_killed() -> bool:
    """`PADDLE_TPU_GROUPED_MATMUL=0`: the operator asked for the
    one-hot einsum reference on every MoE expert matmul."""
    return os.environ.get("PADDLE_TPU_GROUPED_MATMUL", "1") == "0"


def grouped_matmul_enabled(d_in, d_out) -> bool:
    """Dispatch gate: env kill-switch first, then backend/shape — on a
    TPU backend the contraction and output feature axes must be
    lane-aligned so weight tiles fill (sublane x 128) registers; under
    `_INTERPRET` (tests) any shape runs. Alignment comes from the same
    source of truth as the paged gate (`autotune.LANE_ALIGN`)."""
    if grouped_matmul_killed():
        return False
    if _INTERPRET:
        return True
    return (_on_tpu_backend() and d_in % autotune.LANE_ALIGN == 0
            and d_out % autotune.LANE_ALIGN == 0)


def _gmm_kernel(x_ref, w_ref, o_ref, acc_ref, *, nd, qmax):
    """One (expert, c-tile, f-tile, d-tile) grid cell.

    x tile [1, bc, bd]; w tile [1, bd, bf] (int8 when quantized);
    optional scale tile [1, bf] fp32; out tile [1, bc, bf]; fp32
    accumulator scratch [bc, bf] carried across the d axis."""
    d = pl.program_id(3)

    @pl.when(d == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = w_ref[0].astype(jnp.float32)
    acc_ref[...] += jax.lax.dot_general(
        x_ref[0].astype(jnp.float32), w,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(d == nd - 1)
    def _finalize():
        o_ref[0] = acc_ref[...].astype(o_ref.dtype)


def _gmm_kernel_quant(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nd, qmax):
    d = pl.program_id(3)

    @pl.when(d == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # weight-only dequant fused at the tile load: int8 tile * per-
    # out-channel scale/qmax (same formula as fused_transformer._deq)
    w = w_ref[0].astype(jnp.float32) \
        * (s_ref[0].astype(jnp.float32) / qmax)[None, :]
    acc_ref[...] += jax.lax.dot_general(
        x_ref[0].astype(jnp.float32), w,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(d == nd - 1)
    def _finalize():
        o_ref[0] = acc_ref[...].astype(o_ref.dtype)


def _pick_block(n, target):
    """Largest divisor of n that is <= target (tiles must be exact —
    a remainder tile would read past the buffer)."""
    b = min(int(target), int(n))
    while n % b:
        b -= 1
    return b


def _gmm_call(x, w, scale, qmax, bc, bf, bd, out_dtype):
    """The raw pallas_call with resolved tile sizes."""
    E, C, D = x.shape
    F = w.shape[2]
    nd = D // bd
    grid = (E, C // bc, F // bf, nd)
    in_specs = [
        pl.BlockSpec((1, bc, bd), lambda e, c, f, d: (e, c, d)),
        pl.BlockSpec((1, bd, bf), lambda e, c, f, d: (e, d, f)),
    ]
    args = [x, w]
    if scale is not None:
        in_specs.append(pl.BlockSpec((1, bf), lambda e, c, f, d: (e, f)))
        args.append(scale)
        kernel = functools.partial(_gmm_kernel_quant, nd=nd,
                                   qmax=float(qmax))
    else:
        kernel = functools.partial(_gmm_kernel, nd=nd, qmax=float(qmax))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bc, bf), lambda e, c, f, d: (e, c, f)),
        scratch_shapes=[pltpu.VMEM((bc, bf), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((E, C, F), out_dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * E * C * D * F,
            bytes_accessed=(E * C * D * x.dtype.itemsize
                            + E * D * F * w.dtype.itemsize
                            + E * C * F * jnp.dtype(out_dtype).itemsize),
            transcendentals=0),
        interpret=_INTERPRET,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _gmm_core(x, w, bc, bf, bd, out_dtype):
    """Differentiable (unquantized) grouped matmul: Pallas forward,
    XLA einsum backward — the `_flash_core` discipline (the compiler
    fuses the two grouped backward contractions well, and training
    never runs int8 experts)."""
    return _gmm_call(x, w, None, 127.0, bc, bf, bd, out_dtype)


def _gmm_core_fwd(x, w, bc, bf, bd, out_dtype):
    return _gmm_call(x, w, None, 127.0, bc, bf, bd, out_dtype), (x, w)


def _gmm_core_bwd(bc, bf, bd, out_dtype, res, g):
    x, w = res
    gf = g.astype(jnp.float32)
    dx = jnp.einsum("ecf,edf->ecd", gf, w.astype(jnp.float32))
    dw = jnp.einsum("ecd,ecf->edf", x.astype(jnp.float32), gf)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_gmm_core.defvjp(_gmm_core_fwd, _gmm_core_bwd)


def grouped_expert_matmul(x, w, scale=None, *, qmax=127.0,
                          block_c=None, block_f=None, block_d=None,
                          out_dtype=None):
    """x [E, C, D] @ w [E, D, F] -> [E, C, F], one expert per leading
    grid axis. `scale` [E, F] fp32 dequantizes int8 weight-only
    experts inside the kernel (`w * scale / qmax` per out-channel);
    the quantized variant is inference-only (no VJP — int8 experts
    are never trained), the fp variant differentiates via a custom
    VJP whose backward runs the XLA grouped contractions.

    Tile sizes default to the tuned winner for this shape bucket
    (`autotune.kernel_config("grouped_matmul", ...)`) and fall back to
    MXU-shaped 128/512 targets; explicit arguments pin them (the
    tuner's candidate builder does exactly that)."""
    E, C, D = x.shape
    F = w.shape[2]
    if block_c is None or block_f is None or block_d is None:
        # int8 weight-only experts key by the WEIGHT dtype: tiles
        # measured on int8 loads are a different cache entry than the
        # fp variant's (int8 halves the weight fetch per tile)
        key_dt = w.dtype if scale is not None else x.dtype
        cfg = autotune.kernel_config(
            "grouped_matmul", autotune.shape_bucket(E, C, D, F),
            key_dt, default=None) or {}
        block_c = block_c or cfg.get("block_c", 128)
        block_f = block_f or cfg.get("block_f", 128)
        block_d = block_d or cfg.get("block_d", 512)
    bc = _pick_block(C, block_c)
    bf = _pick_block(F, block_f)
    bd = _pick_block(D, block_d)
    out_dtype = jnp.dtype(out_dtype or x.dtype)
    if scale is None:
        return _gmm_core(x, w, bc, bf, bd, out_dtype)
    return _gmm_call(x, w, scale, qmax, bc, bf, bd, out_dtype)


def grouped_matmul_oracle(x, w, scale=None, *, qmax=127.0,
                          out_dtype=None):
    """The einsum reference (CPU oracle + fallback): dequant in the
    compute dtype, then `ecd,edf->ecf` — numerically the
    `fused_transformer._expert_ffn` formulation."""
    cd = out_dtype or x.dtype
    wf = w.astype(cd)
    if scale is not None:
        wf = wf * (scale[:, None, :].astype(cd) / float(qmax))
    return jnp.einsum("ecd,edf->ecf", x.astype(cd), wf).astype(cd)


def tune_grouped_matmul(E, C, D, F, *, dtype="float32",
                        quantized=False, seed=0, budget_s=None,
                        timer=None, persist=True):
    """Search the (block_c, block_f, block_d) tile space of one
    grouped-matmul shape bucket against the einsum oracle. Runs the
    real kernel (interpret mode off-TPU); the winner lands in the
    persistent cache so `grouped_expert_matmul`'s next trace resolves
    it for free."""
    import numpy as np

    global _INTERPRET
    dtype = np.dtype(dtype)
    if dtype == np.int8:
        # an int8 KEY dtype means the weight-quantized variant:
        # activations stay fp32 (the serving compute dtype), weights
        # int8 + scales
        quantized, dtype = True, np.dtype(np.float32)
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(E, C, D).astype(dtype))
    if quantized:
        w = jnp.asarray(rng.randint(-127, 128, (E, D, F)).astype(
            np.int8))
        s = jnp.asarray((np.abs(rng.randn(E, F)) * 0.05 + 0.01).astype(
            np.float32))
        args = (x, w, s)
    else:
        w = jnp.asarray((rng.randn(E, D, F) * 0.1).astype(dtype))
        args = (x, w, None)

    def oracle(x, w, s):
        return grouped_matmul_oracle(x, w, s, out_dtype=dtype)

    def build(cfg):
        def run(x, w, s):
            return grouped_expert_matmul(
                x, w, s, block_c=cfg["block_c"], block_f=cfg["block_f"],
                block_d=cfg["block_d"], out_dtype=dtype)
        return run

    was = _INTERPRET
    if not _on_tpu_backend():
        _INTERPRET = True
    try:
        # quantized winners cache under int8 (the weight dtype the
        # runtime lookup keys by), never clobbering the fp entry
        key_dt = np.dtype(np.int8) if quantized else dtype
        return autotune.search(
            "grouped_matmul", autotune.shape_bucket(E, C, D, F),
            key_dt, autotune.grouped_matmul_candidates(E, C, D, F),
            build, args, oracle, rtol=2e-2, atol=2e-2,
            budget_s=budget_s, timer=timer, persist=persist,
            meta={"quantized": bool(quantized), "seed": seed})
    finally:
        _INTERPRET = was
