"""Grouped-expert matmul Pallas kernel for the MoE capacity buffers.

The MoE serving hot path (ISSUE 10) runs every expert's FFN on its
fixed `[C, d]` capacity buffer. The XLA path expresses the whole block
as one-hot einsums (`moe_utils.dispatch_tokens` / `combine_tokens` +
`einsum("ecd,edf->ecf")`), which materializes `[T, k, C]`/`[T, k, E]`
masks and leaves the per-expert matmuls to the compiler's batching.
This kernel grids DIRECTLY over (expert, C-tile, F-tile) with a
sequential d-reduction axis, so each expert's capacity buffer hits the
MXU as dense tiles:

* grid `(E, C/bc, F/bf, D/bd)` — the leading three axes are
  embarrassingly parallel (`dimension_semantics`), the trailing
  reduction axis carries a VMEM fp32 accumulator;
* int8 weight-only experts dequantize INSIDE the kernel: the
  per-(expert, out-channel) scale tile rides the same (e, f) index
  map as the weight tile and multiplies it right after the load —
  the weight is read from HBM as int8, exactly like `_mm`'s fused
  dequant on the dense path;
* int4 weight-only experts (ISSUE 14) store TWO nibbles per byte
  along the contraction axis (`pack_int4`/`unpack_int4`: low nibble =
  even row, high nibble = odd row, sign-extended by arithmetic
  shifts) with per-(expert, out-channel) fp16 scales; the kernel
  loads the packed `[bd/2, bf]` tile and unpacks + dequantizes it in
  registers right before the dot — the weight is read from HBM at
  0.5 bytes/element, and the autotune cache keys these winners by
  the `int4` weight dtype (the PR 11 int8 keying rule);
* tile sizes `(block_c, block_f, block_d)` are TUNABLE
  (`ops.pallas.autotune`, kernel name ``grouped_matmul``) — the
  einsum path stays the CPU oracle and the fallback for shapes the
  gate refuses.

The companion index-based dispatch/combine (no one-hot
materialization) lives in `parallel.moe_utils`
(`dispatch_tokens_indexed` / `combine_tokens_indexed`); together they
form the grouped MoE path `incubate.nn.fused_transformer` dispatches
to on TPU (or under kernel-test interpret mode).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import autotune

# Set by tests to run the kernel in interpret mode on the CPU mesh.
_INTERPRET = False


def _on_tpu_backend() -> bool:
    from ...core.place import on_tpu_backend
    return on_tpu_backend()


def grouped_matmul_killed() -> bool:
    """`PADDLE_TPU_GROUPED_MATMUL=0`: the operator asked for the
    one-hot einsum reference on every MoE expert matmul."""
    return os.environ.get("PADDLE_TPU_GROUPED_MATMUL", "1") == "0"


def grouped_matmul_enabled(d_in, d_out) -> bool:
    """Dispatch gate: env kill-switch first, then backend/shape — on a
    TPU backend the contraction and output feature axes must be
    lane-aligned so weight tiles fill (sublane x 128) registers; under
    `_INTERPRET` (tests) any shape runs. Alignment comes from the same
    source of truth as the paged gate (`autotune.LANE_ALIGN`)."""
    if grouped_matmul_killed():
        return False
    if _INTERPRET:
        return True
    return (_on_tpu_backend() and d_in % autotune.LANE_ALIGN == 0
            and d_out % autotune.LANE_ALIGN == 0)


# ---------------------------------------------------------------------
# int4 packing (two nibbles per byte along the contraction axis)
# ---------------------------------------------------------------------

INT4_QMAX = 7.0


def pack_int4(q, axis=-2):
    """Pack int4-valued int8 (`[-8, 7]`) pairs along `axis` into one
    int8 byte each: low nibble = even index, high nibble = odd index.
    The axis length must be even (expert contraction axes always are —
    they are MXU-lane-aligned in practice)."""
    q = jnp.asarray(q)
    axis = axis % q.ndim
    if q.shape[axis] % 2:
        raise ValueError(
            f"pack_int4 needs an even axis length, got {q.shape[axis]}")
    even = jnp.take(q, jnp.arange(0, q.shape[axis], 2), axis=axis)
    odd = jnp.take(q, jnp.arange(1, q.shape[axis], 2), axis=axis)
    return ((odd.astype(jnp.int8) << 4)
            | (even.astype(jnp.int8) & 0x0F)).astype(jnp.int8)


def unpack_int4(packed, axis=-2):
    """Inverse of `pack_int4`: int8 bytes -> int4 values, interleaved
    back to the original order (arithmetic shifts sign-extend, so the
    round trip is exact over [-8, 7]). Pure vector ops, so the grouped
    kernel unpacks its weight tile with the same function."""
    axis = axis % packed.ndim
    low = (packed << 4) >> 4
    high = packed >> 4
    out = jnp.stack([low, high], axis=axis + 1)
    shape = list(packed.shape)
    shape[axis] *= 2
    return out.reshape(shape)


def is_packed_int4(w, d_in):
    """True when `w` is an int4-packed weight for a logical `[...,
    d_in, d_out]` matmul: int8 storage with HALF the contraction rows.
    The shape test is unambiguous — an int8 weight always matches its
    activation's contraction axis exactly."""
    return (w.dtype == jnp.int8 or str(w.dtype) == "int8") \
        and w.shape[-2] * 2 == int(d_in)


def quantize_int4_experts(w):
    """[..., In, Out] float -> (packed int8 [..., In/2, Out], fp16
    scales [..., Out]): symmetric per-out-channel amax scaling at
    qmax=7, then nibble-packed along the contraction axis. The fp16
    scales halve the (already small) scale overhead vs the int8
    path's fp32 — int4's point is bytes. Same scale convention as
    `fused_transformer._quantize_expert_stack`: dequant is
    `q * scale / qmax`."""
    wf = jnp.asarray(w).astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=-2), 1e-9)
    q = jnp.clip(jnp.round(wf / scale[..., None, :] * INT4_QMAX),
                 -INT4_QMAX, INT4_QMAX).astype(jnp.int8)
    return pack_int4(q, axis=-2), scale.astype(jnp.float16)


def expert_weight_bytes(E, d_in, d_out, weight_dtype, num_layers=1):
    """HBM bytes one expert-weight stack `[L, E, d_in, d_out]` costs,
    scales included — the analytic side of the int4 capacity contract
    (bf16 2 B/elem; int8 0.5 B... no: 1 B + fp32 scale/out-chan; int4
    0.5 B + fp16 scale/out-chan). Pure host arithmetic."""
    n = num_layers * E * d_in * d_out
    per_scale = num_layers * E * d_out
    if weight_dtype in ("float32",):
        return 4 * n
    if weight_dtype in ("bfloat16", "float16"):
        return 2 * n
    if weight_dtype == "int8":
        return n + 4 * per_scale
    if weight_dtype == "int4":
        return n // 2 + 2 * per_scale
    raise ValueError(f"unknown expert weight dtype {weight_dtype!r}")


def _gmm_kernel(x_ref, w_ref, o_ref, acc_ref, *, nd, qmax):
    """One (expert, c-tile, f-tile, d-tile) grid cell.

    x tile [1, bc, bd]; w tile [1, bd, bf] (int8 when quantized);
    optional scale tile [1, bf] fp32; out tile [1, bc, bf]; fp32
    accumulator scratch [bc, bf] carried across the d axis."""
    d = pl.program_id(3)

    @pl.when(d == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = w_ref[0].astype(jnp.float32)
    acc_ref[...] += jax.lax.dot_general(
        x_ref[0].astype(jnp.float32), w,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(d == nd - 1)
    def _finalize():
        o_ref[0] = acc_ref[...].astype(o_ref.dtype)


def _gmm_kernel_quant(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nd, qmax):
    d = pl.program_id(3)

    @pl.when(d == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # weight-only dequant fused at the tile load: int8 tile * per-
    # out-channel scale/qmax (same formula as fused_transformer._deq)
    w = w_ref[0].astype(jnp.float32) \
        * (s_ref[0].astype(jnp.float32) / qmax)[None, :]
    acc_ref[...] += jax.lax.dot_general(
        x_ref[0].astype(jnp.float32), w,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(d == nd - 1)
    def _finalize():
        o_ref[0] = acc_ref[...].astype(o_ref.dtype)


def _gmm_kernel_quant4(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nd,
                       qmax):
    """int4 variant: the weight tile arrives PACKED `[bd/2, bf]` int8
    and is unpacked + dequantized in registers right before the dot —
    the HBM fetch is half the int8 path's. Same grid/accumulator
    discipline as the other kernels; the d-reduction axis indexes
    packed rows (bd/2 per tile), the x tile the matching bd rows."""
    d = pl.program_id(3)

    @pl.when(d == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w4 = unpack_int4(w_ref[0], axis=0)               # [bd, bf] int4
    w = w4.astype(jnp.float32) \
        * (s_ref[0].astype(jnp.float32) / qmax)[None, :]
    acc_ref[...] += jax.lax.dot_general(
        x_ref[0].astype(jnp.float32), w,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(d == nd - 1)
    def _finalize():
        o_ref[0] = acc_ref[...].astype(o_ref.dtype)


def _pick_block(n, target, multiple=1):
    """Largest divisor of n that is <= target (tiles must be exact —
    a remainder tile would read past the buffer). `multiple` further
    constrains the divisor (the int4 d-tile must cover whole packed
    bytes, so it must be even)."""
    b = min(int(target), int(n))
    b -= b % multiple
    while b > multiple and (n % b or b % multiple):
        b -= 1
    if b <= 0 or n % b:
        b = multiple
    return b


def _gmm_call(x, w, scale, qmax, bc, bf, bd, out_dtype):
    """The raw pallas_call with resolved tile sizes. An int4-packed
    weight (`is_packed_int4`) rides the quant4 kernel: its BlockSpec
    tiles packed rows (`bd // 2` per d-step) while x tiles the
    matching `bd` activation rows — the index maps line up because
    both advance one block per d grid step."""
    E, C, D = x.shape
    F = w.shape[2]
    int4 = is_packed_int4(w, D)
    nd = D // bd
    grid = (E, C // bc, F // bf, nd)
    in_specs = [
        pl.BlockSpec((1, bc, bd), lambda e, c, f, d: (e, c, d)),
        pl.BlockSpec((1, bd // 2 if int4 else bd, bf),
                     lambda e, c, f, d: (e, d, f)),
    ]
    args = [x, w]
    if scale is not None:
        in_specs.append(pl.BlockSpec((1, bf), lambda e, c, f, d: (e, f)))
        args.append(scale)
        kernel = functools.partial(
            _gmm_kernel_quant4 if int4 else _gmm_kernel_quant, nd=nd,
            qmax=float(qmax))
    else:
        kernel = functools.partial(_gmm_kernel, nd=nd, qmax=float(qmax))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bc, bf), lambda e, c, f, d: (e, c, f)),
        scratch_shapes=[pltpu.VMEM((bc, bf), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((E, C, F), out_dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * E * C * D * F,
            bytes_accessed=(E * C * D * x.dtype.itemsize
                            + w.size * w.dtype.itemsize
                            + E * C * F * jnp.dtype(out_dtype).itemsize),
            transcendentals=0),
        interpret=_INTERPRET,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _gmm_core(x, w, bc, bf, bd, out_dtype):
    """Differentiable (unquantized) grouped matmul: Pallas forward,
    XLA einsum backward — the `_flash_core` discipline (the compiler
    fuses the two grouped backward contractions well, and training
    never runs int8 experts)."""
    return _gmm_call(x, w, None, 127.0, bc, bf, bd, out_dtype)


def _gmm_core_fwd(x, w, bc, bf, bd, out_dtype):
    return _gmm_call(x, w, None, 127.0, bc, bf, bd, out_dtype), (x, w)


def _gmm_core_bwd(bc, bf, bd, out_dtype, res, g):
    x, w = res
    gf = g.astype(jnp.float32)
    dx = jnp.einsum("ecf,edf->ecd", gf, w.astype(jnp.float32))
    dw = jnp.einsum("ecd,ecf->edf", x.astype(jnp.float32), gf)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_gmm_core.defvjp(_gmm_core_fwd, _gmm_core_bwd)


def grouped_expert_matmul(x, w, scale=None, *, qmax=None,
                          block_c=None, block_f=None, block_d=None,
                          out_dtype=None):
    """x [E, C, D] @ w [E, D, F] -> [E, C, F], one expert per leading
    grid axis. `scale` [E, F] fp32 dequantizes int8 weight-only
    experts inside the kernel (`w * scale / qmax` per out-channel);
    the quantized variant is inference-only (no VJP — int8 experts
    are never trained), the fp variant differentiates via a custom
    VJP whose backward runs the XLA grouped contractions.

    int4-packed weights (`is_packed_int4`: int8 storage at half the
    contraction rows, the `pack_int4` layout) dispatch the quant4
    kernel with the `[E, F]` fp16 scales; tile lookups then key by
    the `int4` dtype. `qmax` defaults by detected weight format
    (INT4_QMAX packed, 127 int8) so a call site that forgets to
    thread it can never silently mis-scale the dequant.

    Tile sizes default to the tuned winner for this shape bucket
    (`autotune.kernel_config("grouped_matmul", ...)`) and fall back to
    MXU-shaped 128/512 targets; explicit arguments pin them (the
    tuner's candidate builder does exactly that)."""
    E, C, D = x.shape
    int4 = scale is not None and is_packed_int4(w, D)
    if qmax is None:
        qmax = INT4_QMAX if int4 else 127.0
    F = w.shape[2]
    if block_c is None or block_f is None or block_d is None:
        # quantized experts key by the WEIGHT dtype (int8 / int4):
        # tiles measured on 1-byte or packed-nibble loads are a
        # different cache entry than the fp variant's
        if int4:
            key_dt = jnp.dtype(jnp.int4)
        elif scale is not None:
            key_dt = w.dtype
        else:
            key_dt = x.dtype
        cfg = autotune.kernel_config(
            "grouped_matmul", autotune.shape_bucket(E, C, D, F),
            key_dt, default=None) or {}
        block_c = block_c or cfg.get("block_c", 128)
        block_f = block_f or cfg.get("block_f", 128)
        block_d = block_d or cfg.get("block_d", 512)
    bc = _pick_block(C, block_c)
    bf = _pick_block(F, block_f)
    bd = _pick_block(D, block_d, multiple=2 if int4 else 1)
    out_dtype = jnp.dtype(out_dtype or x.dtype)
    if scale is None:
        return _gmm_core(x, w, bc, bf, bd, out_dtype)
    return _gmm_call(x, w, scale, qmax, bc, bf, bd, out_dtype)


def grouped_matmul_oracle(x, w, scale=None, *, qmax=None,
                          out_dtype=None):
    """The einsum reference (CPU oracle + fallback): dequant in the
    compute dtype, then `ecd,edf->ecf` — numerically the
    `fused_transformer._expert_ffn` formulation. int4-packed weights
    unpack first (same nibble layout as the kernel); `qmax` defaults
    by detected format like `grouped_expert_matmul`."""
    cd = out_dtype or x.dtype
    if scale is not None and is_packed_int4(w, x.shape[2]):
        if qmax is None:
            qmax = INT4_QMAX
        w = unpack_int4(w, axis=-2)
    if qmax is None:
        qmax = 127.0
    wf = w.astype(cd)
    if scale is not None:
        wf = wf * (scale[:, None, :].astype(cd) / float(qmax))
    return jnp.einsum("ecd,edf->ecf", x.astype(cd), wf).astype(cd)


def tune_grouped_matmul(E, C, D, F, *, dtype="float32",
                        quantized=False, seed=0, budget_s=None,
                        timer=None, persist=True):
    """Search the (block_c, block_f, block_d) tile space of one
    grouped-matmul shape bucket against the einsum oracle. Runs the
    real kernel (interpret mode off-TPU); the winner lands in the
    persistent cache so `grouped_expert_matmul`'s next trace resolves
    it for free."""
    import numpy as np

    global _INTERPRET
    dtype = np.dtype(dtype)
    int4 = dtype == np.dtype(jnp.int4)
    if int4 or dtype == np.int8:
        # an int8/int4 KEY dtype means the weight-quantized variant:
        # activations stay fp32 (the serving compute dtype), weights
        # quantized + scales (int4: nibble-packed, fp16 scales)
        quantized, dtype = True, np.dtype(np.float32)
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(E, C, D).astype(dtype))
    qmax = INT4_QMAX if int4 else 127.0
    if int4:
        q = rng.randint(-7, 8, (E, D, F)).astype(np.int8)
        w = pack_int4(jnp.asarray(q), axis=-2)
        s = jnp.asarray((np.abs(rng.randn(E, F)) * 0.05 + 0.01).astype(
            np.float16))
        args = (x, w, s)
    elif quantized:
        w = jnp.asarray(rng.randint(-127, 128, (E, D, F)).astype(
            np.int8))
        s = jnp.asarray((np.abs(rng.randn(E, F)) * 0.05 + 0.01).astype(
            np.float32))
        args = (x, w, s)
    else:
        w = jnp.asarray((rng.randn(E, D, F) * 0.1).astype(dtype))
        args = (x, w, None)

    def oracle(x, w, s):
        return grouped_matmul_oracle(x, w, s, qmax=qmax, out_dtype=dtype)

    def build(cfg):
        def run(x, w, s):
            return grouped_expert_matmul(
                x, w, s, qmax=qmax, block_c=cfg["block_c"],
                block_f=cfg["block_f"], block_d=cfg["block_d"],
                out_dtype=dtype)
        return run

    was = _INTERPRET
    if not _on_tpu_backend():
        _INTERPRET = True
    try:
        # quantized winners cache under the weight dtype the runtime
        # lookup keys by (int8 / int4), never clobbering the fp entry
        if int4:
            key_dt = np.dtype(jnp.int4)
        elif quantized:
            key_dt = np.dtype(np.int8)
        else:
            key_dt = dtype
        return autotune.search(
            "grouped_matmul", autotune.shape_bucket(E, C, D, F),
            key_dt, autotune.grouped_matmul_candidates(E, C, D, F),
            build, args, oracle, rtol=2e-2, atol=2e-2,
            budget_s=budget_s, timer=timer, persist=persist,
            meta={"quantized": bool(quantized), "int4": bool(int4),
                  "seed": seed})
    finally:
        _INTERPRET = was
