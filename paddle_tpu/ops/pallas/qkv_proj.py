"""Fused QKV projection Pallas kernel: [B,S,d] x [d,3d] -> 3x [B,H,S,hd].

Why a kernel: producing attention-layout ([B, H, S, 64]) projections
with plain einsums forces XLA into matmuls whose output N-tile is the
64-wide head dim — half the 128 MXU lanes idle, trace-measured ~94 TF/s
vs fc1's 193 TF/s on v5e (docs/gpt_perf_analysis.md round-5 profile).
This kernel computes a head *pair* per MXU pass (N=128, full lanes) and
splits the accumulator across the two heads' [S, 64] output blocks on
store, so the matmul runs at full rate and only the (unavoidable,
bandwidth-cheap) half-lane stores touch 64-wide tiles.

Parity: the reference fuses qkv into one GEMM inside
`paddle/fluid/operators/fused/fused_multi_transformer_op.cu:1` (qkv
weight [3, H, hd, d]); same capability, TPU-shaped.

Backward is plain einsums (custom_vjp): the transposed contractions
have K=H*hd=d and N=d — full-lane shapes XLA already emits at peak.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Set by tests: run the kernel in Pallas interpret mode on CPU.
_INTERPRET = False


def _kernel(x_ref, wq_ref, wk_ref, wv_ref, bq_ref, bk_ref, bv_ref,
            q_ref, k_ref, v_ref):
    # x_ref [bb, S, d]; w*_ref [d, 128] (one head pair); b*_ref [1, 128]
    # q/k/v_ref [bb, 2, S, 64]
    bb, S, d = x_ref.shape
    x = x_ref[...].reshape(bb * S, d)
    for w_ref, b_ref, o_ref in ((wq_ref, bq_ref, q_ref),
                                (wk_ref, bk_ref, k_ref),
                                (wv_ref, bv_ref, v_ref)):
        acc = jax.lax.dot_general(
            x, w_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc = acc + b_ref[0].astype(jnp.float32)[None, :]
        hd = o_ref.shape[-1]
        out = acc.astype(o_ref.dtype).reshape(bb, S, 2 * hd)
        o_ref[:, 0] = out[:, :, :hd]
        o_ref[:, 1] = out[:, :, hd:]


def _qkv_proj_fwd_impl(x, w_qkv, b_qkv, n_heads):
    B, S, d = x.shape
    th = w_qkv.shape[1] // 3   # local width of each q/k/v third (mp-aware)
    hd = th // n_heads
    hp = n_heads // 2          # head pairs
    dt = x.dtype
    wq, wk, wv = (w_qkv[:, :th], w_qkv[:, th:2 * th], w_qkv[:, 2 * th:])
    bq, bk, bv = (b_qkv[:th].reshape(1, th), b_qkv[th:2 * th].reshape(1, th),
                  b_qkv[2 * th:].reshape(1, th))
    # block a few batches per program so the weight tiles stay
    # VMEM-resident across the inner head-pair sweep (grid order: h
    # fastest -> x block cached; bb>1 amortizes the w refetch over bb
    # batches)
    # scoped vmem is 16MB and pallas double-buffers every block: bb=1
    # is the largest batch block that fits at S=1024, d=1024 (bb=2
    # measured 20.35M scoped > 16M limit); fall back to bb=1 for
    # larger S*d (the supported() gate bounds the bb=1 block size)
    bb = next((b for b in (2, 1) if B % b == 0
               and b * S * d * 2 <= 2 * 2 ** 20), 1)
    out_shape = jax.ShapeDtypeStruct((B, n_heads, S, hd), dt)
    w_spec = pl.BlockSpec((d, 2 * hd), lambda b, h: (0, h))
    b_spec = pl.BlockSpec((1, 2 * hd), lambda b, h: (0, h))
    o_spec = pl.BlockSpec((bb, 2, S, hd), lambda b, h: (b, h, 0, 0))
    return pl.pallas_call(
        _kernel,
        grid=(B // bb, hp),
        in_specs=[pl.BlockSpec((bb, S, d), lambda b, h: (b, 0, 0)),
                  w_spec, w_spec, w_spec, b_spec, b_spec, b_spec],
        out_specs=[o_spec, o_spec, o_spec],
        out_shape=[out_shape, out_shape, out_shape],
        interpret=_INTERPRET,
    )(x, wq, wk, wv, bq, bk, bv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def qkv_proj(x, w_qkv, b_qkv, n_heads):
    """x [B,S,d], w_qkv [d,3d], b_qkv [3d] -> (q, k, v) each
    [B, n_heads, S, d/n_heads]. TPU Pallas fast path; the caller is
    responsible for gating on `qkv_proj_supported`."""
    return _qkv_proj_fwd_impl(x, w_qkv, b_qkv, n_heads)


def _fwd(x, w_qkv, b_qkv, n_heads):
    return (_qkv_proj_fwd_impl(x, w_qkv, b_qkv, n_heads),
            (x, w_qkv, b_qkv))


def _bwd(n_heads, res, g):
    x, w_qkv, b_qkv = res
    B, S, d = x.shape
    th = w_qkv.shape[1] // 3
    hd = th // n_heads
    # stay in [B,H,S,hd]: dgrad contracts over (h,e) (K=th, full rate)
    # and wgrad's N-tile is d — both shapes XLA emits at peak; a
    # BHSD->BSD transpose here would reintroduce the 8-10ms relayout
    # copies the forward kernel exists to avoid (r5 trace)
    dx = jnp.zeros(x.shape, jnp.float32)
    dws, dbs = [], []
    for i, gi in enumerate(g):
        wi = jax.lax.dynamic_slice_in_dim(
            w_qkv, i * th, th, axis=1).reshape(d, n_heads, hd)
        dx = dx + jnp.einsum("bhse,dhe->bsd", gi, wi,
                             preferred_element_type=jnp.float32)
        dws.append(jnp.einsum("bsd,bhse->dhe", x, gi,
                              preferred_element_type=jnp.float32)
                   .reshape(d, th))
        dbs.append(jnp.sum(gi.astype(jnp.float32),
                           axis=(0, 2)).reshape(th))
    dw = jnp.concatenate(dws, axis=1).astype(w_qkv.dtype)
    db = jnp.concatenate(dbs).astype(b_qkv.dtype)
    return dx.astype(x.dtype), dw, db


qkv_proj.defvjp(_fwd, _bwd)


def qkv_proj_supported(n_heads, seq_len, local_width,
                       x_width=None) -> bool:
    """Gate: TPU backend, paired heads, the 64-wide head dim that makes
    the einsum path half-lane (hd=128 einsums are already full rate),
    and a bb=1 x-block that fits scoped vmem with double buffering
    (sized for the bf16 compute path: 2 bytes/element)."""
    from .flash_attention import _on_tpu_backend
    hd = local_width // max(n_heads, 1)
    xw = x_width if x_width is not None else local_width
    return (_on_tpu_backend() and n_heads % 2 == 0 and n_heads >= 2
            and n_heads * hd == local_width and hd == 64
            and seq_len % 8 == 0
            and seq_len * xw * 2 <= 4 * 2 ** 20)
