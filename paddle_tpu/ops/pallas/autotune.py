"""Measurement-driven Pallas kernel autotuner (ROADMAP item 2).

Three hand-tiled Pallas surfaces (flash, the paged-attention family,
the MoE grouped-expert matmul) carry grid/tile/pipeline numbers that
were picked once by hand — fastest on the author's box, frozen
thereafter. PAPERS.md's "Automatic Kernel Generation for Volta Tensor
Cores" and "CUDA-L2" both make the same observation: searched kernels
consistently beat hand-picked tiles, and the search is cheap compared
to the serving hours the winner runs for. This module makes the tile
numbers self-maintaining:

* **Search spaces** parameterize the tunable axes of each kernel
  entry — block/tile sizes (flash ``block_q``/``block_k``, splash's
  six block numbers, grouped-matmul ``block_c/f/d``), grid layout /
  pipeline behaviour (``dimension_semantics`` per grid axis for the
  paged family), and the engine-level KV ``block_size`` whose choice
  reshapes every paged tile.
* **Candidates are measured, not modeled**: `search()` times each
  admitted candidate with the PR 1 timer statistics (min over a
  window of repeats — the same `profiler.timer._Stat` the throughput
  benchmark uses) under a wall-clock budget.
* **Parity is the admission gate**: every candidate's output is
  checked against the caller's XLA oracle before it may be timed; a
  candidate that fails parity is rejected and counted
  (`paddle_tpu_kernel_autotune_candidates_rejected_parity_total`) —
  a fast wrong kernel must never win.
* **Winners are cached** per `(kernel, shape-bucket, dtype,
  backend/topology)` in a persistent on-disk JSON cache mirroring
  `parallel.auto_tuner`'s calibrated-placement discipline: measure
  once, replay forever. The repo ships a pre-seeded cache
  (`autotune_cache.json` next to this module) so the default CI path
  never tunes — a cache hit is ONE dict lookup (memoized in-process),
  zero search cost. Misses are recorded so
  `tools/kernel_coverage.py --tuner-audit` can flag shape-buckets
  that serve traffic without a tuned entry.

Env contract:

* ``PADDLE_TPU_KERNEL_AUTOTUNE=0`` — kill-switch: every consumer gets
  its hand-picked default, the cache is neither read nor written.
* ``PADDLE_TPU_KERNEL_AUTOTUNE=1`` (default) — cached winners apply;
  a miss falls back to the default (and is recorded for the audit).
* ``PADDLE_TPU_KERNEL_AUTOTUNE=tune`` — a miss additionally runs the
  registered search for that kernel (bounded by its time budget) and
  persists the winner: the re-tune-on-new-hardware path
  (docs/KERNELS.md).
* ``PADDLE_TPU_KERNEL_CACHE=<path>`` — the writable cache location
  (default ``~/.cache/paddle_tpu/kernel_autotune.json``); the seeded
  package cache stays read-only underneath it.

Alignment single source of truth: `paged_alignment_ok` below is THE
definition of the paged kernels' shape constraints. The dispatch gate
(`paged_attention.paged_pallas_enabled`) and the tuner's candidate
filters both call it, so a tuned candidate can never be admitted that
the serve-time gate would refuse (ISSUE 11 satellite).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

# ---------------------------------------------------------------------
# alignment constraints — ONE source of truth for the dispatch gate
# (paged_attention.paged_pallas_enabled) AND every tuner candidate
# filter. head_dim rides the 128-wide lane axis of the KV tiles,
# block_size the 8-deep sublane axis.
# ---------------------------------------------------------------------

LANE_ALIGN = 128
SUBLANE_ALIGN = 8


def paged_alignment_ok(head_dim, block_size) -> bool:
    """True when the paged Pallas kernels can tile this
    (head_dim, block_size) on real TPU hardware. The serve-time
    dispatch gate and the tuner's block-size candidate filter share
    this predicate by construction."""
    return int(head_dim) % LANE_ALIGN == 0 \
        and int(block_size) % SUBLANE_ALIGN == 0


# ---------------------------------------------------------------------
# mode / keys
# ---------------------------------------------------------------------

_ENV = "PADDLE_TPU_KERNEL_AUTOTUNE"


def mode() -> str:
    """"off" | "on" | "tune" from the env contract above."""
    v = os.environ.get(_ENV, "1").strip().lower()
    if v in ("0", "off", "false"):
        return "off"
    if v == "tune":
        return "tune"
    return "on"


def enabled() -> bool:
    return mode() != "off"


def backend_key() -> str:
    """Cache-key backend/topology component: platform + device kind +
    device count, so a cache tuned on one slice never silently applies
    to another (v5e-8 tiles are not v4-32 tiles — and neither are the
    CPU interpret-mode numbers the CI cache ships). The CPU backend
    drops the count: `--xla_force_host_platform_device_count` is a
    test-harness knob, not a topology."""
    try:
        import jax
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", dev.platform) or dev.platform
        kind = "".join(c if c.isalnum() else "-" for c in str(kind))
        if dev.platform == "cpu":
            return f"cpu-{kind}"
        return f"{dev.platform}-{kind}-d{jax.device_count()}"
    except Exception:  # noqa: BLE001 — no backend: key must still form
        return "none"


def _pow2_bucket(n, lo=1):
    n = max(int(n), 1)
    p = int(lo)
    while p < n:
        p *= 2
    return p


def shape_bucket(*dims):
    """Bucket a shape tuple: every axis rounds up to a power of two,
    so nearby traffic shapes share one tuned entry (the engine's token
    budget and slot counts are already pow2-disciplined via
    `serving.batcher`, making the serving buckets exact)."""
    return tuple(_pow2_bucket(d) for d in dims)


def cache_key(kernel, bucket, dtype, backend=None) -> str:
    b = "x".join(str(int(d)) for d in bucket)
    return f"{kernel}|{b}|{np.dtype(dtype).name}|" \
           f"{backend or backend_key()}"


# ---------------------------------------------------------------------
# persistent cache: seeded package file + writable user overlay
# ---------------------------------------------------------------------

_SEED_CACHE_FILE = os.path.join(os.path.dirname(os.path.abspath(
    __file__)), "autotune_cache.json")

_CACHE = None            # key -> {"config": {...}, ...}
_MEMO = {}               # key -> config (the one-dict-lookup hot path)
_REQUESTED = {}          # key -> bool hit (audit + stale detection)


def user_cache_path() -> str:
    p = os.environ.get("PADDLE_TPU_KERNEL_CACHE", "")
    if p:
        return p
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "paddle_tpu", "kernel_autotune.json")


def _read_json(path):
    try:
        with open(path) as f:
            data = json.load(f)
        return dict(data.get("entries", {}))
    except (OSError, ValueError):
        return {}


def load_cache(refresh=False) -> dict:
    """The merged cache (seeded package entries under the user
    overlay). Loaded once per process; `refresh=True` re-reads disk."""
    global _CACHE
    if _CACHE is None or refresh:
        _CACHE = _read_json(_SEED_CACHE_FILE)
        _CACHE.update(_read_json(user_cache_path()))
        _MEMO.clear()
    return _CACHE


def _persist(key, entry):
    path = user_cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        user = _read_json(path)
        user[key] = entry
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": 1, "entries": user}, f, indent=1,
                      sort_keys=True)
        os.replace(tmp, path)
        return True
    except OSError:
        return False


def record(kernel, bucket, dtype, config, meta=None, persist=True):
    """Cache a tuned winner (and persist it to the user cache file)."""
    key = cache_key(kernel, bucket, dtype)
    entry = {"config": dict(config)}
    if meta:
        entry["meta"] = dict(meta)
    load_cache()[key] = entry
    _MEMO[key] = dict(config)
    if persist:
        _persist(key, entry)
    return key


def _metrics():
    from ...profiler import metrics as pm
    return pm


def kernel_config(kernel, bucket, dtype, default=None):
    """The hot lookup every tuned kernel entry calls at TRACE time
    (inside the one compile — never per step): cached winner on hit,
    `default` on miss or with the kill-switch set. A hit is one dict
    probe; hits/misses are counted and every requested key is recorded
    for the stale-cache audit."""
    if not enabled():
        return default
    key = cache_key(kernel, bucket, dtype)
    cfg = _MEMO.get(key)
    if cfg is None:
        entry = load_cache().get(key)
        if entry is not None:
            cfg = _MEMO[key] = dict(entry["config"])
    hit = cfg is not None
    _REQUESTED[key] = hit or _REQUESTED.get(key, False)
    pm = _metrics()
    if pm._enabled:
        (pm.KERNEL_AUTOTUNE_CACHE_HITS if hit
         else pm.KERNEL_AUTOTUNE_CACHE_MISSES).labels(kernel).inc()
    return dict(cfg) if hit else default


def requested() -> dict:
    """Every cache key `kernel_config` was asked for this process,
    mapped to whether it ever hit — the audit's traffic record."""
    return dict(_REQUESTED)


def audit(requested_keys=None):
    """Stale-cache detection: cache keys traffic asked for that hold
    no tuned entry. Returns (missing_keys, hit_keys)."""
    req = requested() if requested_keys is None else {
        k: False for k in requested_keys}
    cache = load_cache()
    missing, hit = [], []
    for key in sorted(req):
        (hit if key in cache else missing).append(key)
    return missing, hit


def reset_for_tests():
    """Drop the in-process cache/memo/audit state (tests only)."""
    global _CACHE
    _CACHE = None
    _MEMO.clear()
    _REQUESTED.clear()


# ---------------------------------------------------------------------
# the search
# ---------------------------------------------------------------------


class SearchResult:
    def __init__(self, config, seconds, tried, rejected, elapsed,
                 timings=None):
        self.config = config          # winning candidate (dict)
        self.seconds = seconds        # its measured time
        self.tried = tried            # candidates timed
        self.rejected = rejected      # candidates failing parity
        self.elapsed = elapsed        # wall seconds the search spent
        self.timings = timings or []  # [(config, seconds)] admitted

    def __repr__(self):
        return (f"SearchResult({self.config}, {self.seconds:.3e}s, "
                f"tried={self.tried}, rejected={self.rejected})")


def _default_timer(fn, args, repeats):
    """Min-of-window candidate pricing on the PR 1 timer statistics:
    one warmup call (compile), then `repeats` timed calls, min wins
    (host noise only ever inflates a sample)."""
    import jax
    from ...profiler.timer import _Stat

    def run():
        out = fn(*args)
        jax.tree_util.tree_map(
            lambda a: a.block_until_ready()
            if hasattr(a, "block_until_ready") else a, out)
        return out

    run()
    stat = _Stat()
    for _ in range(max(int(repeats), 1)):
        t0 = time.perf_counter()
        run()
        stat.add(time.perf_counter() - t0)
    return min(stat.window)


def _parity_ok(out, ref, rtol, atol):
    import jax
    outs = jax.tree_util.tree_leaves(out)
    refs = jax.tree_util.tree_leaves(ref)
    if len(outs) != len(refs):
        return False
    for o, r in zip(outs, refs):
        o = np.asarray(o, np.float64)
        r = np.asarray(r, np.float64)
        if o.shape != r.shape or not np.allclose(o, r, rtol=rtol,
                                                 atol=atol):
            return False
    return True


def search(kernel, bucket, dtype, candidates, build, args, oracle,
           *, rtol=2e-2, atol=2e-2, budget_s=None, repeats=3,
           timer=None, persist=True, meta=None):
    """Measure candidates, gate each on oracle parity, cache the winner.

    candidates  ordered list of config dicts (deterministic: a fixed
                seed reproduces the same winner when the timer is
                deterministic — the replay property test injects one)
    build       config -> callable(*args), or -> (callable, args) when
                the candidate re-shapes its own inputs (the engine-
                level block-size axis); returning None SKIPS the
                candidate (the space's shape filter)
    oracle      callable(*args) -> the reference output the admission
                gate compares every candidate against (re-evaluated on
                a candidate's own args when build supplies them)
    budget_s    wall-clock budget; at least one admitted candidate is
                always evaluated, the rest are dropped once exceeded
    timer       (fn, args, repeats) -> seconds; injectable so tests
                (and the replay contract) can price deterministically

    Returns the `SearchResult`; the winner is recorded in the cache
    under `(kernel, bucket, dtype, backend)` unless `persist=False`
    wants a dry run (the result still carries it)."""
    timer = timer or _default_timer
    ref = oracle(*args) if args is not None else None
    t_start = time.perf_counter()
    best_cfg, best_t = None, float("inf")
    tried = rejected = 0
    timings = []
    pm = _metrics()
    for cfg in candidates:
        elapsed = time.perf_counter() - t_start
        if budget_s is not None and elapsed > budget_s and tried > 0:
            break
        built = build(dict(cfg))
        if built is None:
            continue
        if isinstance(built, tuple):
            fn, cand_args = built
        else:
            fn, cand_args = built, args
        cand_ref = ref if cand_args is args else oracle(*cand_args)
        try:
            out = fn(*cand_args)
        except Exception:  # noqa: BLE001 — an untileable candidate is
            # a rejection, not a search abort
            rejected += 1
            if pm._enabled:
                pm.KERNEL_AUTOTUNE_REJECTED_PARITY.labels(kernel).inc()
            continue
        if not _parity_ok(out, cand_ref, rtol, atol):
            rejected += 1
            if pm._enabled:
                pm.KERNEL_AUTOTUNE_REJECTED_PARITY.labels(kernel).inc()
            continue
        t = timer(fn, cand_args, repeats)
        tried += 1
        timings.append((dict(cfg), t))
        if t < best_t:
            best_cfg, best_t = dict(cfg), t
    elapsed = time.perf_counter() - t_start
    if pm._enabled:
        pm.KERNEL_AUTOTUNE_SEARCH_SECONDS.labels(kernel).inc(elapsed)
    if best_cfg is None:
        raise ValueError(
            f"kernel autotune: no candidate for '{kernel}' passed the "
            f"parity gate ({rejected} rejected)")
    info = {"seconds": best_t, "tried": tried, "rejected": rejected,
            "search_seconds": round(elapsed, 4)}
    if meta:
        info.update(meta)
    if persist:
        record(kernel, bucket, dtype, best_cfg, meta=info)
    return SearchResult(best_cfg, best_t, tried, rejected, elapsed,
                        timings)


#: kernel name -> searcher(bucket, dtype) -> SearchResult. Registered
#: lazily by `_default_searcher` so `ensure()` can run the matching
#: search on a miss under mode() == "tune" without the kernel modules
#: importing this one at definition time (they already do the reverse).
SEARCHERS = {}


def _default_searcher(kernel, bucket, dtype, budget_s):
    """The registered search for a kernel key, or None. These are the
    HOST-level entry points (engine build time, seed tool) — trace-time
    hooks stay cache-only so a jit trace never launches a search."""
    if not SEARCHERS:
        from . import flash_attention as _fa
        from . import grouped_matmul as _gmm
        from . import paged_attention as _pa
        SEARCHERS.update({
            "paged_ragged": lambda b, d, t: _pa.tune_paged_kernel(
                "paged_ragged", *b, dtype=d, budget_s=t),
            "paged_verify": lambda b, d, t: _pa.tune_paged_kernel(
                "paged_verify", *b, dtype=d, budget_s=t),
            "paged_decode": lambda b, d, t: _pa.tune_paged_kernel(
                "paged_decode", *b, dtype=d, budget_s=t),
            # block-sparse decode (ISSUE 15): 6-dim bucket — the last
            # axis is the shortened-table width (sparsity budget B)
            "paged_sparse": lambda b, d, t: _pa.tune_paged_sparse(
                *b, dtype=d, budget_s=t),
            "paged_block_size": lambda b, d, t: _pa.tune_block_size(
                *b, dtype=d, budget_s=t),
            "flash_fwd": lambda b, d, t: _fa.tune_flash(
                b[0], b[1], dtype=d, budget_s=t),
            "splash": lambda b, d, t: _fa.tune_splash(
                b[0], head_dim=(b[2] if len(b) > 2 else 128),
                dtype=d, budget_s=t),
            "grouped_matmul": lambda b, d, t: _gmm.tune_grouped_matmul(
                *b, dtype=d, budget_s=t),
        })
    fn = SEARCHERS.get(kernel)
    if fn is None:
        return None
    return lambda: fn(tuple(bucket), dtype, budget_s)


def ensure(kernel, bucket, dtype, default, searcher=None,
           budget_s=20.0):
    """Cache-or-default lookup with opt-in search-on-miss: a hit costs
    one dict probe (the zero-search-cost contract); a miss returns the
    default unless mode() == "tune", in which case the given `searcher`
    thunk — or the kernel's registered default search (`SEARCHERS`) —
    runs once under `budget_s` and its winner is cached. Callers on
    the serving path invoke this at BUILD time (before/outside the
    jitted step), so tuning never runs inside a trace."""
    cfg = kernel_config(kernel, bucket, dtype, default=None)
    if cfg is not None:
        return cfg
    if mode() == "tune":
        if searcher is None:
            searcher = _default_searcher(kernel, bucket, dtype,
                                         budget_s)
        if searcher is not None:
            try:
                return dict(searcher().config)
            except Exception:  # noqa: BLE001 — tuning must degrade to
                # the hand-picked default, never take serving down
                return default
    return default


# ---------------------------------------------------------------------
# per-kernel search spaces (the tunable axes of each Pallas entry)
# ---------------------------------------------------------------------


def flash_candidates(seq_len, head_dim):
    """Hand flash-attention forward kernel: (block_q, block_k) tiles.
    Divisibility keeps the grid exact (the kernel refuses remainders);
    the default (256, 256) is always candidate 0 so an empty search
    can never lose it."""
    opts = [b for b in (128, 256, 512, 1024)
            if seq_len % b == 0 and b <= seq_len]
    if not opts:
        opts = [seq_len]
    cands = [{"block_q": 256, "block_k": 256}]
    for bq in opts:
        for bk in opts:
            c = {"block_q": bq, "block_k": bk}
            if c not in cands:
                cands.append(c)
    return cands


def splash_candidates(seq_len):
    """Splash attention: the six block sizes of `sk.BlockSizes`
    (fwd q/kv/kv_compute + fused-bwd dq/kv/kv_compute), the axes the
    r5 hand sweep walked one point of (`PADDLE_TPU_SPLASH_BLOCKS`)."""
    full = next((b for b in (1024, 512, 256, 128)
                 if seq_len % b == 0), seq_len)
    opts = sorted({min(b, full) for b in (128, 256, 512, full)})
    cands = []
    # current hand-picked default first (flash_attention._splash_kernel)
    bq0 = min(512, full)
    cands.append({"block_q": bq0, "block_kv": full,
                  "block_kv_compute": bq0, "block_q_dkv": bq0,
                  "block_kv_dkv": full, "block_kv_dkv_compute": full})
    for bq in opts:
        for bkvc in opts:
            c = {"block_q": bq, "block_kv": full,
                 "block_kv_compute": bkvc, "block_q_dkv": bq,
                 "block_kv_dkv": full, "block_kv_dkv_compute": bkvc}
            if c not in cands:
                cands.append(c)
    return cands


#: grid-layout / pipeline variants for the paged family: how Mosaic
#: may schedule the (group, kv-block) grid. The kv-block axis carries
#: the online-softmax carry, so it is always "arbitrary" (sequential);
#: the group axis can be declared parallel, letting the pipeline
#: overlap groups, or left arbitrary (the conservative default).
PAGED_DIMENSION_SEMANTICS = (
    ("arbitrary", "arbitrary"),
    ("parallel", "arbitrary"),
)


def paged_candidates():
    return [{"dimension_semantics": list(ds)}
            for ds in PAGED_DIMENSION_SEMANTICS]


def paged_block_size_candidates(head_dim, max_seq_len=None):
    """Engine-level KV block-size axis (`ServingEngine(block_size=
    "auto")`): every candidate must satisfy the SAME alignment
    predicate the serve-time dispatch gate enforces — a tuned block
    size the gate would refuse can never be admitted, by construction
    (they share `paged_alignment_ok`). Sublane alignment is enforced
    even when tuning on a backend whose XLA path would accept any
    size: a CPU-tuned cache must stay admissible on the TPU gate.
    (`head_dim` is part of the bucket identity but does not constrain
    the block-size axis — the predicate factors per axis.)"""
    del head_dim
    cands = []
    for bs in (8, 16, 32, 64):
        if max_seq_len is not None and bs > max_seq_len:
            continue
        if not paged_alignment_ok(LANE_ALIGN, bs):
            continue
        cands.append({"block_size": bs})
    return cands or [{"block_size": 16}]


def grouped_matmul_candidates(E, C, D, F):
    """Grouped-expert matmul: (block_c, block_f, block_d) tiles over
    the (expert, capacity, out-features) grid with a sequential
    D-reduction axis. Targets clamp to the largest divisor of the
    axis, so every candidate tiles exactly."""
    def divisors(n, targets):
        out = []
        for t in targets:
            d = min(t, n)
            while n % d:
                d -= 1
            if d >= 1 and d not in out:
                out.append(d)
        return out

    cands = []
    for bc in divisors(C, (128, 256, 512, C)):
        for bf in divisors(F, (128, 256, 512, F)):
            for bd in divisors(D, (256, 512, D)):
                c = {"block_c": bc, "block_f": bf, "block_d": bd}
                if c not in cands:
                    cands.append(c)
    return cands


SEARCH_SPACES = {
    "flash_fwd": flash_candidates,
    "splash": splash_candidates,
    "paged_ragged": paged_candidates,
    "paged_verify": paged_candidates,
    "paged_decode": paged_candidates,
    "paged_sparse": paged_candidates,
    "paged_block_size": paged_block_size_candidates,
    "grouped_matmul": grouped_matmul_candidates,
}
