"""Block-table-native Pallas TPU kernels for paged attention.

The serving engine's three attention shapes — ragged chunked prefill
(one query per flat token), K-wide speculative verify (K consecutive
queries per slot) and K=1 decode — all reduce to ONE grouped pattern:
`G` queries that share a slot attend that slot's paged K/V at key
positions `<= their own`. The pure-XLA paths in
`ops.pallas.flash_attention` gather the slot's whole block list into a
contiguous `[S_max, H, Dh]` copy before attending; these kernels never
materialize that copy. Instead the grid iterates the
`[max_slots, max_blocks]` block tables directly:

* the block tables, owning-slot ids and per-query positions ride in
  **scalar memory** (`pltpu.PrefetchScalarGridSpec`), so each grid
  step's KV tile address is computed from the table BEFORE the body
  runs and Pallas double-buffers the `[block_size, H, Dh]` tile fetch
  against compute;
* the body runs **online softmax** (running max / denominator /
  weighted accumulator in VMEM scratch) over one KV block per grid
  step — peak live KV is one tile per buffer, not one sequence;
* **per-slot context-length masking** zeroes keys past the query's
  position, which also guarantees the NULL block's garbage and the
  unwritten tail of the newest block are never read through;
* KV tiles past the query group's last needed block are skipped with
  `pl.when` (the grid is rectangular over `max_blocks`, real work is
  ragged).

Quantized pools: with `k_scale`/`v_scale` (`[NB, BS, H]` fp32,
per-pool-entry-per-head — see `serving.kv_cache.PagedKVCache`), the
K/V tiles arrive int8 and are dequantized INSIDE the kernel right
after the tile load; the scale tiles ride the same block-table index
maps as the pools, so quantization adds two small scalar-indexed
fetches and two VPU multiplies per tile and nothing else changes.

The XLA gather paths stay the CPU parity oracles and the
`PADDLE_TPU_PAGED_PALLAS=0` fallback; `tests/test_paged_kernels.py`
runs every (shape x dtype) cell of this module against them in
interpret mode.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import autotune

# finite mask value: -inf would NaN the running-max rescale on fully
# masked tiles (exp(-inf - -inf)); matches jax's paged kernel choice
MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max) / 1e6  # ~-3.4e32/1e6

# Set by tests to run the kernels in Pallas interpret mode on the CPU
# mesh (exercises the real block-table/scalar-prefetch plumbing
# without a TPU).
_INTERPRET = False


def _on_tpu_backend() -> bool:
    from ...core.place import on_tpu_backend
    return on_tpu_backend()


def pallas_killed() -> bool:
    """True when `PADDLE_TPU_PAGED_PALLAS=0` is set: the operator asked
    for the pure-XLA gather reference on EVERY paged-attention entry —
    including jax's library decode kernel, not just these kernels — so
    a Pallas miscompile can be ruled out with one env var."""
    return os.environ.get("PADDLE_TPU_PAGED_PALLAS", "1") == "0"


def paged_pallas_enabled(head_dim, block_size) -> bool:
    """Dispatch gate for the block-table-native kernels.

    Env kill-switch first (`PADDLE_TPU_PAGED_PALLAS=0` restores the
    XLA gather paths everywhere), then backend/shape: on a TPU backend
    the kernels want a lane-aligned head_dim and a sublane-aligned
    block size so KV tiles hit full (8/32 x 128) registers; under
    `_INTERPRET` (tests) any shape runs. The alignment predicate is
    `autotune.paged_alignment_ok` — the SAME source of truth the
    kernel tuner's candidate filters use, so a tuned candidate the
    serve-time gate would refuse cannot exist (ISSUE 11)."""
    if pallas_killed():
        return False
    if _INTERPRET:
        return True
    return (_on_tpu_backend()
            and autotune.paged_alignment_ok(head_dim, block_size))


def _group_positions(pos_ref, g, G):
    """The group's G query positions as a [G] vector. G is static and
    tiny (1, or draft_k+1), so per-element SMEM reads unroll."""
    return jnp.stack([pos_ref[g, j] for j in range(G)])


def _paged_attend_kernel(slot_ref, bt_ref, pos_ref, q_ref, k_ref, v_ref,
                         *rest, block_size, G, quantized):
    """One (group, kv-block) grid cell.

    Refs: scalar-prefetch (slots [N], block tables [S, MB], positions
    [N, G]); q tile [1, G, H, Dh]; k/v tiles [1, BS, H, Dh] (int8 when
    quantized, + [1, BS, H] fp32 scale tiles); out tile [1, G, H, Dh];
    scratch m/l [H, G] and acc [H, G, Dh] carried across the kv-block
    grid axis."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    g = pl.program_id(0)
    b = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(b == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, MASK_VALUE)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = _group_positions(pos_ref, g, G)            # [G] int32
    max_pos = pos[G - 1] if G > 1 else pos[0]
    # positions within a verify group ascend, but take the true max so
    # the skip never depends on that packing detail
    for j in range(G - 1):
        max_pos = jnp.maximum(max_pos, pos[j])

    @pl.when(b * block_size <= max_pos)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)             # [G, H, Dh]
        k = k_ref[0].astype(jnp.float32)             # [BS, H, Dh]
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0].astype(jnp.float32)[..., None]
            v = v * vs_ref[0].astype(jnp.float32)[..., None]
        # [H, G, BS] logits: one MXU contraction per head over Dh
        s = jax.lax.dot_general(
            jnp.swapaxes(q, 0, 1), jnp.swapaxes(k, 0, 1),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        key_pos = b * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (G, block_size), 1)           # [G, BS]
        keep = key_pos <= pos[:, None]               # [G, BS]
        s = jnp.where(keep[None], s, MASK_VALUE)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_cur = jnp.max(s, axis=-1)                  # [H, G]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # explicit zeroing: on an all-masked tile s == m_new == MASK
        # and exp(0) would otherwise count the mask as probability 1
        p = jnp.exp(s - m_new[..., None]) * keep[None].astype(jnp.float32)
        m_ref[...] = m_new
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_ref[...] = (acc_ref[...] * alpha[..., None]
                        + jax.lax.dot_general(
                            p, jnp.swapaxes(v, 0, 1),
                            (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32))

    @pl.when(b == nb - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)[..., None]    # [H, G, 1]
        out = acc_ref[...] / l                           # [H, G, Dh]
        o_ref[0] = jnp.swapaxes(out, 0, 1).astype(o_ref.dtype)


def _paged_attend_grouped(q, k_pool, v_pool, block_tables, slot_ids,
                          positions, k_scale=None, v_scale=None, *,
                          scale=None, kernel_name="paged_ragged",
                          tuning=None):
    """Grouped block-table-native attention.

    q [N, G, H, Dh]; k_pool/v_pool [NB, BS, H, Dh]; block_tables
    [S, MB] int32; slot_ids [N] int32 (-1 = padding group); positions
    [N, G] int32. Optional k_scale/v_scale [NB, BS, H] fp32 dequantize
    int8 pools inside the kernel. Returns [N, G, H, Dh] in q.dtype.

    `kernel_name` keys the autotuner lookup: the tuned grid-layout
    config (`dimension_semantics` — whether Mosaic may treat the
    group axis as parallel) is resolved HERE, at trace time, so a
    cached winner costs one dict probe inside the one compile and
    nothing per step. The block-sparse decode entry ("paged_sparse",
    ISSUE 15) is this same kernel fed a SHORTENED per-slot block table
    — the table width IS the sparsity budget, so its cache bucket
    carries MB where the dense entries' buckets do not."""
    N, G, H, Dh = q.shape
    NB, BS = k_pool.shape[0], k_pool.shape[1]
    S, MB = block_tables.shape
    quantized = k_scale is not None
    if scale is None:
        scale = 1.0 / math.sqrt(Dh)
    if kernel_name == "paged_sparse":
        bucket = autotune.shape_bucket(N, G, H, Dh, BS, MB)
    else:
        bucket = autotune.shape_bucket(N, G, H, Dh, BS)
    tuned = tuning if tuning is not None else autotune.kernel_config(
        kernel_name, bucket, k_pool.dtype, default=None) or {}
    dim_sem = tuned.get("dimension_semantics")
    compiler_params = None
    if dim_sem is not None:
        compiler_params = pltpu.TPUCompilerParams(
            dimension_semantics=tuple(dim_sem))
    qs = (q.astype(jnp.float32) * scale).astype(
        q.dtype if q.dtype != jnp.float64 else jnp.float32)

    def pool_map(g, b, slots, bt, pos):
        # padding groups (slot -1) clamp to slot 0; their table entries
        # may be NULL — the position mask hides whatever is fetched
        return (bt[jnp.maximum(slots[g], 0), b], 0, 0, 0)

    def scale_map(g, b, slots, bt, pos):
        return (bt[jnp.maximum(slots[g], 0), b], 0, 0)

    in_specs = [
        pl.BlockSpec((1, G, H, Dh), lambda g, b, *_: (g, 0, 0, 0)),
        pl.BlockSpec((1, BS, H, Dh), pool_map),
        pl.BlockSpec((1, BS, H, Dh), pool_map),
    ]
    args = [qs, k_pool, v_pool]
    if quantized:
        in_specs += [pl.BlockSpec((1, BS, H), scale_map),
                     pl.BlockSpec((1, BS, H), scale_map)]
        args += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(N, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, G, H, Dh),
                               lambda g, b, *_: (g, 0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((H, G), jnp.float32),
                        pltpu.VMEM((H, G), jnp.float32),
                        pltpu.VMEM((H, G, Dh), jnp.float32)],
    )
    kernel = functools.partial(
        _paged_attend_kernel, block_size=BS, G=G, quantized=quantized)
    extra = {}
    if compiler_params is not None:
        extra["compiler_params"] = compiler_params
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, G, H, Dh), q.dtype),
        interpret=_INTERPRET, **extra,
        cost_estimate=pl.CostEstimate(
            flops=4 * N * G * H * Dh * MB * BS,
            bytes_accessed=(2 * N * MB * BS * H * Dh
                            * k_pool.dtype.itemsize
                            + 2 * N * G * H * Dh * q.dtype.itemsize),
            transcendentals=N * G * H * MB * BS),
    )(slot_ids.astype(jnp.int32), block_tables.astype(jnp.int32),
      positions.astype(jnp.int32), *args)


# --------------------------------------------------------------- entries


def ragged_attend(q, k_pool, v_pool, block_tables, slot_ids, positions,
                  k_scale=None, v_scale=None, *, scale=None,
                  kernel_name="paged_ragged"):
    """Flat-token ragged paged attention (chunked prefill + plain
    decode): q [T, H, Dh], one G=1 group per flat token. Signature
    mirrors `flash_attention.ragged_paged_attention`. The sparse
    decode region passes `kernel_name="paged_sparse"` with its
    shortened tables so tuned configs resolve under the sparse key."""
    T = q.shape[0]
    out = _paged_attend_grouped(
        q[:, None], k_pool, v_pool, block_tables, slot_ids,
        positions.reshape(T, 1), k_scale, v_scale, scale=scale,
        kernel_name=kernel_name)
    return out[:, 0]


def verify_attend(q, k_pool, v_pool, block_tables, slot_ids, positions,
                  k_scale=None, v_scale=None, *, scale=None,
                  kernel_name="paged_verify"):
    """K-wide speculative verify: q [B, K, H, Dh], positions [B, K] —
    one G=K group per slot, ONE block-table walk per group."""
    return _paged_attend_grouped(
        q, k_pool, v_pool, block_tables, slot_ids, positions,
        k_scale, v_scale, scale=scale, kernel_name=kernel_name)


def decode_attend(q, k_pool, v_pool, block_tables, context_lens,
                  k_scale=None, v_scale=None, *, scale=None):
    """K=1 decode: q [B, H, Dh], one query per slot attending its first
    `context_lens[b]` cached tokens."""
    B = q.shape[0]
    positions = (context_lens.astype(jnp.int32) - 1).reshape(B, 1)
    out = _paged_attend_grouped(
        q[:, None], k_pool, v_pool, block_tables,
        jnp.arange(B, dtype=jnp.int32), positions,
        k_scale, v_scale, scale=scale, kernel_name="paged_decode")
    return out[:, 0]


# ----------------------------------------------------------- autotuning


def _synth_paged_inputs(N, G, H, Dh, BS, context_len, dtype, seed):
    """Deterministic synthetic pools/tables/queries for one paged
    shape bucket (the tuner's measurement workload). `dtype` is the
    POOL dtype: int8/float8_e4m3fn build quantized pools with
    per-entry-per-head fp32 scales (the `kv_dtype="int8"`/"fp8_e4m3"
    serving layouts) under fp32 queries; otherwise scales are None."""
    import numpy as np
    rng = np.random.RandomState(seed)
    mb = -(-int(context_len) // BS)
    NB = N * mb + 1
    dtype = np.dtype(dtype)
    quant = dtype.itemsize == 1       # int8 or a scaled fp8 format
    qdt = np.float32 if quant else dtype
    q = jnp.asarray(rng.randn(N, G, H, Dh).astype(qdt))
    if quant:
        if dtype == np.int8:
            kp = jnp.asarray(rng.randint(-127, 128, (NB, BS, H, Dh))
                             .astype(np.int8))
            vp = jnp.asarray(rng.randint(-127, 128, (NB, BS, H, Dh))
                             .astype(np.int8))
        else:
            # fp8: stay inside the e4m3 finite range (casts past 448
            # produce NaN, which would poison the parity oracle)
            kp = jnp.asarray(np.clip(rng.randn(NB, BS, H, Dh) * 100,
                                     -440, 440).astype(np.float32)
                             ).astype(dtype)
            vp = jnp.asarray(np.clip(rng.randn(NB, BS, H, Dh) * 100,
                                     -440, 440).astype(np.float32)
                             ).astype(dtype)
        ks = jnp.asarray((np.abs(rng.randn(NB, BS, H)) * 0.02
                          + 0.005).astype(np.float32))
        vs = jnp.asarray((np.abs(rng.randn(NB, BS, H)) * 0.02
                          + 0.005).astype(np.float32))
    else:
        kp = jnp.asarray(rng.randn(NB, BS, H, Dh).astype(dtype))
        vp = jnp.asarray(rng.randn(NB, BS, H, Dh).astype(dtype))
        ks = vs = None
    bt = jnp.asarray(
        1 + np.arange(N * mb, dtype=np.int32).reshape(N, mb))
    slots = jnp.arange(N, dtype=jnp.int32)
    pos = jnp.asarray(
        np.clip(context_len - 1 - np.arange(G)[::-1], 0,
                context_len - 1).astype(np.int32)[None].repeat(N, 0))
    return q, kp, vp, bt, slots, pos, ks, vs


def tune_paged_kernel(kernel_name, N, G, H, Dh, BS, *,
                      context_len=None, dtype="float32", seed=0,
                      budget_s=None, timer=None, persist=True):
    """Search the grid-layout space of one paged-attention bucket.

    Candidates run the REAL block-table kernel (interpret mode off-TPU
    — the same plumbing tier-1 parity uses) against the XLA gather
    oracle; the winner lands in the persistent cache under
    `(kernel_name, shape_bucket(N, G, H, Dh, BS), dtype, backend)` so
    the serving engine's next trace picks it up for free."""
    import numpy as np
    from . import flash_attention as fa

    global _INTERPRET
    dtype = np.dtype(dtype)
    context_len = int(context_len or 4 * BS)
    args = _synth_paged_inputs(N, G, H, Dh, BS, context_len,
                               dtype, seed)

    def oracle(q, kp, vp, bt, slots, pos, ks, vs):
        if G == 1:
            return fa.ragged_gather_reference(q[:, 0], kp, vp, bt,
                                              slots, pos[:, 0], ks, vs)
        return fa.verify_gather_reference(q, kp, vp, bt, slots, pos,
                                          ks, vs)

    def build(cfg):
        def run(q, kp, vp, bt, slots, pos, ks, vs):
            out = _paged_attend_grouped(q, kp, vp, bt, slots, pos,
                                        ks, vs,
                                        kernel_name=kernel_name,
                                        tuning=cfg)
            return out[:, 0] if G == 1 else out
        return run

    was = _INTERPRET
    if not _on_tpu_backend():
        _INTERPRET = True
    try:
        return autotune.search(
            kernel_name, autotune.shape_bucket(N, G, H, Dh, BS), dtype,
            autotune.paged_candidates(), build, args, oracle,
            rtol=2e-2, atol=2e-2, budget_s=budget_s, timer=timer,
            persist=persist,
            meta={"context_len": context_len, "seed": seed})
    finally:
        _INTERPRET = was


def tune_paged_sparse(N, G, H, Dh, BS, B, *, dtype="float32", seed=0,
                      budget_s=None, timer=None, persist=True):
    """Search the grid-layout space of the BLOCK-SPARSE decode bucket
    (ISSUE 15): the same grouped kernel fed a shortened `[N, B]` block
    table — the table width IS the sparsity budget, so the bucket key
    carries B (`shape_bucket(N, G, H, Dh, BS, B)`) and a tuned dense
    entry can never alias a sparse one. The measurement workload holds
    exactly B resident blocks per slot (context_len = B * BS), which
    is what the serving engine's compacted-position masking reduces
    the sparse region to."""
    import numpy as np
    from . import flash_attention as fa

    global _INTERPRET
    dtype = np.dtype(dtype)
    args = _synth_paged_inputs(N, G, H, Dh, BS, int(B) * BS,
                               dtype, seed)

    def oracle(q, kp, vp, bt, slots, pos, ks, vs):
        if G == 1:
            return fa.ragged_gather_reference(q[:, 0], kp, vp, bt,
                                              slots, pos[:, 0], ks, vs)
        return fa.verify_gather_reference(q, kp, vp, bt, slots, pos,
                                          ks, vs)

    def build(cfg):
        def run(q, kp, vp, bt, slots, pos, ks, vs):
            out = _paged_attend_grouped(q, kp, vp, bt, slots, pos,
                                        ks, vs,
                                        kernel_name="paged_sparse",
                                        tuning=cfg)
            return out[:, 0] if G == 1 else out
        return run

    was = _INTERPRET
    if not _on_tpu_backend():
        _INTERPRET = True
    try:
        return autotune.search(
            "paged_sparse", autotune.shape_bucket(N, G, H, Dh, BS, B),
            dtype, autotune.paged_candidates(), build, args, oracle,
            rtol=2e-2, atol=2e-2, budget_s=budget_s, timer=timer,
            persist=persist, meta={"sparse_blocks": int(B),
                                   "seed": seed})
    finally:
        _INTERPRET = was


def tune_block_size(max_slots, H, Dh, *, context_len=64,
                    dtype="float32", seed=0, budget_s=None,
                    timer=None, persist=True):
    """Search the ENGINE-level KV block-size axis: each candidate
    re-shapes the pools (`NB = slots * ceil(ctx / BS) + 1`) and times
    decode-shaped ragged attention over them; parity holds per
    candidate against the gather oracle on the candidate's own pools.
    Candidates come from `autotune.paged_block_size_candidates` — the
    SAME alignment predicate as the serve-time dispatch gate, so the
    cached winner is admissible wherever the kernels are
    (`ServingEngine(block_size="auto")` reads the result)."""
    import numpy as np
    from . import flash_attention as fa

    global _INTERPRET
    dtype = np.dtype(dtype)

    def oracle(q, kp, vp, bt, slots, pos, ks, vs):
        return fa.ragged_gather_reference(q[:, 0], kp, vp, bt, slots,
                                          pos[:, 0], ks, vs)

    def build(cfg):
        bs = int(cfg["block_size"])
        cand_args = _synth_paged_inputs(max_slots, 1, H, Dh, bs,
                                        context_len, dtype, seed)

        def run(q, kp, vp, bt, slots, pos, ks, vs):
            if paged_pallas_enabled(Dh, bs):
                out = _paged_attend_grouped(q, kp, vp, bt, slots, pos,
                                            ks, vs,
                                            kernel_name="paged_decode")
                return out[:, 0]
            return fa.ragged_gather_reference(q[:, 0], kp, vp, bt,
                                              slots, pos[:, 0], ks, vs)
        return run, cand_args

    was = _INTERPRET
    if not _on_tpu_backend():
        _INTERPRET = True
    try:
        return autotune.search(
            "paged_block_size", autotune.shape_bucket(max_slots, H, Dh),
            dtype,
            autotune.paged_block_size_candidates(Dh, context_len),
            build, None, oracle, rtol=2e-2, atol=2e-2,
            budget_s=budget_s, timer=timer, persist=persist,
            meta={"context_len": int(context_len), "seed": seed})
    finally:
        _INTERPRET = was
