"""Flash attention as Pallas TPU kernels.

Parity: the reference's FlashAttention integration
(`paddle/phi/kernels/flash_attn_kernel.h`, `cmake/external/flashattn.cmake`,
`python/paddle/nn/functional/flash_attention.py:142`) — re-implemented as
TPU-native online-softmax kernels instead of the CUDA library.

Two tiers:

* `splash_mha` — the production path: jax's Pallas *splash attention*
  kernel (fwd + fused dkv/dq backward, causal block-skipping), tuned
  block sizes for v5e. Trace-measured 2.1x faster fwd+bwd than XLA's
  fused attention at [32,16,1024,64] and the engine behind the GPT
  training headline (see docs/gpt_perf_analysis.md). Falls back to
  XLA's `jax.nn.dot_product_attention` off-TPU (the CPU test mesh) or
  for shapes the kernel doesn't tile.
* `flash_attention` — the hand-written educational fwd kernel kept for
  the paddle [B, S, H, D] API surface; backward recomputes in XLA.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


# ---------------------------------------------------------------------------
# splash attention (library Pallas kernel, fused backward) — production path
# ---------------------------------------------------------------------------

_SPLASH_CACHE = {}

# (seq_len, head_dim) combos the installed kernel refused at trace time
_SPLASH_REFUSED = set()

# Set by tests to run the splash kernel in Pallas interpret mode on the
# CPU mesh (exercises the real mask/segment plumbing without a TPU).
_INTERPRET = False


def _on_tpu_backend() -> bool:
    if _INTERPRET:
        return True
    from ...core.place import on_tpu_backend
    return on_tpu_backend()


_SPLASH_DIM_QUANTUM = None


def splash_head_dim_quantum() -> int:
    """head_dim multiple the INSTALLED splash kernel accepts.

    jax 0.4.x's kernel refuses head_dim % 128 != 0 at trace time
    ("head_dim=64 should be a multiple of 128") where newer kernels
    pad 64-multiples — probed ONCE by `jax.eval_shape`-tracing a
    minimal kernel at head_dim 64 (abstract eval only: no device work,
    no compile), so `splash_supported` can gate unsupported shapes to
    the XLA path at the callsite instead of relying on the
    trace-and-refuse `_SPLASH_REFUSED` machinery below (which stays as
    the belt-and-braces net for refusals this probe can't predict)."""
    global _SPLASH_DIM_QUANTUM
    if _SPLASH_DIM_QUANTUM is None:
        try:
            from jax.experimental.pallas.ops.tpu.splash_attention import (
                splash_attention_kernel as sk,
                splash_attention_mask as smask)
            mask = smask.MultiHeadMask([smask.CausalMask((128, 128))])
            kern = jax.vmap(sk.make_splash_mha(
                mask, head_shards=1, q_seq_shards=1, interpret=True))
            probe = jax.ShapeDtypeStruct((1, 1, 128, 64), jnp.float32)
            jax.eval_shape(kern, probe, probe, probe)
            _SPLASH_DIM_QUANTUM = 64
        except Exception:  # noqa: BLE001 — the gate must never raise:
            # NotImplementedError is the known 0.4.x refusal, but a
            # moved module path (ImportError) or a different refusal
            # type must also degrade to "128-multiples only", keeping
            # splash_supported a pure fallback decision.
            _SPLASH_DIM_QUANTUM = 128
    return _SPLASH_DIM_QUANTUM


def splash_supported(seq_len: int, head_dim: int) -> bool:
    """Static gate for the splash kernel: lane-aligned sequence and a
    head_dim the installed kernel actually tiles (64-multiples only
    where the kernel pads them — jax 0.4.x wants 128)."""
    return (_on_tpu_backend() and seq_len % 128 == 0
            and head_dim % splash_head_dim_quantum() == 0
            and seq_len >= 128)


def _splash_kernel(n_heads: int, seq_len: int, causal: bool,
                   segmented: bool = False,
                   residual_ckpt: str | None = None,
                   dtype: str = "float32", head_dim: int = 128):
    """Build (and cache) a vmapped splash kernel for [B, H, S, D] inputs.

    Block sizes: the largest power-of-two tile <= 1024 dividing S, with
    the fused dkv backward — measured fastest on v5e at S=1024 (5.0
    ms/layer fwd+bwd vs 10.6 for XLA's attention at [32,16,1024,64]).

    `segmented=True` builds the variant taking per-position segment ids
    (key-padding / ragged batches): position i attends j iff their
    segment ids match, fused into the same kernel (the TPU answer to the
    reference's varlen `flash_attn_unpadded` cu_seqlens path,
    `python/paddle/nn/functional/flash_attention.py:327`)."""
    import os
    block = next(b for b in (1024, 512, 256, 128) if seq_len % b == 0)
    # experiment override: "bq,bkv,bkvc,bqd,bkvd,bkvdc"
    env = os.environ.get("PADDLE_TPU_SPLASH_BLOCKS", "")
    # r5 in-model sweep at [32,16,1024,64] (tools/gpt_microbench.py):
    # fwd q-block 512 with full kv tiles but kv_compute 512, bwd
    # dq-block 512 / full kv — 836.5 vs 853.6 ms/step for the old
    # uniform-1024 fwd config; uniform 512 and q=256 were worse.
    # The autotuner ("splash" kernel space) supersedes the hand sweep
    # when a cached winner exists for the bucket; the env override
    # stays the top-priority experiment knob.
    bq = min(512, block)
    sizes = [bq, block, bq, bq, block, block]
    from . import autotune as _autotune
    _tuned = _autotune.kernel_config(
        "splash", _autotune.shape_bucket(seq_len, block, head_dim),
        dtype, default=None)
    if _tuned:
        sizes = [min(int(_tuned.get(k, s)), block) for k, s in zip(
            ("block_q", "block_kv", "block_kv_compute", "block_q_dkv",
             "block_kv_dkv", "block_kv_dkv_compute"), sizes)]
    key = (n_heads, seq_len, causal, block, segmented, residual_ckpt,
           env, tuple(sizes), _INTERPRET)
    if key not in _SPLASH_CACHE:
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sk, splash_attention_mask as smask)
        if env:
            parts = env.split(",")
            if len(parts) != 6:
                raise ValueError(
                    "PADDLE_TPU_SPLASH_BLOCKS wants 6 comma-separated "
                    "ints: bq,bkv,bkv_compute,bq_dkv,bkv_dkv,"
                    f"bkv_dkv_compute (got {env!r})")
            sizes = [min(int(x), block) for x in parts]
        bs = sk.BlockSizes(
            block_q=sizes[0], block_kv=sizes[1], block_kv_compute=sizes[2],
            block_q_dkv=sizes[3], block_kv_dkv=sizes[4],
            block_kv_dkv_compute=sizes[5],
            use_fused_bwd_kernel=True)
        m = (smask.CausalMask((seq_len, seq_len)) if causal
             else smask.FullMask((seq_len, seq_len)))
        mask = smask.MultiHeadMask([m] * n_heads)
        kern = sk.make_splash_mha(mask, head_shards=1, q_seq_shards=1,
                                  block_sizes=bs, interpret=_INTERPRET,
                                  residual_checkpoint_name=residual_ckpt)
        if segmented:
            _SPLASH_CACHE[key] = jax.vmap(
                lambda q, k, v, seg: kern(q, k, v, segment_ids=seg))
        else:
            _SPLASH_CACHE[key] = jax.vmap(kern)
    return _SPLASH_CACHE[key]


SPLASH_RESIDUAL_NAME = "splash_residuals"


def splash_mha(q, k, v, *, causal=True, scale=None, kv_keep=None,
               save_residuals_for_remat=False):
    """Multi-head self-attention on [B, H, S, D] tensors (q and k/v
    must share S — causal alignment for a shorter decode-style q is a
    different op; use the general masked path in
    `nn.functional.scaled_dot_product_attention` for KV-cache decode).

    `kv_keep`: optional [B, S] key-padding mask (nonzero = real token).
    Folded into the kernel as segment ids — real tokens are segment 1,
    padding segment 0, so real queries attend exactly the real keys.
    Padded query rows attend (only) other padded rows; their outputs are
    garbage by contract, exactly like the reference's varlen flash path
    where padded rows are never read back.

    `save_residuals_for_remat`: tag the kernel's saved residuals (out +
    logsumexp) with `checkpoint_name(SPLASH_RESIDUAL_NAME)` so a
    surrounding `jax.checkpoint(policy=save_only_these_names(
    SPLASH_RESIDUAL_NAME))` keeps them across the backward instead of
    re-running the attention forward during remat (the reference keeps
    softmax_lse for the same reason, `flash_attn_kernel.h:21`).

    TPU: splash Pallas kernel (fwd + fused backward). Off-TPU or for
    non-tileable shapes: XLA's fused attention. Differentiable either
    way."""
    b, h, s, d = q.shape
    if k.shape[2] != s or v.shape[2] != s:
        raise ValueError(
            f"splash_mha requires equal q/kv sequence lengths, got "
            f"q S={s}, k S={k.shape[2]}, v S={v.shape[2]}")
    if k.shape[1] != h or v.shape[1] != h:
        raise ValueError(
            f"splash_mha requires equal q/kv head counts (no GQA/MQA), "
            f"got q H={h}, k H={k.shape[1]}, v H={v.shape[1]}")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if splash_supported(s, d) and (s, d) not in _SPLASH_REFUSED:
        try:
            qs = (q * scale).astype(q.dtype)
            rc = SPLASH_RESIDUAL_NAME if save_residuals_for_remat \
                else None
            if kv_keep is not None:
                from jax.experimental.pallas.ops.tpu.splash_attention \
                    import splash_attention_kernel as sk
                seg = kv_keep.astype(jnp.int32)
                kern = _splash_kernel(h, s, causal, segmented=True,
                                      residual_ckpt=rc,
                                      dtype=str(q.dtype), head_dim=d)
                return kern(qs, k, v, sk.SegmentIds(q=seg, kv=seg))
            kern = _splash_kernel(h, s, causal, residual_ckpt=rc,
                                  dtype=str(q.dtype), head_dim=d)
            return kern(qs, k, v)
        except NotImplementedError:
            # the installed kernel refused the shape at trace time
            # (e.g. jax 0.4.x tiles head_dim by 128 where newer
            # kernels pad 64) — remember and take the XLA path
            _SPLASH_REFUSED.add((s, d))
    mask = None
    if kv_keep is not None:
        mask = (kv_keep != 0)[:, None, None, :]  # [B, 1, 1(q), S]
    return jax.nn.dot_product_attention(
        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
        jnp.swapaxes(v, 1, 2), scale=scale, mask=mask,
        is_causal=causal).transpose(0, 2, 1, 3)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k,
                seq_len):
    # q_ref: [1, block_q, d]; k_ref/v_ref: [1, seq, d]; o_ref like q_ref
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    q_idx = pl.program_id(1)
    q = q_ref[0] * scale  # [bq, d]

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    q_start = q_idx * block_q
    if causal:
        num_k = jax.lax.div(q_start + block_q + block_k - 1, block_k)
    else:
        num_k = seq_len // block_k

    def body(ki, carry):
        m_prev, l_prev, acc_prev = carry
        k_start = ki * block_k
        k = k_ref[0, pl.ds(k_start, block_k), :]   # [bk, d]
        v = v_ref[0, pl.ds(k_start, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # [bq, bk]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_new = acc_prev * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    """q/k/v: [BH, S, D] -> [BH, S, D]."""
    bh, s, d = q.shape
    grid = (bh, s // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_len=s)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=_INTERPRET,
    )(q, k, v)


def _xla_reference(q, k, v, scale, causal):
    logits = jnp.einsum("bsd,btd->bst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), bool))
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bst,btd->bsd", p, v.astype(jnp.float32)).astype(
        q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, scale, causal, block_q, block_k):
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k)


def _flash_core_fwd(q, k, v, scale, causal, block_q, block_k):
    out = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v)


def _flash_core_bwd(scale, causal, block_q, block_k, res, g):
    # recompute-based backward in XLA (fused well by the compiler)
    q, k, v = res

    def f(q, k, v):
        return _xla_reference(q, k, v, scale, causal)
    _, vjp = jax.vjp(f, q, k, v)
    return vjp(g)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, bias=None, causal=False, scale=None,
                    block_q=None, block_k=None):
    """q/k/v: [B, S, H, D] (paddle layout). bias unsupported -> caller
    falls back to the XLA path.

    `block_q`/`block_k` default to the autotuner's cached winner for
    this (S, D) shape bucket (`ops.pallas.autotune`, kernel
    ``flash_fwd``) and to the hand-picked 256/256 on a cache miss or
    with the kill-switch set; explicit arguments always win."""
    if bias is not None:
        raise NotImplementedError("flash_attention kernel: bias "
                                  "unsupported; use the XLA path")
    b, s, h, d = q.shape
    if block_q is None or block_k is None:
        from . import autotune
        tuned = autotune.kernel_config(
            "flash_fwd", autotune.shape_bucket(s, d), q.dtype,
            default=None) or {}

        def usable(v):
            # the pow2 bucket may cover sequences its winner doesn't
            # divide (S=768 in the 1024 bucket, winner 512): such a
            # tile would demote the shape to the XLA fallback, so the
            # hand default — which the pre-tuner path served — wins
            return v is not None and s % min(int(v), s) == 0

        tq, tk = tuned.get("block_q"), tuned.get("block_k")
        block_q = block_q or (tq if usable(tq) else DEFAULT_BLOCK_Q)
        block_k = block_k or (tk if usable(tk) else DEFAULT_BLOCK_K)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q != 0 or s % block_k != 0 or d % 128 != 0:
        # grid/num_k floor-divide by the block size: a non-divisible seq
        # would silently drop trailing queries/keys — refuse so the caller
        # falls back to the XLA path
        raise NotImplementedError(
            f"flash_attention kernel needs seq divisible by block "
            f"({block_q}/{block_k}) and head_dim%128==0 (got S={s}, D={d})")
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    def to_bh(x):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)

    out = _flash_core(to_bh(q), to_bh(k), to_bh(v), float(scale),
                      bool(causal), block_q, block_k)
    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2)


def tune_flash(seq_len, head_dim, *, batch_heads=4, causal=True,
               dtype="float32", seed=0, budget_s=None, timer=None,
               persist=True):
    """Search the (block_q, block_k) space of the hand flash-forward
    kernel against the XLA softmax reference; the winner lands in the
    persistent cache so `flash_attention`'s next call resolves it for
    free (interpret mode off-TPU)."""
    import numpy as np

    from . import autotune

    global _INTERPRET
    dtype = np.dtype(dtype)
    rng = np.random.RandomState(seed)
    shape = (batch_heads, seq_len, head_dim)
    q = jnp.asarray(rng.randn(*shape).astype(dtype))
    k = jnp.asarray(rng.randn(*shape).astype(dtype))
    v = jnp.asarray(rng.randn(*shape).astype(dtype))
    scale = 1.0 / math.sqrt(head_dim)

    def oracle(q, k, v):
        return _xla_reference(q, k, v, scale, causal)

    def build(cfg):
        bq, bk = int(cfg["block_q"]), int(cfg["block_k"])
        if seq_len % bq or seq_len % bk:
            return None

        def run(q, k, v):
            return _flash_fwd(q, k, v, scale, causal, bq, bk)
        return run

    was = _INTERPRET
    if not _on_tpu_backend() or _INTERPRET:
        _INTERPRET = True
    try:
        return autotune.search(
            "flash_fwd", autotune.shape_bucket(seq_len, head_dim),
            dtype, autotune.flash_candidates(seq_len, head_dim), build,
            (q, k, v), oracle, rtol=2e-2, atol=2e-2,
            budget_s=budget_s, timer=timer, persist=persist,
            meta={"causal": bool(causal), "seed": seed})
    finally:
        _INTERPRET = was


def tune_splash(seq_len, *, n_heads=2, batch=1, head_dim=128,
                causal=True, dtype="float32", seed=0, budget_s=None,
                timer=None, persist=True):
    """Search the six splash block sizes (fwd q/kv/kv_compute +
    fused-bwd dq/kv/kv_compute) against the XLA attention oracle.
    Candidates run the REAL library kernel — value AND input grads,
    so the backward block sizes are exercised too — in interpret mode
    off-TPU; the winner lands in the cache `_splash_kernel` resolves
    at build time."""
    import numpy as np

    from . import autotune

    dtype = np.dtype(dtype)
    block = next(b for b in (1024, 512, 256, 128)
                 if seq_len % b == 0)
    rng = np.random.RandomState(seed)
    shape = (batch, n_heads, seq_len, head_dim)
    q = jnp.asarray(rng.randn(*shape).astype(dtype))
    k = jnp.asarray(rng.randn(*shape).astype(dtype))
    v = jnp.asarray(rng.randn(*shape).astype(dtype))
    scale = 1.0 / math.sqrt(head_dim)

    def oracle(q, k, v):
        def f(q, k, v):
            out = jax.nn.dot_product_attention(
                jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                jnp.swapaxes(v, 1, 2), scale=scale, is_causal=causal)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        loss, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
        return (loss,) + grads

    interp = not _on_tpu_backend() or _INTERPRET

    def build(cfg):
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sk,
            splash_attention_mask as smask)
        bs = sk.BlockSizes(
            block_q=cfg["block_q"], block_kv=cfg["block_kv"],
            block_kv_compute=cfg["block_kv_compute"],
            block_q_dkv=cfg["block_q_dkv"],
            block_kv_dkv=cfg["block_kv_dkv"],
            block_kv_dkv_compute=cfg["block_kv_dkv_compute"],
            use_fused_bwd_kernel=True)
        m = (smask.CausalMask((seq_len, seq_len)) if causal
             else smask.FullMask((seq_len, seq_len)))
        mask = smask.MultiHeadMask([m] * n_heads)
        kern = jax.vmap(sk.make_splash_mha(
            mask, head_shards=1, q_seq_shards=1, block_sizes=bs,
            interpret=interp))

        def run(q, k, v):
            def f(q, k, v):
                out = kern((q * scale).astype(q.dtype), k, v)
                return jnp.sum(out.astype(jnp.float32) ** 2)
            loss, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(
                q, k, v)
            return (loss,) + grads
        return run

    return autotune.search(
        "splash", autotune.shape_bucket(seq_len, block, head_dim),
        dtype, autotune.splash_candidates(seq_len), build, (q, k, v),
        oracle, rtol=5e-2, atol=5e-2, budget_s=budget_s, timer=timer,
        persist=persist, meta={"causal": bool(causal), "seed": seed})


# ---------------------------------------------------------------------------
# paged attention (block-paged KV cache — the serving engine's kernel)
# ---------------------------------------------------------------------------


def _check_pool_heads(name, h_q, k_pool, v_pool):
    """Queries and pools must carry the SAME head count. Under tensor
    parallelism both are the per-shard slice (`H // tp`); a mismatch
    means a caller handed a sharded pool to unsharded queries (or vice
    versa), which the einsums would otherwise mis-broadcast into
    garbage attention instead of failing."""
    if k_pool.shape[-2] != h_q or v_pool.shape[-2] != h_q:
        raise ValueError(
            f"{name}: q has {h_q} heads but k_pool/v_pool have "
            f"{k_pool.shape[-2]}/{v_pool.shape[-2]} — under tensor "
            "parallelism every operand must be the per-shard head "
            "slice (serving.distributed.tp_engine shards q and the "
            "pools together on the 'mp' axis)")


def _paged_kernel_enabled(head_dim, block_size):
    from . import paged_attention as _pk
    return _pk.paged_pallas_enabled(head_dim, block_size)


def _gather_dequant(pool, scale_pool, bt, q_dtype):
    """pool[bt] as q.dtype, dequantized by the per-entry-per-head
    scales when the pool is int8 (`serving.kv_cache` layout:
    pool [NB, BS, H, Dh], scales [NB, BS, H])."""
    g = pool[bt].astype(q_dtype)
    if scale_pool is not None:
        g = g * scale_pool[bt].astype(q_dtype)[..., None]
    return g


def ragged_paged_attention(q, k_pool, v_pool, block_tables, slot_ids,
                           positions, k_scale=None, v_scale=None, *,
                           scale=None, kernel_name="paged_ragged"):
    """Flat-token attention over a block-paged KV cache — the kernel of
    the continuous-batching mixed step (`paddle_tpu.serving.engine`),
    following the Ragged-Paged-Attention shape discipline: ONE fixed
    `[T]` token axis carries an arbitrary mix of decode tokens and
    prefill chunks, so the compiled step never retraces as requests
    come and go.

    q            [T, H, Dh]  — one query per flat token
    k_pool/v_pool [NB, BS, H, Dh] — one layer's paged pools
    block_tables [S, MB] int32 — per-slot block lists, NULL-padded
    slot_ids     [T] int32 — owning slot per token (-1 = padding)
    positions    [T] int32 — token's position in its sequence

    Token t attends keys at positions <= positions[t] of its own slot
    (padding blocks beyond the sequence are masked by construction, so
    the NULL-block garbage is never read through).

    With `k_scale`/`v_scale` (`[NB, BS, H]` fp32) the pools are
    quantized (int8 or fp8_e4m3) and dequantized per entry per head —
    on the gather path right after the gather, in the Pallas kernels
    inside the KV tile load.

    `kernel_name` keys the autotuner lookup (the sparse decode region
    passes "paged_sparse" with its shortened block tables, ISSUE 15);
    the math is identical for any name.

    On a TPU backend (or under kernel-test interpret mode) this
    dispatches to the block-table-native Pallas kernel
    (`ops.pallas.paged_attention.ragged_attend`) — no gathered
    contiguous KV copy is ever materialized; `PADDLE_TPU_PAGED_PALLAS=0`
    or a CPU backend keeps the pure-XLA gather path below, which runs
    under JAX_PLATFORMS=cpu and is the parity oracle.

    Tensor parallelism: the TP serving engine
    (`serving.distributed.tp_engine`) calls this INSIDE shard_map with
    the head axis partitioned on `mp` — q and the pools both arrive as
    the per-shard head slice, and per-head attention needs no
    cross-shard communication. The head counts must agree."""
    T, H, Dh = q.shape
    _check_pool_heads("ragged_paged_attention", H, k_pool, v_pool)
    BS = k_pool.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(Dh)
    if _paged_kernel_enabled(Dh, BS):
        from .paged_attention import ragged_attend
        return ragged_attend(q, k_pool, v_pool, block_tables, slot_ids,
                             positions, k_scale, v_scale, scale=scale,
                             kernel_name=kernel_name)
    return ragged_gather_reference(q, k_pool, v_pool, block_tables,
                                   slot_ids, positions, k_scale,
                                   v_scale, scale=scale)


def ragged_gather_reference(q, k_pool, v_pool, block_tables, slot_ids,
                            positions, k_scale=None, v_scale=None, *,
                            scale=None):
    """The pure-XLA gather implementation of `ragged_paged_attention`
    — the CPU path, the kernel-parity oracle, and the admission gate
    the autotuner holds every paged candidate against."""
    T, H, Dh = q.shape
    BS = k_pool.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(Dh)
    safe_slot = jnp.clip(slot_ids, 0, block_tables.shape[0] - 1)
    bt = block_tables[safe_slot]                      # [T, MB]
    S = bt.shape[1] * BS
    k = _gather_dequant(k_pool, k_scale, bt, q.dtype).reshape(
        T, S, H, Dh)
    v = _gather_dequant(v_pool, v_scale, bt, q.dtype).reshape(
        T, S, H, Dh)
    logits = jnp.einsum("thd,tshd->ths", q, k).astype(jnp.float32)
    logits = logits * scale
    keep = jnp.arange(S)[None, :] <= positions[:, None]   # [T, S]
    logits = jnp.where(keep[:, None, :], logits, -1e9)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("ths,tshd->thd", p, v)


def verify_paged_attention(q, k_pool, v_pool, block_tables, slot_ids,
                           positions, k_scale=None, v_scale=None, *,
                           scale=None, kernel_name="paged_verify"):
    """Verify-shaped paged attention: q `[B, K, H, Dh]` — K queries per
    slot (the speculative draft window: the last accepted token plus
    the proposed draft tokens), each attending its own slot's paged
    keys at positions <= its own.

    q            [B, K, H, Dh] — K consecutive queries per slot
    k_pool/v_pool [NB, BS, H, Dh] — one layer's paged pools
    block_tables [S, MB] int32 — per-slot block lists, NULL-padded
    slot_ids     [B] int32 — owning slot per query GROUP (-1 = padding)
    positions    [B, K] int32 — per-query positions in the sequence

    The row-granular sibling of `ragged_paged_attention`: the block
    table is gathered ONCE per slot instead of once per flat token, so
    the K-wide verify window costs one decode-shaped gather rather than
    K of them — this is the entry the serving engine's speculative
    mixed step uses for its fixed `[max_slots, K]` verify region.
    Causality across the window is the position mask itself: draft
    query j sees drafts 0..j-1 and nothing later, which is exactly the
    sequential-greedy semantics the verifier needs.

    On a TPU backend (or kernel-test interpret mode) this dispatches
    to the block-table-native Pallas kernel
    (`ops.pallas.paged_attention.verify_attend`); otherwise the
    pure-XLA gather path below is the CPU-safe parity oracle. With
    `k_scale`/`v_scale` the int8 pools dequantize per entry per head.
    Under tensor parallelism q and the pools are the per-shard head
    slice, like `ragged_paged_attention`."""
    B, K, H, Dh = q.shape
    _check_pool_heads("verify_paged_attention", H, k_pool, v_pool)
    BS = k_pool.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(Dh)
    if _paged_kernel_enabled(Dh, BS):
        from .paged_attention import verify_attend
        return verify_attend(q, k_pool, v_pool, block_tables, slot_ids,
                             positions, k_scale, v_scale, scale=scale,
                             kernel_name=kernel_name)
    return verify_gather_reference(q, k_pool, v_pool, block_tables,
                                   slot_ids, positions, k_scale,
                                   v_scale, scale=scale)


def verify_gather_reference(q, k_pool, v_pool, block_tables, slot_ids,
                            positions, k_scale=None, v_scale=None, *,
                            scale=None):
    """The pure-XLA gather implementation of `verify_paged_attention`
    (CPU path / parity oracle / tuner admission gate)."""
    B, K, H, Dh = q.shape
    BS = k_pool.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(Dh)
    safe_slot = jnp.clip(slot_ids, 0, block_tables.shape[0] - 1)
    bt = block_tables[safe_slot]                      # [B, MB]
    S = bt.shape[1] * BS
    k = _gather_dequant(k_pool, k_scale, bt, q.dtype).reshape(
        B, S, H, Dh)
    v = _gather_dequant(v_pool, v_scale, bt, q.dtype).reshape(
        B, S, H, Dh)
    logits = jnp.einsum("bkhd,bshd->bhks", q, k).astype(jnp.float32)
    logits = logits * scale
    keep = jnp.arange(S)[None, None, :] <= positions[:, :, None]
    logits = jnp.where(keep[:, None], logits, -1e9)    # [B, H, K, S]
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhks,bshd->bkhd", p, v)


def paged_attention(q, k_pool, v_pool, block_tables, context_lens,
                    k_scale=None, v_scale=None, *, scale=None):
    """Decode-shaped paged attention: q [B, H, Dh], one query per
    sequence, attending its first `context_lens[b]` cached tokens.

    On a TPU backend (or kernel-test interpret mode) this dispatches
    to our block-table-native Pallas kernel
    (`ops.pallas.paged_attention.decode_attend` — handles fp AND int8
    pools); everywhere else — CPU, shapes the gate refuses, or the
    `PADDLE_TPU_PAGED_PALLAS=0` kill-switch — the pure-XLA gather
    reference above runs. (jax's library paged kernel, the TPU path
    before the grouped kernel landed, accepted only a strict subset
    of the shapes our gate takes, so it can no longer be reached and
    was dropped.) Under tensor parallelism q and the pools are the
    per-shard head slice. `context_lens` must be >= 1 per row: an
    empty context has no defined attention output (the kernel yields
    ~0, the gather reference a uniform average — neither meaningful),
    and the serving engine never decodes an empty slot."""
    B, H, Dh = q.shape
    _check_pool_heads("paged_attention", H, k_pool, v_pool)
    BS = k_pool.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(Dh)
    if _paged_kernel_enabled(Dh, BS):
        from .paged_attention import decode_attend
        return decode_attend(q, k_pool, v_pool, block_tables,
                             context_lens, k_scale, v_scale,
                             scale=scale)
    return ragged_paged_attention(
        q, k_pool, v_pool, block_tables,
        jnp.arange(B, dtype=jnp.int32),
        context_lens.astype(jnp.int32) - 1, k_scale, v_scale,
        scale=scale)
