"""Fused (residual-add +) LayerNorm as Pallas TPU kernels, fwd + bwd.

Motivation (docs/gpt_perf_analysis.md): in the GPT train step the
residual adds + LN fusions run 5-15x above their bandwidth roofline —
XLA materialises layout conversions between the scan carry's S-minor
layout and the matmuls' d-minor layout around every add/LN. A Pallas
kernel pins one layout and does the add + normalise in a single
read/write pass; the custom vjp's backward kernel computes the heavy
[N, d] dz in one pass, with the small dgamma/dbeta reductions left to
XLA (they fuse into a single f32[d] pass).

API (used by parallel/hybrid_gpt.py when enabled):
    add_ln(x, r, w, b, eps)     -> (normalized, z=x+r)   (z is the new
                                   residual stream)
Falls back to plain jnp math off-TPU or for non-tileable shapes.
`_INTERPRET` runs the kernels in pallas interpret mode (CPU tests).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _on_tpu():
    from ...core.place import on_tpu_backend
    return on_tpu_backend()


# --------------------------------------------------------------- kernels

def _fwd_kernel(x_ref, r_ref, w_ref, b_ref, o_ref, z_ref, mu_ref,
                rs_ref, *, eps):
    x = x_ref[...].astype(jnp.float32) + r_ref[...].astype(jnp.float32)
    z_ref[...] = x.astype(z_ref.dtype)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rs = jax.lax.rsqrt(var + eps)
    out = xc * rs * w_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    o_ref[...] = out.astype(o_ref.dtype)
    mu_ref[...] = mu
    rs_ref[...] = rs


def _bwd_kernel(z_ref, w_ref, mu_ref, rs_ref, g_ref, dz_ref, *, eps):
    z = z_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    mu = mu_ref[...]
    rs = rs_ref[...]
    zhat = (z - mu) * rs
    dzh = g * w
    m1 = jnp.mean(dzh, axis=-1, keepdims=True)
    m2 = jnp.mean(dzh * zhat, axis=-1, keepdims=True)
    dz = rs * (dzh - m1 - zhat * m2)
    dz_ref[...] = dz.astype(dz_ref.dtype)


_BLOCK_ROWS = 256
_INTERPRET = False  # pallas interpret mode (CPU tests)


def _run_fwd(x2, r2, w, b, eps):
    n, d = x2.shape
    br = _BLOCK_ROWS
    grid = (n // br,)
    kernel = functools.partial(_fwd_kernel, eps=eps)
    out, z, mu, rs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(x2, r2, w.reshape(1, d), b.reshape(1, d))
    return out, z, mu, rs


def _run_bwd_dz(z2, w, mu, rs, g2, eps):
    n, d = z2.shape
    br = _BLOCK_ROWS
    grid = (n // br,)
    kernel = functools.partial(_bwd_kernel, eps=eps)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, d), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), g2.dtype),
        interpret=_INTERPRET,
    )(z2, w.reshape(1, d), mu, rs, g2)


# ------------------------------------------------------------ custom vjp

@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _add_ln(x, r, w, b, eps):
    out, z, _, _ = _core_fwd(x, r, w, b, eps)
    return out, z


def _core_fwd(x, r, w, b, eps):
    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    r2 = r.reshape(-1, d)
    out, z, mu, rs = _run_fwd(x2, r2, w, b, eps)
    return out.reshape(shape), z.reshape(shape), mu, rs


def _add_ln_fwd(x, r, w, b, eps):
    out, z, mu, rs = _core_fwd(x, r, w, b, eps)
    return (out, z), (z, w, mu, rs)


def _add_ln_bwd(eps, res, cts):
    g_out, g_z = cts
    z, w, mu, rs = res
    shape = z.shape
    d = shape[-1]
    z2 = z.reshape(-1, d)
    g2 = g_out.reshape(-1, d)
    dz = _run_bwd_dz(z2, w, mu, rs, g2, eps).reshape(shape)
    dz = dz + g_z  # the residual-stream cotangent flows straight through
    # small per-feature reductions: one fused f32[d] XLA pass
    zf = z2.astype(jnp.float32)
    zhat = (zf - mu) * rs
    gf = g2.astype(jnp.float32)
    dw = jnp.sum(gf * zhat, axis=0).astype(w.dtype)
    db = jnp.sum(gf, axis=0).astype(w.dtype)
    return dz, dz, dw, db


_add_ln.defvjp(_add_ln_fwd, _add_ln_bwd)


def add_ln(x, r, w, b, eps=1e-5):
    """(LN(x + r) * w + b, x + r) — fused on TPU, jnp fallback off-TPU
    or when rows/features don't tile (rows % 256, d % 128)."""
    import math as _math
    n_rows = _math.prod(x.shape[:-1])
    if (_on_tpu() or _INTERPRET) and x.shape[-1] % 128 == 0 \
            and n_rows % _BLOCK_ROWS == 0:
        return _add_ln(x, r, w.astype(jnp.float32),
                       b.astype(jnp.float32), eps)
    z = x + r
    zf = z.astype(jnp.float32)
    mu = jnp.mean(zf, axis=-1, keepdims=True)
    var = jnp.var(zf, axis=-1, keepdims=True)
    out = ((zf - mu) / jnp.sqrt(var + eps) * w + b).astype(x.dtype)
    return out, z
