"""SelectedRows: rows-sparse tensors (embedding-style sparse gradients).

Parity: `paddle/phi/core/selected_rows.h` + the
`paddle/phi/kernels/selected_rows/` kernel family. A SelectedRows holds
(rows, values, height): logically a [height, *value_dims] tensor that is
zero outside `rows`. The reference uses it for embedding gradients and
rows-sparse optimizer updates; here the same capability rides jax
segment/scatter ops (TPU-friendly: fixed shapes, no host compaction).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ._helpers import as_tensor


class SelectedRows:
    """Rows-sparse value container (`selected_rows.h:28`)."""

    def __init__(self, rows, values, height):
        self.rows = as_tensor(rows)            # [n] int
        self.values = as_tensor(values)        # [n, *dims]
        self.height = int(height)

    @property
    def shape(self):
        return [self.height] + list(self.values.shape[1:])

    def to_dense(self):
        """Densify (merging duplicate rows by summation, the reference's
        MergeAdd semantics)."""
        out = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                        self.values._data.dtype)
        return Tensor(out.at[self.rows._data].add(self.values._data))

    def merge_rows(self):
        """Merge duplicate rows (scatter-add into unique rows) —
        `merge_selected_rows` / MergeAdd kernel.

        `jnp.unique(..., size=n)` pads its output when duplicates are
        present. The padding must not leak: the old `fill_value=-1`
        OverflowError'd on unsigned row dtypes and emitted phantom
        rows with id -1 (a table-push consumer would turn those into
        garbage uint64-max keys). The sentinel is now `height` — out
        of range by contract, so a scatter via `.at[...]` drops it —
        and eager calls compact the padding away entirely (the
        sentinel only survives under jit, where shapes are fixed)."""
        rows = self.rows._data
        n = rows.shape[0]
        if n == 0:
            return SelectedRows(self.rows, self.values, self.height)
        if not isinstance(rows, jax.core.Tracer):
            # concrete: merge in numpy with an exact-sized output (no
            # padding at all). Slicing a jax array to the data-dependent
            # unique count would compile a fresh slice kernel per
            # distinct count — the embedding push path hits a new count
            # every batch.
            rows_np = np.asarray(rows)
            vals_np = np.asarray(self.values._data)
            uniq, inv = np.unique(rows_np, return_inverse=True)
            out = merge_with_inverse(inv, vals_np, uniq.size)
            return SelectedRows(Tensor(uniq), Tensor(out), self.height)
        fill = jnp.asarray(self.height).astype(rows.dtype)
        uniq, inv = jnp.unique(rows, return_inverse=True, size=n,
                               fill_value=fill)
        summed = jax.ops.segment_sum(self.values._data,
                                     inv.reshape(-1), num_segments=n)
        return SelectedRows(Tensor(uniq), Tensor(summed), self.height)

    def map_fn(self, fn, name):
        return SelectedRows(self.rows, Tensor(fn(self.values._data)),
                            self.height)


def merge_with_inverse(inv: np.ndarray, values: np.ndarray,
                       num_uniq: int) -> np.ndarray:
    """The MergeAdd segment-sum given a PRECOMPUTED inverse index
    (`merge_rows` = unique + this): out[u] = sum of values whose inv
    is u. Callers that already dedup'd their keys (the embedding
    engine's push path) skip the redundant O(n log n) re-sort."""
    values = np.asarray(values)
    # np.unique(return_inverse=True) keeps the INPUT shape on numpy
    # >= 2.1 — flatten so both numpy generations land here, and fail
    # loudly on a row-count mismatch instead of scattering garbage
    inv = np.asarray(inv).reshape(-1)
    if inv.size != values.shape[0]:
        raise ValueError(
            f"inverse index has {inv.size} entries for "
            f"{values.shape[0]} value rows")
    if values.size == 0:
        return np.zeros((num_uniq,) + values.shape[1:], values.dtype)
    if values.ndim == 2 and values.shape[1] <= 256 and \
            np.issubdtype(values.dtype, np.floating):
        # segment-sum via per-column bincount: ~3x faster than
        # np.add.at on embedding-push shapes ([8k, 8..64])
        out = np.empty((num_uniq, values.shape[1]), values.dtype)
        for d in range(values.shape[1]):
            out[:, d] = np.bincount(inv, weights=values[:, d],
                                    minlength=num_uniq)
        return out
    out = np.zeros((num_uniq,) + values.shape[1:], values.dtype)
    np.add.at(out, inv, values)
    return out


def add_n(inputs):
    """`selected_rows/add_n_kernel.h` — sum SelectedRows (concat rows;
    duplicates merge on densify/merge_rows)."""
    rows = jnp.concatenate([s.rows._data for s in inputs])
    vals = jnp.concatenate([s.values._data for s in inputs])
    return SelectedRows(Tensor(rows), Tensor(vals), inputs[0].height)


def scale(x: SelectedRows, scale_v, bias=0.0, bias_after_scale=True):
    """`selected_rows/scale_kernel.h`."""
    def f(v):
        if bias_after_scale:
            return v * scale_v + bias
        return (v + bias) * scale_v
    return x.map_fn(f, "scale")


def clip(x: SelectedRows, min, max):
    return x.map_fn(lambda v: jnp.clip(v, min, max), "clip")


def clip_by_norm(x: SelectedRows, max_norm):
    """`selected_rows/clip_by_norm_kernel.h` — norm over the (merged)
    values."""
    m = x.merge_rows()

    def f(v):
        n = jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32))))
        s = jnp.where(n > max_norm, max_norm / (n + 1e-12), 1.0)
        return (v.astype(jnp.float32) * s).astype(v.dtype)
    return m.map_fn(f, "clip_by_norm")


def multiply(x: SelectedRows, y):
    """`selected_rows/elementwise_multiply_kernel.h` — rows-sparse *
    dense (gathers the dense rows)."""
    y = as_tensor(y)
    gathered = y._data[x.rows._data]
    return SelectedRows(x.rows, Tensor(x.values._data * gathered),
                        x.height)


def isfinite(x: SelectedRows):
    return x.map_fn(lambda v: jnp.isfinite(v), "isfinite")


def activation(x: SelectedRows, act="square"):
    """`selected_rows/activation_kernel.h` (square etc. on values)."""
    fns = {"square": jnp.square, "sqrt": jnp.sqrt, "abs": jnp.abs}
    return x.map_fn(fns[act], "activation")


def adam_sparse(param, grad: SelectedRows, moment1, moment2, lr,
                beta1=0.9, beta2=0.999, epsilon=1e-8, t=1):
    """`selected_rows/adam_kernel.h` — rows-sparse Adam: only touched
    rows update their moments and values (lazy_mode semantics).
    param/moment1/moment2: dense Tensors [height, D]. Returns updated
    (param, m1, m2)."""
    p = as_tensor(param)._data
    m1 = as_tensor(moment1)._data
    m2 = as_tensor(moment2)._data
    g = grad.merge_rows()
    rows = g.rows._data
    gv = g.values._data.astype(jnp.float32)
    # merge_rows pads with the out-of-range sentinel `height` under
    # jit (and compacts eagerly); mask both that and any negative id
    ok = (rows >= 0) & (rows < p.shape[0])
    rws = jnp.clip(rows, 0, p.shape[0] - 1)
    m1r = m1[rws]
    m2r = m2[rws]
    nm1 = beta1 * m1r + (1 - beta1) * gv
    nm2 = beta2 * m2r + (1 - beta2) * gv * gv
    mhat = nm1 / (1 - beta1 ** t)
    vhat = nm2 / (1 - beta2 ** t)
    upd = lr * mhat / (jnp.sqrt(vhat) + epsilon)
    okf = ok.reshape(-1, *([1] * (gv.ndim - 1))).astype(jnp.float32)
    new_p = p.at[rws].add((-upd * okf).astype(p.dtype))
    # scatter-ADD masked deltas for the moments too: the clipped
    # padding rows alias a real row index, and a scatter-SET with
    # duplicate indices picks an arbitrary winner (the real update
    # could lose to the padding's old-value write); adds of zero are
    # order-independent
    new_m1 = m1.at[rws].add(((nm1 - m1r) * okf).astype(m1.dtype))
    new_m2 = m2.at[rws].add(((nm2 - m2r) * okf).astype(m2.dtype))
    return Tensor(new_p), Tensor(new_m1), Tensor(new_m2)


def merge_selected_rows(x: SelectedRows):
    return x.merge_rows()
