"""SelectedRows: rows-sparse tensors (embedding-style sparse gradients).

Parity: `paddle/phi/core/selected_rows.h` + the
`paddle/phi/kernels/selected_rows/` kernel family. A SelectedRows holds
(rows, values, height): logically a [height, *value_dims] tensor that is
zero outside `rows`. The reference uses it for embedding gradients and
rows-sparse optimizer updates; here the same capability rides jax
segment/scatter ops (TPU-friendly: fixed shapes, no host compaction).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ._helpers import as_tensor


class SelectedRows:
    """Rows-sparse value container (`selected_rows.h:28`)."""

    def __init__(self, rows, values, height):
        self.rows = as_tensor(rows)            # [n] int
        self.values = as_tensor(values)        # [n, *dims]
        self.height = int(height)

    @property
    def shape(self):
        return [self.height] + list(self.values.shape[1:])

    def to_dense(self):
        """Densify (merging duplicate rows by summation, the reference's
        MergeAdd semantics)."""
        out = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                        self.values._data.dtype)
        return Tensor(out.at[self.rows._data].add(self.values._data))

    def merge_rows(self):
        """Merge duplicate rows (scatter-add into unique rows) —
        `merge_selected_rows` / MergeAdd kernel."""
        rows = self.rows._data
        uniq, inv = jnp.unique(rows, return_inverse=True,
                               size=rows.shape[0], fill_value=-1)
        summed = jax.ops.segment_sum(self.values._data, inv,
                                     num_segments=rows.shape[0])
        return SelectedRows(Tensor(uniq), Tensor(summed), self.height)

    def map_fn(self, fn, name):
        return SelectedRows(self.rows, Tensor(fn(self.values._data)),
                            self.height)


def add_n(inputs):
    """`selected_rows/add_n_kernel.h` — sum SelectedRows (concat rows;
    duplicates merge on densify/merge_rows)."""
    rows = jnp.concatenate([s.rows._data for s in inputs])
    vals = jnp.concatenate([s.values._data for s in inputs])
    return SelectedRows(Tensor(rows), Tensor(vals), inputs[0].height)


def scale(x: SelectedRows, scale_v, bias=0.0, bias_after_scale=True):
    """`selected_rows/scale_kernel.h`."""
    def f(v):
        if bias_after_scale:
            return v * scale_v + bias
        return (v + bias) * scale_v
    return x.map_fn(f, "scale")


def clip(x: SelectedRows, min, max):
    return x.map_fn(lambda v: jnp.clip(v, min, max), "clip")


def clip_by_norm(x: SelectedRows, max_norm):
    """`selected_rows/clip_by_norm_kernel.h` — norm over the (merged)
    values."""
    m = x.merge_rows()

    def f(v):
        n = jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32))))
        s = jnp.where(n > max_norm, max_norm / (n + 1e-12), 1.0)
        return (v.astype(jnp.float32) * s).astype(v.dtype)
    return m.map_fn(f, "clip_by_norm")


def multiply(x: SelectedRows, y):
    """`selected_rows/elementwise_multiply_kernel.h` — rows-sparse *
    dense (gathers the dense rows)."""
    y = as_tensor(y)
    gathered = y._data[x.rows._data]
    return SelectedRows(x.rows, Tensor(x.values._data * gathered),
                        x.height)


def isfinite(x: SelectedRows):
    return x.map_fn(lambda v: jnp.isfinite(v), "isfinite")


def activation(x: SelectedRows, act="square"):
    """`selected_rows/activation_kernel.h` (square etc. on values)."""
    fns = {"square": jnp.square, "sqrt": jnp.sqrt, "abs": jnp.abs}
    return x.map_fn(fns[act], "activation")


def adam_sparse(param, grad: SelectedRows, moment1, moment2, lr,
                beta1=0.9, beta2=0.999, epsilon=1e-8, t=1):
    """`selected_rows/adam_kernel.h` — rows-sparse Adam: only touched
    rows update their moments and values (lazy_mode semantics).
    param/moment1/moment2: dense Tensors [height, D]. Returns updated
    (param, m1, m2)."""
    p = as_tensor(param)._data
    m1 = as_tensor(moment1)._data
    m2 = as_tensor(moment2)._data
    g = grad.merge_rows()
    rows = g.rows._data
    gv = g.values._data.astype(jnp.float32)
    ok = (rows >= 0)
    rws = jnp.clip(rows, 0, p.shape[0] - 1)
    m1r = m1[rws]
    m2r = m2[rws]
    nm1 = beta1 * m1r + (1 - beta1) * gv
    nm2 = beta2 * m2r + (1 - beta2) * gv * gv
    mhat = nm1 / (1 - beta1 ** t)
    vhat = nm2 / (1 - beta2 ** t)
    upd = lr * mhat / (jnp.sqrt(vhat) + epsilon)
    okf = ok.reshape(-1, *([1] * (gv.ndim - 1))).astype(jnp.float32)
    new_p = p.at[rws].add((-upd * okf).astype(p.dtype))
    new_m1 = m1.at[rws].set(jnp.where(okf > 0, nm1, m1r))
    new_m2 = m2.at[rws].set(jnp.where(okf > 0, nm2, m2r))
    return Tensor(new_p), Tensor(new_m1), Tensor(new_m2)


def merge_selected_rows(x: SelectedRows):
    return x.merge_rows()
