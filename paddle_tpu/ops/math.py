"""Elementwise + reduction math ops.

Parity: `python/paddle/tensor/math.py` / `tensor/stat.py` over PHI kernels
(`paddle/phi/kernels/elementwise_*`, `funcs/broadcast_function.h`,
`funcs/reduce_function.h`). On TPU each op lowers to an XLA HLO that the
compiler fuses; there is no hand-written kernel per op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor
from ._helpers import as_tensor, binary, unary, norm_axis

# ---------------------------------------------------------------- binary


def add(x, y, name=None):
    return binary("add", jnp.add, x, y)


def subtract(x, y, name=None):
    return binary("subtract", jnp.subtract, x, y)


def multiply(x, y, name=None):
    return binary("multiply", jnp.multiply, x, y)


def divide(x, y, name=None):
    return binary("divide", jnp.divide, x, y)


def floor_divide(x, y, name=None):
    return binary("floor_divide", jnp.floor_divide, x, y,
                  differentiable=False)


def remainder(x, y, name=None):
    return binary("remainder", jnp.remainder, x, y, differentiable=False)


mod = remainder


def pow(x, y, name=None):
    return binary("pow", jnp.power, x, y)


def atan2(x, y, name=None):
    return binary("atan2", jnp.arctan2, x, y)


def maximum(x, y, name=None):
    return binary("maximum", jnp.maximum, x, y)


def minimum(x, y, name=None):
    return binary("minimum", jnp.minimum, x, y)


def fmax(x, y, name=None):
    return binary("fmax", jnp.fmax, x, y)


def fmin(x, y, name=None):
    return binary("fmin", jnp.fmin, x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """PHI scale kernel parity (`paddle/phi/kernels/scale_kernel.h`)."""
    s, b = scale, bias

    def _fn(a):
        if bias_after_scale:
            return a * s + b
        return (a + b) * s
    out = unary("scale", _fn, as_tensor(x))
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def add_n(inputs, name=None):
    """sum of a list of tensors (PHI add_n kernel)."""
    ts = [as_tensor(t) for t in inputs]
    from ..core import dispatch

    def _fn(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out
    return dispatch.apply("add_n", _fn, tuple(ts))


# ----------------------------------------------------------------- unary

_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "exp": jnp.exp, "expm1": jnp.expm1,
    "log": jnp.log, "log2": jnp.log2, "log10": jnp.log10, "log1p": jnp.log1p,
    "sqrt": jnp.sqrt, "square": jnp.square, "sin": jnp.sin, "cos": jnp.cos,
    "tan": jnp.tan, "asin": jnp.arcsin, "acos": jnp.arccos,
    "atan": jnp.arctan, "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "asinh": jnp.arcsinh, "acosh": jnp.arccosh, "atanh": jnp.arctanh,
    "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "lgamma": jax.scipy.special.gammaln, "digamma": jax.scipy.special.digamma,
    "reciprocal": lambda a: 1.0 / a,
    "rsqrt": jax.lax.rsqrt,
    "neg": jnp.negative,
}

_UNARY_NODIFF = {
    "floor": jnp.floor, "ceil": jnp.ceil, "round": jnp.round,
    "trunc": jnp.trunc, "isnan": jnp.isnan, "isinf": jnp.isinf,
    "isfinite": jnp.isfinite, "logical_not": jnp.logical_not,
    "bitwise_not": jnp.invert,
}


def _make_unary(name, fn, diff):
    def op(x, name=None, _f=fn, _n=name, _d=diff):
        return unary(_n, _f, as_tensor(x), differentiable=_d)
    op.__name__ = name
    return op


for _n, _f in _UNARY.items():
    globals()[_n] = _make_unary(_n, _f, True)
for _n, _f in _UNARY_NODIFF.items():
    globals()[_n] = _make_unary(_n, _f, False)


def clip(x, min=None, max=None, name=None):
    lo = float(min.item()) if isinstance(min, Tensor) else min
    hi = float(max.item()) if isinstance(max, Tensor) else max
    return unary("clip", lambda a: jnp.clip(a, lo, hi), as_tensor(x))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return unary("nan_to_num",
                 lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                          neginf=neginf), as_tensor(x))


def sigmoid(x, name=None):
    return unary("sigmoid", jax.nn.sigmoid, as_tensor(x))


def increment(x, value=1.0, name=None):
    out = unary("increment", lambda a: a + value, as_tensor(x))
    return _rebind(x, out)


# ------------------------------------------------------------- reductions


def _reduce(name, jfn, x, axis=None, keepdim=False, dtype=None,
            differentiable=True):
    x = as_tensor(x)
    ax = norm_axis(axis)
    dt = dtype_mod.convert_dtype(dtype)

    def _fn(a):
        out = jfn(a, axis=ax, keepdims=keepdim)
        if dt is not None:
            out = out.astype(dt)
        return out
    return unary(name, _fn, x, differentiable=differentiable)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    return _reduce("sum", jnp.sum, x, axis, keepdim, dtype)


def mean(x, axis=None, keepdim=False, name=None):
    return _reduce("mean", jnp.mean, x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return _reduce("prod", jnp.prod, x, axis, keepdim, dtype)


def max(x, axis=None, keepdim=False, name=None):
    return _reduce("max", jnp.max, x, axis, keepdim)


def min(x, axis=None, keepdim=False, name=None):
    return _reduce("min", jnp.min, x, axis, keepdim)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def all(x, axis=None, keepdim=False, name=None):
    return _reduce("all", jnp.all, x, axis, keepdim, differentiable=False)


def any(x, axis=None, keepdim=False, name=None):
    return _reduce("any", jnp.any, x, axis, keepdim, differentiable=False)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = as_tensor(x)
    ax = norm_axis(axis)
    dd = 1 if unbiased else 0
    return unary("std", lambda a: jnp.std(a, axis=ax, ddof=dd,
                                          keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = as_tensor(x)
    ax = norm_axis(axis)
    dd = 1 if unbiased else 0
    return unary("var", lambda a: jnp.var(a, axis=ax, ddof=dd,
                                          keepdims=keepdim), x)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = as_tensor(x)
    ax = norm_axis(axis)
    dt = dtype_mod.convert_dtype(dtype)

    def _fn(a):
        out = jnp.argmax(a, axis=ax, keepdims=keepdim) if ax is not None \
            else jnp.argmax(a)
        return out.astype(dt)
    return unary("argmax", _fn, x, differentiable=False)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = as_tensor(x)
    ax = norm_axis(axis)
    dt = dtype_mod.convert_dtype(dtype)

    def _fn(a):
        out = jnp.argmin(a, axis=ax, keepdims=keepdim) if ax is not None \
            else jnp.argmin(a)
        return out.astype(dt)
    return unary("argmin", _fn, x, differentiable=False)


def cumsum(x, axis=None, dtype=None, name=None):
    x = as_tensor(x)
    dt = dtype_mod.convert_dtype(dtype)

    def _fn(a):
        if axis is None:
            out = jnp.cumsum(a.reshape(-1))
        else:
            out = jnp.cumsum(a, axis=int(axis))
        return out.astype(dt) if dt is not None else out
    return unary("cumsum", _fn, x)


def cumprod(x, dim=None, dtype=None, name=None):
    x = as_tensor(x)
    dt = dtype_mod.convert_dtype(dtype)

    def _fn(a):
        out = jnp.cumprod(a, axis=int(dim) if dim is not None else None)
        return out.astype(dt) if dt is not None else out
    return unary("cumprod", _fn, x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    ax = norm_axis(axis)
    return unary("logsumexp",
                 lambda a: jax.scipy.special.logsumexp(a, axis=ax,
                                                       keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    ax = norm_axis(axis)
    return unary("count_nonzero",
                 lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim),
                 x, differentiable=False)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return unary("trace",
                 lambda a: jnp.trace(a, offset, axis1, axis2), as_tensor(x))


def outer(x, y, name=None):
    return binary("outer", lambda a, b: jnp.outer(a, b), x, y)


def inner(x, y, name=None):
    return binary("inner", jnp.inner, x, y)


def kron(x, y, name=None):
    return binary("kron", jnp.kron, x, y)


def median(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    ax = norm_axis(axis)
    return unary("median", lambda a: jnp.median(a, axis=ax, keepdims=keepdim),
                 x)


def quantile(x, q, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    ax = norm_axis(axis)
    return unary("quantile",
                 lambda a: jnp.quantile(a, q, axis=ax, keepdims=keepdim), x)


def logaddexp(x, y, name=None):
    return binary("logaddexp", jnp.logaddexp, x, y)


def heaviside(x, y, name=None):
    # differentiable: dx = 0 a.e., dy = 1 where x == 0 (reference grads)
    return binary("heaviside", jnp.heaviside, x, y)


def frac(x, name=None):
    return unary("frac", lambda a: a - jnp.trunc(a), as_tensor(x))


def deg2rad(x, name=None):
    return unary("deg2rad", jnp.deg2rad, as_tensor(x))


def rad2deg(x, name=None):
    return unary("rad2deg", jnp.rad2deg, as_tensor(x))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = as_tensor(x)
    pre = as_tensor(prepend)._data if prepend is not None else None
    app = as_tensor(append)._data if append is not None else None
    return unary("diff",
                 lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre,
                                    append=app), x)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = as_tensor(y)
    if x is not None:
        xs = as_tensor(x)
        from ..core import dispatch as _dispatch
        return _dispatch.apply(
            "trapezoid",
            lambda ya, xa: jnp.trapezoid(ya, xa, axis=axis), (y, xs))
    return unary("trapezoid",
                 lambda a: jnp.trapezoid(a, dx=dx or 1.0, axis=axis), y)


def logcumsumexp(x, axis=None, name=None):
    x = as_tensor(x)

    def _fn(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis
        return jax.lax.cumlogsumexp(a, axis=ax)
    return unary("logcumsumexp", _fn, x)


def _cum_extreme(name, scan_fn, x, axis, dtype):
    """Shared cummax/cummin: ONE dispatch returning (values, indices)."""
    x = as_tensor(x)
    dt = dtype_mod.convert_dtype(dtype)
    from ..core import dispatch as _dispatch

    def _fn(a):
        ax = 0 if axis is None else axis
        arr = a.reshape(-1) if axis is None else a
        vals = scan_fn(arr, axis=ax)
        changed = arr == vals
        idx = jnp.arange(arr.shape[ax])
        shape = [1] * arr.ndim
        shape[ax] = -1
        idx = jnp.broadcast_to(idx.reshape(shape), arr.shape)
        indices = jax.lax.cummax(jnp.where(changed, idx, 0),
                                 axis=ax).astype(dt)
        return vals, indices
    return _dispatch.apply(name, _fn, (x,))


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extreme("cummax", jax.lax.cummax, x, axis, dtype)


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extreme("cummin", jax.lax.cummin, x, axis, dtype)


# ---------------------------------------------------- inplace variants
# Parity: paddle's `op_` inplace APIs. TPU-native: functional compute +
# wrapper rebind (version-counter semantics: the wrapper adopts the new
# value/grad node; aliasing views are not mutated).


def _rebind(x, out):
    x._data = out._data
    x._layout = out._layout  # the op may have materialized a tagged x
    if out._grad_node is not None:
        x._grad_node, x._out_slot = out._grad_node, out._out_slot
    else:
        x._grad_node, x._out_slot = None, 0
    # NOTE: x.stop_gradient is preserved (paddle semantics — an in-place
    # op under no_grad, or zero_/fill_, must not freeze a trainable
    # tensor)
    return x


def add_(x, y, name=None):
    return _rebind(x, add(x, y))


def subtract_(x, y, name=None):
    return _rebind(x, subtract(x, y))


def multiply_(x, y, name=None):
    return _rebind(x, multiply(x, y))


def scale_(x, scale=1.0, bias=0.0, bias_after_scale=True, name=None):
    _scale_fn = globals()["scale"]
    return _rebind(x, _scale_fn(x, scale, bias, bias_after_scale))


def clip_(x, min=None, max=None, name=None):
    return _rebind(x, clip(x, min, max))


def exp_(x, name=None):
    return _rebind(x, exp(x))  # noqa: F821


def sqrt_(x, name=None):
    return _rebind(x, sqrt(x))  # noqa: F821


def tanh_(x, name=None):
    return _rebind(x, tanh(x))  # noqa: F821


def zero_(x, name=None):
    from .creation import zeros_like
    return _rebind(x, zeros_like(x))


def fill_(x, value, name=None):
    from .creation import full_like
    return _rebind(x, full_like(x, value))
