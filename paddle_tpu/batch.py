"""`paddle.batch` parity (`python/paddle/batch.py`): decorate a sample
reader into a batched reader (the legacy reader protocol)."""


def batch(reader, batch_size, drop_last=False):
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched
