"""paddle_tpu.nn — parity with `python/paddle/nn/`."""
from .layer_base import Layer  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from . import initializer  # noqa: F401
from . import functional  # noqa: F401
from .container import Sequential, LayerList, ParameterList  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm)

from .layers.common import (  # noqa: F401
    Identity, Linear, Embedding, Dropout, Dropout2D, Dropout3D,
    AlphaDropout, Flatten, Upsample, PixelShuffle, Pad1D, Pad2D, Pad3D,
    CosineSimilarity, Unfold,
)
from .layers.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose,
    Conv3DTranspose,
)
from .layers.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    RMSNorm, LocalResponseNorm,
)
from .layers.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
)
from .layers.activation import (  # noqa: F401
    ReLU, ReLU6, Sigmoid, Tanh, Silu, Swish, Mish, GELU, SELU, CELU,
    Hardswish, Hardsigmoid, Hardshrink, Softshrink, Tanhshrink, Softplus,
    Softsign, LogSigmoid, LeakyReLU, ELU, Hardtanh, PReLU, Softmax,
    LogSoftmax, Maxout,
)
from .layers.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, MarginRankingLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss,
)
from .layers.rnn import (  # noqa: F401
    RNNCellBase, LSTMCell, GRUCell, SimpleRNNCell, RNN, BiRNN, SimpleRNN,
    LSTM, GRU,
)
from .layers.extras import (  # noqa: F401
    Bilinear, CTCLoss, ChannelShuffle, Fold, Unfold, HSigmoidLoss,
    LayerDict, MaxUnPool1D, MaxUnPool2D, MultiLabelSoftMarginLoss,
    PairwiseDistance, PixelUnshuffle, RReLU, SoftMarginLoss, Softmax2D,
    ThresholdedReLU, TripletMarginWithDistanceLoss,
    UpsamplingBilinear2D, UpsamplingNearest2D, ZeroPad2D,
)
from .layers.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from . import utils  # noqa: F401 — paddle.nn.utils
from . import quant  # noqa: F401 — paddle.nn.quant

import sys as _sys
# paddle code imports `paddle.nn.functional as F`
functional = functional  # noqa
