"""`paddle.nn.quant` parity (`python/paddle/nn/quant/`): quantization
layers + the weight-only linear functional surface. The engines live in
`paddle_tpu.quantization` (QAT/PTQ with fake-quant + STE) and the fused
int8 serving stack (`incubate/nn/fused_transformer.py`); this package
exposes them under the reference's nn.quant names."""
from ...quantization import (  # noqa: F401
    fake_quant, abs_max_scale, QuantedLinear, QuantConfig,
    weight_quantize, weight_only_linear,
)

# reference quant_layers naming
QuantizedLinear = QuantedLinear


class Stub:
    """`nn/quant/stub.py` parity: a placeholder layer the quantization
    passes replace with observers/quanters; identity until then."""

    def __init__(self, observer=None):
        self._observer = observer

    def __call__(self, x):
        return x

    forward = __call__


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """`quantized_linear.py llm_int8_linear` parity (same positional
    signature): weight-only int8 matmul + bias (the outlier-threshold
    decomposition is unnecessary on the MXU path — dequant fuses into
    the bf16 dot)."""
    return weight_only_linear(x, weight, weight_scale, bias=bias)
