"""paddle.nn.utils parity: weight_norm, vector<->parameters, clip helper."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor, Parameter
from ... import ops


def parameters_to_vector(parameters, name=None):
    ts = [ops.reshape(p, [-1]) for p in parameters]
    return ops.concat(ts, axis=0)


def vector_to_parameters(vec, parameters, name=None):
    vec = vec if isinstance(vec, Tensor) else Tensor(vec)
    offset = 0
    arr = vec.numpy()
    for p in parameters:
        n = p.size
        p.set_value(arr[offset:offset + n].reshape(p.shape))
        offset += n


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(np.float32(0.0))
    import jax.numpy as jnp
    if norm_type == float("inf"):
        total = max(float(jnp.max(jnp.abs(p.grad._data))) for p in params)
    else:
        total = float(sum(
            jnp.sum(jnp.abs(p.grad._data.astype(jnp.float32))
                    ** norm_type) for p in params) ** (1.0 / norm_type))
    if error_if_nonfinite and not np.isfinite(total):
        raise RuntimeError("non-finite gradient norm")
    scale = max_norm / (total + 1e-6)
    if scale < 1.0:
        for p in params:
            p.grad._data = p.grad._data * scale
    return Tensor(np.float32(total))


class _WeightNormWrapper:
    """weight_norm(layer): reparameterise weight = g * v / ||v|| via a
    forward pre-hook (paddle.nn.utils.weight_norm parity)."""

    def __init__(self, layer, name, dim):
        self.name = name
        self.dim = dim
        w = getattr(layer, name)
        axes = [i for i in range(w.ndim) if i != dim] if dim is not None \
            else None
        norm = np.sqrt((w.numpy() ** 2).sum(
            axis=tuple(axes) if axes else None, keepdims=True))
        g = Parameter(norm.astype(np.float32).reshape(-1)
                      if dim is not None else norm.astype(np.float32))
        v = Parameter(w.numpy())
        layer.add_parameter(name + "_g", g)
        layer.add_parameter(name + "_v", v)
        # the original weight leaves the parameter registry (it is now a
        # derived value recomputed each forward)
        layer._parameters.pop(name, None)
        self.axes = axes

    def __call__(self, layer, inputs):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        vn = ops.sqrt(ops.sum(v * v,
                              axis=self.axes if self.axes else None,
                              keepdim=True)) if self.axes else \
            ops.sqrt(ops.sum(v * v))
        if self.dim is not None:
            shape = [1] * v.ndim
            shape[self.dim] = -1
            gshaped = ops.reshape(g, shape)
        else:
            gshaped = g
        w = v * (gshaped / (vn + 1e-12))
        layer.__dict__[self.name] = w  # visible to forward
        return None


def weight_norm(layer, name="weight", dim=0):
    hook = _WeightNormWrapper(layer, name, dim)
    layer.register_forward_pre_hook(hook)
    # materialise once so the attribute exists before the first call
    hook(layer, ())
    return layer


def remove_weight_norm(layer, name="weight"):
    w = layer.__dict__.pop(name, None)
    if w is not None:
        layer.add_parameter(name, Parameter(w.numpy()))
    for hid, hook in list(layer._forward_pre_hooks.items()):
        if isinstance(hook, _WeightNormWrapper) and hook.name == name:
            layer._forward_pre_hooks.pop(hid)
    layer._parameters.pop(name + "_g", None)
    layer._parameters.pop(name + "_v", None)
    return layer


class _SpectralNormWrapper:
    """Power-iteration pre-hook (`nn/utils/spectral_norm_hook.py:140`):
    weight = weight_orig / sigma(weight_orig), sigma estimated by
    n_power_iterations of u/v updates per forward (u persisted as a
    buffer, updated without gradient — the reference semantics)."""

    def __init__(self, layer, name, n_power_iterations, eps, dim):
        self.name = name
        self.n = n_power_iterations
        self.eps = eps
        w = getattr(layer, name)
        arr = w.numpy()
        if dim is None:
            cls = layer.__class__.__name__
            # Linear/Embedding store weight [in, out]; transposed convs
            # store [in_c, out_c/groups, *k] — the OUT axis is 1 for
            # both (reference spectral_norm_hook default)
            dim = 1 if (cls in ("Linear", "Embedding")
                        or "Transpose" in cls) else 0
        self.dim = dim
        layer.add_parameter(name + "_orig", Parameter(arr))
        rng = np.random.RandomState(0)
        u = rng.randn(arr.shape[dim]).astype(arr.dtype)
        layer.register_buffer(name + "_u",
                              ops.to_tensor(u / np.linalg.norm(u)))
        layer._parameters.pop(name, None)

    def _mat(self, arr):
        if self.dim != 0:
            perm = [self.dim] + [i for i in range(arr.ndim)
                                 if i != self.dim]
            arr = np.transpose(np.asarray(arr), perm)
        return np.asarray(arr).reshape(arr.shape[0], -1)

    def __call__(self, layer, inputs):
        w_orig = getattr(layer, self.name + "_orig")
        u = np.asarray(getattr(layer, self.name + "_u")._data)
        wm = self._mat(w_orig._data)         # numpy: no grad through
        v = None                             # the power iteration
        for _ in range(self.n):
            v = wm.T @ u
            v = v / (np.linalg.norm(v) + self.eps)
            u = wm @ v
            u = u / (np.linalg.norm(u) + self.eps)
        layer._buffers[self.name + "_u"] = ops.to_tensor(u)
        # sigma as a differentiable function of w_orig: u^T W v
        ut = ops.to_tensor(u.astype(np.float32))
        vt = ops.to_tensor(v.astype(np.float32))
        worm = ops.reshape(
            ops.transpose(w_orig, [self.dim] + [
                i for i in range(w_orig.ndim) if i != self.dim])
            if self.dim != 0 else w_orig, [wm.shape[0], -1])
        sigma = ops.sum(ut * ops.squeeze(
            ops.matmul(worm, ops.unsqueeze(vt, -1)), -1))
        layer.__dict__[self.name] = w_orig / sigma
        return None


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Apply spectral normalization (`spectral_norm_hook.py:140`)."""
    hook = _SpectralNormWrapper(layer, name, n_power_iterations, eps,
                                dim)
    layer.register_forward_pre_hook(hook)
    hook(layer, ())
    return layer


def remove_spectral_norm(layer, name="weight"):
    """Re-materialize the CURRENT normalized weight as the plain
    parameter (post-removal forwards must match the trained behavior),
    then strip the hook/orig/u state."""
    for hid, hook in list(layer._forward_pre_hooks.items()):
        if isinstance(hook, _SpectralNormWrapper) and hook.name == name:
            hook(layer, ())          # refresh layer.__dict__[name]
            layer._forward_pre_hooks.pop(hid)
    w = layer.__dict__.pop(name, None)
    layer._parameters.pop(name + "_orig", None)
    if w is not None:
        layer.add_parameter(name, Parameter(w.numpy()))
    layer._buffers.pop(name + "_u", None)
    return layer
