"""Convolution functionals over `jax.lax.conv_general_dilated`.

Parity: `python/paddle/nn/functional/conv.py` over PHI conv kernels
(`paddle/phi/kernels/gpudnn/conv_kernel.cu` → cuDNN). On TPU the conv
lowers straight onto the MXU; XLA picks the layout/tiling, replacing the
reference's cuDNN algo search + `phi/kernels/autotune/`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core import layout as _layout
from ...ops._helpers import as_tensor


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    return v


def _conv_padding(padding, n, strides=None):
    # tuples, not lists: this value lands in op-fn closures and a list
    # would knock the op out of the memoized-vjp cache (dispatch.py
    # fingerprint INVARIANT)
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return ((padding, padding),) * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return tuple((p, p) for p in padding)
    if len(padding) == 2 * n:
        return tuple((padding[2 * i], padding[2 * i + 1])
                     for i in range(n))
    if all(isinstance(p, (list, tuple)) for p in padding):
        return tuple(tuple(p) for p in padding)
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format, name):
    x, weight = as_tensor(x), as_tensor(weight)
    from ...ops.linalg import _amp_cast2
    x, weight = _amp_cast2(x, weight)  # O1 cast + O2 dtype harmonization
    strides = _tuple(stride, n)
    dilations = _tuple(dilation, n)
    pad = _conv_padding(padding, n)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    # layout autotune (imperative/layout_autotune.cc capability): TPU convs
    # run ~20x faster channels-last, so compute internally in N...C.
    # 2-D NCHW convs under PADDLE_TPU_LAYOUT_AUTOTUNE additionally keep
    # the output PHYSICALLY NHWC (tagged, core/layout.py) so the whole
    # conv/BN/pool interior runs channels-last with one transpose per
    # graph edge; with the gate off, transposes sit at this op's edges
    # as before and XLA is left to cancel what it can.
    spec = {1: ("NWC", "OIW", "NWC"), 2: ("NHWC", "OIHW", "NHWC"),
            3: ("NDHWC", "OIDHW", "NDHWC")}[n]
    propagate = n == 2 and not channel_last and _layout.enabled()
    if x._layout is not None and not propagate:
        x = _layout.materialize(x)   # gate off / exotic format: logical in
    in_nhwc = propagate and x._layout is not None
    out_nhwc = propagate

    if propagate and not in_nhwc and groups == 1 and \
            _layout.s2d_stem_enabled():
        s2d = _s2d_stem(x, weight, bias, strides, pad, dilations)
        if s2d is not None:
            return s2d

    def _fn(a, w, *b):
        if not channel_last and not in_nhwc:
            a = jnp.moveaxis(a, 1, -1)
        dn = jax.lax.conv_dimension_numbers(a.shape, w.shape, spec)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None)
        if b:
            out = out + b[0].reshape((1,) * (out.ndim - 1)
                                     + (-1,)).astype(out.dtype)
        if not channel_last and not out_nhwc:
            out = jnp.moveaxis(out, -1, 1)
        return out
    if bias is not None:
        bias = as_tensor(bias)
        out = dispatch.apply(f"conv{n}d", _fn, (x, weight, bias))
    else:
        out = dispatch.apply(f"conv{n}d", _fn, (x, weight))
    if out_nhwc:
        out._layout = _layout.NHWC
    return out


def _s2d_stem(x, weight, bias, strides, pad, dilations):
    """Space-to-depth rewrite of the classic 3-channel 7x7/s2 ResNet stem
    (PADDLE_TPU_S2D_STEM=1; MLPerf-ResNet TPU trick). C_in=3 leaves the
    128-lane MXU ~97% idle; regrouping 2x2 pixel blocks into channels
    runs the SAME convolution as a 4x4/s1 conv over 12 channels:

        out[f,i,j] = sum_{c,p,q<7} x[c, 2i+p-3, 2j+q-3] w[f,c,p,q]
                   = sum_{c,r,t,a,b<4} y[(r,t,c), i+a-2, j+b-2]
                     * w8[f,c,2a+r,2b+t]

    with y = space_to_depth(x, 2) and w8 the kernel zero-padded to 8x8
    at the (top,left) so p=2a+r spans it exactly. The stored checkpoint
    weight stays [F,3,7,7]; the transform is traced into the step.
    Returns None when the conv doesn't match the stem pattern."""
    xs = x._data.shape        # physically NCHW here (untagged input)
    ws = weight._data.shape
    if not (len(ws) == 4 and ws[1] == 3 and ws[2:] == (7, 7)
            and strides == (2, 2) and dilations == (1, 1)
            and pad == ((3, 3), (3, 3))
            and xs[2] % 2 == 0 and xs[3] % 2 == 0):
        return None

    def _fn(a, w, *b):
        n_, c, h, wd = a.shape
        y = jnp.moveaxis(a, 1, -1)                     # N,H,W,C edge in
        y = y.reshape(n_, h // 2, 2, wd // 2, 2, c)
        y = jnp.transpose(y, (0, 1, 3, 2, 4, 5))       # N,H2,W2,r,t,C
        y = y.reshape(n_, h // 2, wd // 2, 4 * c)      # (r,t,c) channels
        w8 = jnp.pad(w, ((0, 0), (0, 0), (1, 0), (1, 0)))
        w4 = w8.reshape(w.shape[0], c, 4, 2, 4, 2)     # [F,c,a,r,b,t]
        w4 = jnp.transpose(w4, (0, 3, 5, 1, 2, 4))     # [F,r,t,c,a,b]
        w4 = w4.reshape(w.shape[0], 4 * c, 4, 4)
        dn = jax.lax.conv_dimension_numbers(
            y.shape, w4.shape, ("NHWC", "OIHW", "NHWC"))
        out = jax.lax.conv_general_dilated(
            y, w4, window_strides=(1, 1), padding=[(2, 1), (2, 1)],
            dimension_numbers=dn)
        if b:
            out = out + b[0].reshape((1, 1, 1, -1)).astype(out.dtype)
        return out

    if bias is not None:
        out = dispatch.apply("conv2d", _fn,
                             (x, weight, as_tensor(bias)))
    else:
        out = dispatch.apply("conv2d", _fn, (x, weight))
    out._layout = _layout.NHWC
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NLC" if data_format == "NLC" else "NCL"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 fmt, name)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, name)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, name)


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, n, data_format, output_size, name):
    x, weight = as_tensor(x), as_tensor(weight)
    strides = _tuple(stride, n)
    dilations = _tuple(dilation, n)
    opad = _tuple(output_padding, n) if output_padding is not None \
        else (0,) * n
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    if isinstance(padding, str):
        pads = padding.upper()
    else:
        pads = _conv_padding(padding, n)

    if channel_last:
        spec = {1: ("NWC", "OIW", "NWC"), 2: ("NHWC", "OIHW", "NHWC"),
                3: ("NDHWC", "OIDHW", "NDHWC")}[n]
        ch_in_axis = x.ndim - 1
    else:
        spec = {1: ("NCW", "OIW", "NCW"), 2: ("NCHW", "OIHW", "NCHW"),
                3: ("NCDHW", "OIDHW", "NCDHW")}[n]
        ch_in_axis = 1

    def _one_group(a, w):
        # paddle conv_transpose weight layout: [in_c, out_c, *k];
        # transpose conv = conv with lhs_dilation (fractional stride),
        # flipped kernel, swapped in/out channels.
        k = w.shape[2:]
        if isinstance(pads, str):
            if pads == "SAME":
                pad_t = [(min(dilations[i] * (k[i] - 1), strides[i] - 1
                              + dilations[i] * (k[i] - 1)) // 1,) * 2
                         for i in range(n)]
                pad_t = [(dilations[i] * (k[i] - 1) // 2,
                          dilations[i] * (k[i] - 1)
                          - dilations[i] * (k[i] - 1) // 2)
                         for i in range(n)]
            else:  # VALID
                pad_t = [(dilations[i] * (k[i] - 1),
                          dilations[i] * (k[i] - 1) + opad[i])
                         for i in range(n)]
        else:
            pad_t = []
            for i in range(n):
                lo, hi = pads[i]
                eff_k = dilations[i] * (k[i] - 1)
                pad_t.append((eff_k - lo, eff_k - hi + opad[i]))
        wf = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        wf = jnp.swapaxes(wf, 0, 1)  # [out_c, in_c, *k]
        dn = jax.lax.conv_dimension_numbers(a.shape, wf.shape, spec)
        return jax.lax.conv_general_dilated(
            a, wf, window_strides=(1,) * n, padding=pad_t,
            lhs_dilation=strides, rhs_dilation=dilations,
            dimension_numbers=dn)

    def _fn(a, w, *b):
        if groups == 1:
            out = _one_group(a, w)
        else:
            a_groups = jnp.split(a, groups, axis=ch_in_axis)
            w_groups = jnp.split(w, groups, axis=0)
            out = jnp.concatenate(
                [_one_group(ag, wg) for ag, wg in zip(a_groups, w_groups)],
                axis=ch_in_axis)
        if b:
            bias_shape = [1] * out.ndim
            ch_axis = out.ndim - 1 if channel_last else 1
            bias_shape[ch_axis] = b[0].size
            out = out + b[0].reshape(bias_shape).astype(out.dtype)
        return out
    if bias is not None:
        bias = as_tensor(bias)
        return dispatch.apply(f"conv{n}d_transpose", _fn, (x, weight, bias))
    return dispatch.apply(f"conv{n}d_transpose", _fn, (x, weight))


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format, output_size,
                           name)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size,
                           name)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size,
                           name)
