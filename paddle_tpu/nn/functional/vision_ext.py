"""Vision/sequence functional ops (round-5 kernel-family coverage).

Parity: `paddle/phi/kernels/{affine_grid,grid_sample,channel_shuffle,
pixel_unshuffle,temporal_shift,log_loss,rrelu,gather_tree,
margin_cross_entropy,spectral_norm}_kernel.h` and the matching
`python/paddle/nn/functional` entry points — implemented as pure-jax
gather/arithmetic programs that XLA fuses (no CUDA kernels to port).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...ops._helpers import as_tensor


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N,2,3] -> sampling grid [N,H,W,2]
    (`affine_grid_kernel.h`)."""
    theta = as_tensor(theta)
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy().tolist()]
    N, _, H, W = [int(s) for s in out_shape]

    def f(th):
        def axis(n):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, n)
            step = 2.0 / n
            return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)
        ys, xs = axis(H), axis(W)
        gx, gy = jnp.meshgrid(xs, ys)               # [H, W]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)   # [H, W, 3]
        # broadcast multiply-add, not einsum: coordinate math must stay
        # full f32 (matmul default precision may downcast)
        return jnp.sum(base[None, :, :, None, :].astype(th.dtype)
                       * th[:, None, None, :, :], axis=-1)
    return dispatch.apply("affine_grid", f, (theta,))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """x [N,C,H,W], grid [N,Ho,Wo,2] in [-1,1] -> [N,C,Ho,Wo]
    (`grid_sample_kernel.h`). Bilinear/nearest; zeros/border/reflection
    padding."""
    x, grid = as_tensor(x), as_tensor(grid)

    def f(xa, ga):
        N, C, H, W = xa.shape

        def unnorm(coord, size):
            if align_corners:
                return (coord + 1.0) * 0.5 * (size - 1)
            return ((coord + 1.0) * size - 1.0) * 0.5

        gx = unnorm(ga[..., 0].astype(jnp.float32), W)  # [N,Ho,Wo]
        gy = unnorm(ga[..., 1].astype(jnp.float32), H)

        def reflect(v, lo, hi):
            rng = hi - lo
            v = jnp.abs(v - lo) % (2 * rng + 1e-12)
            return lo + jnp.where(v > rng, 2 * rng - v, v)

        if padding_mode == "border":
            gx = jnp.clip(gx, 0, W - 1)
            gy = jnp.clip(gy, 0, H - 1)
        elif padding_mode == "reflection":
            gx = reflect(gx, 0.0, W - 1.0) if align_corners else \
                jnp.clip(reflect(gx, -0.5, W - 0.5), 0, W - 1)
            gy = reflect(gy, 0.0, H - 1.0) if align_corners else \
                jnp.clip(reflect(gy, -0.5, H - 0.5), 0, H - 1)

        def gather2d(iy, ix):
            iyc = jnp.clip(iy, 0, H - 1)
            ixc = jnp.clip(ix, 0, W - 1)
            # [N,C,Ho,Wo] gather via advanced indexing per batch
            bidx = jnp.arange(N)[:, None, None]
            out = xa[bidx, :, iyc, ix * 0 + ixc]      # [N,Ho,Wo,C]
            out = jnp.moveaxis(out, -1, 1)
            if padding_mode == "zeros":
                ok = ((iy >= 0) & (iy <= H - 1) & (ix >= 0)
                      & (ix <= W - 1))
                out = out * ok[:, None, :, :].astype(out.dtype)
            return out

        if mode == "nearest":
            return gather2d(jnp.round(gy).astype(jnp.int32),
                            jnp.round(gx).astype(jnp.int32)).astype(
                                xa.dtype)
        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = (gx - x0)[:, None]
        wy = (gy - y0)[:, None]
        out = (gather2d(y0, x0) * (1 - wy) * (1 - wx)
               + gather2d(y0, x1) * (1 - wy) * wx
               + gather2d(y1, x0) * wy * (1 - wx)
               + gather2d(y1, x1) * wy * wx)
        return out.astype(xa.dtype)
    return dispatch.apply("grid_sample", f, (x, grid))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """`channel_shuffle_kernel.h` — interleave channel groups."""
    x = as_tensor(x)

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, groups, c // groups, h, w) \
                    .swapaxes(1, 2).reshape(n, c, h, w)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, groups, c // groups) \
                .swapaxes(3, 4).reshape(n, h, w, c)
    return dispatch.apply("channel_shuffle", f, (x,))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    """`pixel_unshuffle_kernel.h` — inverse of pixel_shuffle."""
    x = as_tensor(x)
    r = int(downscale_factor)

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            return a.transpose(0, 1, 3, 5, 2, 4).reshape(
                n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        return a.transpose(0, 1, 3, 2, 4, 5).reshape(
            n, h // r, w // r, c * r * r)
    return dispatch.apply("pixel_unshuffle", f, (x,))


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """`temporal_shift_kernel.h` — TSM channel time-shift."""
    x = as_tensor(x)

    def f(a):
        if data_format == "NHWC":
            a = jnp.moveaxis(a, -1, 1)
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        back = jnp.pad(a[:, 1:, :c1], ((0, 0), (0, 1), (0, 0), (0, 0),
                                       (0, 0)))
        fwd = jnp.pad(a[:, :-1, c1:c2], ((0, 0), (1, 0), (0, 0), (0, 0),
                                         (0, 0)))
        out = jnp.concatenate([back, fwd, a[:, :, c2:]], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    return dispatch.apply("temporal_shift", f, (x,))


def log_loss(input, label, epsilon=1e-4, name=None):
    """`log_loss_kernel.h` — elementwise negative log likelihood."""
    input, label = as_tensor(input), as_tensor(label)

    def f(p, y):
        return (-y * jnp.log(p + epsilon)
                - (1.0 - y) * jnp.log(1.0 - p + epsilon))
    return dispatch.apply("log_loss", f, (input, label))


def rrelu(x, lower=1. / 8., upper=1. / 3., training=False, name=None):
    """`rrelu_kernel.h` — randomized leaky relu (train: slope ~
    U[lower, upper]; eval: fixed mean slope)."""
    x = as_tensor(x)
    if training:
        from ...core import random as rng
        key = rng.next_key()

        def f(a):
            slope = jax.random.uniform(key, a.shape, jnp.float32,
                                       lower, upper).astype(a.dtype)
            return jnp.where(a >= 0, a, a * slope)
        return dispatch.apply("rrelu", f, (x,))
    mid = (lower + upper) / 2.0

    def f(a):
        return jnp.where(a >= 0, a, a * jnp.asarray(mid, a.dtype))
    return dispatch.apply("rrelu", f, (x,))


def gather_tree(ids, parents, name=None):
    """`gather_tree_kernel.h` — beam-search backtrace.
    ids/parents [T, B, beam] -> full sequences [T, B, beam]."""
    ids, parents = as_tensor(ids), as_tensor(parents)

    def f(idsa, par):
        T, B, K = idsa.shape
        bidx = jnp.arange(B)[:, None]

        def step(beam, t):
            tok = idsa[t, bidx, beam]
            beam = par[t, bidx, beam]
            return beam, tok
        beam0 = jnp.broadcast_to(jnp.arange(K)[None, :], (B, K))
        _, toks = jax.lax.scan(step, beam0, jnp.arange(T - 1, -1, -1))
        return toks[::-1]
    return dispatch.apply("gather_tree", f, (ids, parents))


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean",
                         name=None):
    """`margin_cross_entropy` (ArcFace/CosFace margins, the reference's
    class-parallel `margin_cross_entropy_op.cu`) — single-shard form;
    model-parallel sharding rides GSPMD like the rest of the stack."""
    logits, label = as_tensor(logits), as_tensor(label)

    def f(lg, lab):
        lf = lg.astype(jnp.float32)
        theta = jnp.arccos(jnp.clip(lf, -1.0, 1.0))
        m_cos = jnp.cos(margin1 * theta + margin2) - margin3
        oh = jax.nn.one_hot(lab, lf.shape[-1], dtype=jnp.float32)
        adj = jnp.where(oh > 0, m_cos, lf) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        loss = -jnp.sum(oh * logp, axis=-1)
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jax.nn.softmax(adj, axis=-1)
        return loss
    return dispatch.apply("margin_cross_entropy", f, (logits, label))


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """`spectral_norm_kernel.h` — normalize weight by its largest
    singular value (power iteration)."""
    weight = as_tensor(weight)

    def f(w):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1) \
            .astype(jnp.float32)
        u = jnp.ones((wm.shape[0],), jnp.float32)
        v = jnp.ones((wm.shape[1],), jnp.float32)

        def it(_, uv):
            u, v = uv
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
            return (u, v)
        u, v = jax.lax.fori_loop(0, max(1, power_iters), it, (u, v))
        sigma = u @ wm @ v
        return (w.astype(jnp.float32) / sigma).astype(w.dtype)
    return dispatch.apply("spectral_norm", f, (weight,))


def bilinear(x1, x2, weight, bias=None, name=None):
    """`bilinear_tensor_product_kernel.h` — out[b,k] = x1[b,:] W[k] x2[b,:]."""
    x1, x2, weight = as_tensor(x1), as_tensor(x2), as_tensor(weight)
    inputs = [x1, x2, weight]
    if bias is not None:
        inputs.append(as_tensor(bias))

    def f(a, b, w, *rest):
        out = jnp.einsum("bi,kij,bj->bk", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    return dispatch.apply("bilinear", f, tuple(inputs))
