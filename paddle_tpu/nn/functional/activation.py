"""Activation functionals.

Parity: `python/paddle/nn/functional/activation.py` over PHI activation
kernels (`paddle/phi/kernels/activation_kernel.h`). All are single XLA
elementwise HLOs — fused into surrounding ops by the compiler.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...ops._helpers import as_tensor, unary


def relu(x, name=None):
    return unary("relu", jax.nn.relu, as_tensor(x))


def relu6(x, name=None):
    return unary("relu6", jax.nn.relu6, as_tensor(x))


def relu_(x, name=None):
    out = relu(x)
    x._data, x._grad_node, x._out_slot = out._data, out._grad_node, \
        out._out_slot
    x._layout = out._layout
    return x


def sigmoid(x, name=None):
    return unary("sigmoid", jax.nn.sigmoid, as_tensor(x))


def tanh(x, name=None):
    return unary("tanh", jnp.tanh, as_tensor(x))


def gelu(x, approximate=False, name=None):
    return unary("gelu",
                 lambda a: jax.nn.gelu(a, approximate=approximate),
                 as_tensor(x))


def silu(x, name=None):
    return unary("silu", jax.nn.silu, as_tensor(x))


def swish(x, name=None):
    return unary("swish", jax.nn.silu, as_tensor(x))


def mish(x, name=None):
    return unary("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)),
                 as_tensor(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return unary("leaky_relu",
                 lambda a: jax.nn.leaky_relu(a, negative_slope),
                 as_tensor(x))


def elu(x, alpha=1.0, name=None):
    return unary("elu", lambda a: jax.nn.elu(a, alpha), as_tensor(x))


def selu(x,
         scale=1.0507009873554804934193349852946,
         alpha=1.6732632423543772848170429916717, name=None):
    return unary("selu",
                 lambda a: scale * jnp.where(
                     a > 0, a, alpha * jnp.expm1(a)), as_tensor(x))


def celu(x, alpha=1.0, name=None):
    return unary("celu", lambda a: jax.nn.celu(a, alpha), as_tensor(x))


def hardshrink(x, threshold=0.5, name=None):
    return unary("hardshrink",
                 lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0),
                 as_tensor(x))


def softshrink(x, threshold=0.5, name=None):
    return unary(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)),
        as_tensor(x))


def tanhshrink(x, name=None):
    return unary("tanhshrink", lambda a: a - jnp.tanh(a), as_tensor(x))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return unary("hardsigmoid",
                 lambda a: jnp.clip(a * slope + offset, 0.0, 1.0),
                 as_tensor(x))


def hardswish(x, name=None):
    return unary("hardswish", jax.nn.hard_swish, as_tensor(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return unary("hardtanh", lambda a: jnp.clip(a, min, max), as_tensor(x))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return unary(
        "softplus",
        lambda a: jnp.where(a * beta > threshold, a,
                            jax.nn.softplus(a * beta) / beta), as_tensor(x))


def softsign(x, name=None):
    return unary("softsign", jax.nn.soft_sign, as_tensor(x))


def log_sigmoid(x, name=None):
    return unary("log_sigmoid", jax.nn.log_sigmoid, as_tensor(x))


def softmax(x, axis=-1, dtype=None, name=None):
    x = as_tensor(x)
    if dtype is not None:
        x = x.astype(dtype)
    return unary("softmax", lambda a: jax.nn.softmax(a, axis=axis), x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = as_tensor(x)
    if dtype is not None:
        x = x.astype(dtype)
    return unary("log_softmax",
                 lambda a: jax.nn.log_softmax(a, axis=axis), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as rng
    x = as_tensor(x)
    key = rng.next_key()

    def _fn(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                        inplace=False)
            y = y_hard + jax.lax.stop_gradient(-y) + y
        return y
    return unary("gumbel_softmax", _fn, x)


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = as_tensor(x), as_tensor(weight)

    def _fn(a, w):
        if w.size > 1:
            ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
            shape = [1] * a.ndim
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, w * a)
    return dispatch.apply("prelu", _fn, (x, weight))


def maxout(x, groups, axis=1, name=None):
    x = as_tensor(x)

    def _fn(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = (list(a.shape[:ax]) + [c // groups, groups]
                     + list(a.shape[ax + 1:]))
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return unary("maxout", _fn, x)


def glu(x, axis=-1, name=None):
    return unary("glu", lambda a: jax.nn.glu(a, axis=axis), as_tensor(x))
