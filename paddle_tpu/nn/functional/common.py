"""Common functionals: linear, dropout, embedding, normalize, interpolate,
one_hot, cosine_similarity, unfold.

Parity: `python/paddle/nn/functional/common.py` + `input.py` over PHI
kernels (matmul/dropout/embedding/interpolate). linear() is the MXU hot
path: x @ W + b in one fused XLA dot.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dispatch
from ...core import random as rng
from ...core.tensor import Tensor
from ...ops._helpers import as_tensor, unary
from ...ops.manipulation import pad as _pad  # re-exported


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b. Weight layout [in, out] (paddle nn.Linear)."""
    x, weight = as_tensor(x), as_tensor(weight)
    from ...ops.linalg import _amp_cast2
    x, weight = _amp_cast2(x, weight)  # O1 cast + O2 dtype harmonization
    if bias is not None:
        bias = as_tensor(bias)
        if bias.dtype != x.dtype and jnp.issubdtype(x.dtype, jnp.floating):
            bias = bias.astype(x.dtype)

        def _fn(a, w, b):
            return jnp.matmul(a, w) + b
        return dispatch.apply("linear", _fn, (x, weight, bias))

    def _fn(a, w):
        return jnp.matmul(a, w)
    return dispatch.apply("linear", _fn, (x, weight))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    x = as_tensor(x)
    if axis is not None and x._layout is not None:
        # axis semantics are logical — the tag-transparent fast path in
        # dispatch would broadcast the mask over the wrong physical axes
        from ...core import layout as _layout
        x = _layout.materialize(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return unary("dropout_scale", lambda a: a * (1 - p), x)
        return x
    key = rng.next_key()

    def _fn(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return unary("dropout", _fn, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = as_tensor(x)
    if not training or p == 0.0:
        return x
    key = rng.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def _fn(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef
    return unary("alpha_dropout", _fn, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Parity: `paddle/phi/kernels/embedding_kernel.h`; on TPU this is an
    XLA gather feeding the MXU."""
    x, weight = as_tensor(x), as_tensor(weight)

    def _fn(idx, w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return dispatch.apply("embedding", _fn, (x, weight))


def one_hot(x, num_classes, name=None):
    from ...ops.manipulation import one_hot as _oh
    return _oh(x, num_classes)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = as_tensor(x)

    def _fn(a):
        nrm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(nrm, epsilon)
    return unary("normalize", _fn, x)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    x1, x2 = as_tensor(x1), as_tensor(x2)

    def _fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)
    return dispatch.apply("cosine_similarity", _fn, (x1, x2))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = as_tensor(x)
    r = upscale_factor

    def _fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = jnp.transpose(a, (0, 1, 3, 2, 4, 5))
        return a.reshape(n, h * r, w * r, c // (r * r))
    return unary("pixel_shuffle", _fn, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (`paddle/phi/kernels/funcs/im2col.h`)."""
    x = as_tensor(x)

    def _to2(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    k, s, p, d = _to2(kernel_sizes), _to2(strides), _to2(paddings), \
        _to2(dilations)

    def _fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])))
        out_h = (h + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        out_w = (w + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        patches = []
        for i in range(k[0]):
            for j in range(k[1]):
                di, dj = i * d[0], j * d[1]
                patches.append(
                    a[:, :, di:di + out_h * s[0]:s[0],
                      dj:dj + out_w * s[1]:s[1]])
        col = jnp.stack(patches, axis=2)  # [N, C, k*k, oh, ow]
        return col.reshape(n, c * k[0] * k[1], out_h * out_w)
    return unary("unfold", _fn, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0,
         dilations=1, name=None):
    """col2im — inverse of unfold: overlapping patches scatter-ADD back
    (`paddle/phi/kernels/funcs/im2col.h` col2im path)."""
    x = as_tensor(x)

    def _to2(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    o = _to2(output_sizes)
    k, s, p, d = _to2(kernel_sizes), _to2(strides), _to2(paddings), \
        _to2(dilations)

    def _fn(a):
        n, ckk, L = a.shape
        c = ckk // (k[0] * k[1])
        out_h = (o[0] + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        out_w = (o[1] + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        col = a.reshape(n, c, k[0] * k[1], out_h, out_w)
        out = jnp.zeros((n, c, o[0] + 2 * p[0], o[1] + 2 * p[1]),
                        a.dtype)
        pos = 0
        for i in range(k[0]):
            for j in range(k[1]):
                di, dj = i * d[0], j * d[1]
                out = out.at[:, :, di:di + out_h * s[0]:s[0],
                             dj:dj + out_w * s[1]:s[1]].add(
                    col[:, :, pos])
                pos += 1
        return out[:, :, p[0]:p[0] + o[0], p[1]:p[1] + o[1]]

    return unary("fold", _fn, x)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    from ...core import layout as _layout
    x = as_tensor(x)
    nchw = data_format in ("NCHW", "NCDHW", "NCL")

    # layout propagation: resize the tagged (physically NHWC) array in
    # place — jax.image.resize is layout-agnostic given the full shape
    tagged = (data_format == "NCHW" and x._layout is not None
              and _layout.enabled())
    if x._layout is not None and not tagged:
        x = _layout.materialize(x)
    if tagged:
        nchw = False

    def _fn(a):
        spatial = a.shape[2:] if nchw else a.shape[1:-1]
        if size is not None:
            tgt = [int(v) for v in (size.tolist() if isinstance(size, Tensor)
                                    else size)]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(spatial)
            tgt = [int(s * f) for s, f in zip(spatial, sf)]
        jmode = {"nearest": "nearest", "bilinear": "linear",
                 "linear": "linear", "trilinear": "linear",
                 "bicubic": "cubic", "area": "linear"}[mode]
        if nchw:
            full = list(a.shape[:2]) + tgt
        else:
            full = [a.shape[0]] + tgt + [a.shape[-1]]
        return jax.image.resize(a, full, method=jmode)
    out = unary("interpolate", _fn, x)
    if tagged:
        out._layout = _layout.NHWC
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       data_format=data_format)


pad = _pad


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = as_tensor(label)

    def _fn(a):
        k = a.shape[-1]
        if prior_dist is None:
            return (1 - epsilon) * a + epsilon / k
        return (1 - epsilon) * a + epsilon * prior_dist._data
    return unary("label_smooth", _fn, label)


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError("class_center_sample: planned (PS round)")
