"""Normalization functionals.

Parity: `python/paddle/nn/functional/norm.py` over PHI batch_norm /
layer_norm / group_norm kernels (`paddle/phi/kernels/batch_norm_kernel.h`,
`layer_norm_kernel.h`). On TPU these are XLA-fused reductions +
elementwise — no cuDNN equivalent needed.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core import layout as _layout
from ...core.tensor import Tensor
from ...ops._helpers import as_tensor


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _bn_train_core(axes, eps, x, w, b):
    """Affine train-mode batch norm with a hand-written backward.

    jax AD of the naive form runs three separate reduction fusions over
    the feature map (profiled at ~20% of a ResNet-50 train step); the
    analytic backward needs exactly two passes — one fused quad-reduce
    (sum dy, sum dy*xhat — both read (dy, x) once) and one elementwise
    dx pass."""
    return _bn_fwd_math(axes, eps, x, w, b)[0]


def _bn_fwd_math(axes, eps, x, w, b):
    af = x.astype(jnp.float32)
    m1 = jnp.mean(af, axis=axes, keepdims=True)
    # Centered two-pass variance: E[(x-m)^2].  The single-pass
    # E[x^2]-E[x]^2 form cancels catastrophically in f32 when
    # |mean| >> std, silently collapsing var toward 0.
    var = jnp.mean(jnp.square(af - m1), axis=axes, keepdims=True)
    ivar = jax.lax.rsqrt(var + eps)
    xhat = (af - m1) * ivar
    bshape = m1.shape
    out = xhat * w.astype(jnp.float32).reshape(bshape) \
        + b.astype(jnp.float32).reshape(bshape)
    return ((out.astype(x.dtype), m1.reshape(-1), var.reshape(-1)),
            (x, m1, ivar, w))


def _bn_train_fwd(axes, eps, x, w, b):
    return _bn_fwd_math(axes, eps, x, w, b)


def _bn_train_bwd(axes, eps, res, cots):
    x, m1, ivar, w = res
    dy, dm1_c, dvar_c = cots
    n = 1
    for ax in axes:
        n *= x.shape[ax]
    nf = jnp.float32(n)
    af = x.astype(jnp.float32)
    xhat = (af - m1) * ivar
    dyf = dy.astype(jnp.float32)
    bshape = m1.shape
    # pass 1: both reductions read (dy, x) once (multi-output fusion)
    s1 = jnp.sum(dyf, axis=axes, keepdims=True)          # = dbeta
    s2 = jnp.sum(dyf * xhat, axis=axes, keepdims=True)   # = dgamma
    wf = w.astype(jnp.float32).reshape(bshape)
    # pass 2: elementwise dx (+ cotangents of the mean/var outputs,
    # which feed running-stat updates: usually zero, kept for
    # correctness — they are per-channel broadcasts, no extra pass)
    dx = (wf * ivar / nf) * (nf * dyf - s1 - xhat * s2)
    if dm1_c is not None:
        dx = dx + dm1_c.reshape(bshape) / nf
    if dvar_c is not None:
        dx = dx + dvar_c.reshape(bshape) * 2.0 * (af - m1) / nf
    dgamma = s2.reshape(-1).astype(w.dtype)
    dbeta = s1.reshape(-1)
    return (dx.astype(x.dtype), dgamma, dbeta.astype(w.dtype))


_bn_train_core.defvjp(_bn_train_fwd, _bn_train_bwd)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    x = as_tensor(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    # layout propagation (core/layout.py): a tagged input is physically
    # NHWC — reduce over the leading axes and keep the output tagged, so
    # conv->BN->conv chains never transpose. Per-channel running stats /
    # affine params are 1-D and layout-free.
    tagged = (not channel_last and x._layout is not None
              and _layout.enabled())
    if x._layout is not None and not tagged:
        x = _layout.materialize(x)
    phys_cl = channel_last or tagged
    nd = x._data.ndim
    ch_axis = nd - 1 if phys_cl else (1 if nd > 1 else 0)
    reduce_axes = tuple(i for i in range(nd) if i != ch_axis)
    use_stats = (not training) if use_global_stats is None else \
        use_global_stats

    inputs = [x]
    w_idx = b_idx = None
    if weight is not None:
        w_idx = len(inputs)
        inputs.append(as_tensor(weight))
    if bias is not None:
        b_idx = len(inputs)
        inputs.append(as_tensor(bias))

    # tuple, not list: lists aren't hashable so a list bshape would
    # knock this op out of the memoized-vjp cache (dispatch.py)
    bshape = tuple(x._data.shape[i] if i == ch_axis else 1
                   for i in range(nd))

    if use_stats:
        rm, rv = as_tensor(running_mean), as_tensor(running_var)
        inputs.extend([rm, rv])

        def _fn(*arrs):
            a = arrs[0]
            mean = arrs[-2].reshape(bshape)
            var = arrs[-1].reshape(bshape)
            out = (a - mean) / jnp.sqrt(var + epsilon)
            if w_idx is not None:
                out = out * arrs[w_idx].reshape(bshape)
            if b_idx is not None:
                out = out + arrs[b_idx].reshape(bshape)
            return out.astype(a.dtype)
        out = dispatch.apply("batch_norm_infer", _fn, tuple(inputs))
        if tagged:
            out._layout = _layout.NHWC
        return out

    # training: compute batch stats; update running stats (stateful, on the
    # Tensor wrappers — traced arrays flow through during functional mode).
    # PERF: on the TPU backend, mixed-dtype (bf16 data + f32 stats)
    # backward is pathologically slow (~35x, measured); for bf16 inputs we
    # therefore keep the whole computation in bf16 (standard TPU practice
    # — the var uses E[x^2]-E[x]^2 whose grads lower cleanly, unlike
    # jnp.var's). fp32 inputs keep fp32 stats.
    def _fn(*arrs):
        a = arrs[0]
        if w_idx is not None and b_idx is not None:
            # affine hot path: single-pass f32 moments forward +
            # analytic two-pass backward (see _bn_train_core)
            return _bn_train_core(reduce_axes, epsilon, a,
                                  arrs[w_idx], arrs[b_idx])
        # generic path (no affine params): same math, jax AD backward.
        # f32 accumulation keeps E[x^2]-E[x]^2 from cancelling (it was
        # bf16 accumulation that produced negative variances).
        af = a.astype(jnp.float32)
        m1 = jnp.mean(af, axis=reduce_axes, keepdims=True)
        m2 = jnp.mean(jnp.square(af), axis=reduce_axes, keepdims=True)
        var = jnp.maximum(m2 - jnp.square(m1), 0.0)
        out = (af - m1) * jax.lax.rsqrt(var + epsilon)
        if w_idx is not None:
            out = out * arrs[w_idx].astype(jnp.float32).reshape(bshape)
        if b_idx is not None:
            out = out + arrs[b_idx].astype(jnp.float32).reshape(bshape)
        return (out.astype(a.dtype),
                m1.reshape(-1),
                var.reshape(-1))

    out, batch_mean, batch_var = dispatch.apply(
        "batch_norm_train", _fn, tuple(inputs))
    if tagged:
        out._layout = _layout.NHWC
    if running_mean is not None:
        rm, rv = as_tensor(running_mean), as_tensor(running_var)
        # The reference kernel updates running_var with the *biased*
        # batch variance (paddle/phi/kernels/cpu/batch_norm_kernel.cc:125,
        # 152) — no n/(n-1) correction — so checkpoints eval identically.
        rm._data = (momentum * rm._data
                    + (1 - momentum) * batch_mean._data.astype(rm.dtype))
        rv._data = (momentum * rv._data
                    + (1 - momentum) * batch_var._data.astype(rv.dtype))
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    x = as_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))
    axes = tuple(range(x.ndim - n_axes, x.ndim))

    inputs = [x]
    w_idx = b_idx = None
    if weight is not None:
        w_idx = len(inputs)
        inputs.append(as_tensor(weight))
    if bias is not None:
        b_idx = len(inputs)
        inputs.append(as_tensor(bias))

    def _fn(*arrs):
        a = arrs[0]
        af = a.astype(jnp.float32)
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = (af - mean) / jnp.sqrt(var + epsilon)
        if w_idx is not None:
            out = out * arrs[w_idx].astype(jnp.float32)
        if b_idx is not None:
            out = out + arrs[b_idx].astype(jnp.float32)
        return out.astype(a.dtype)
    return dispatch.apply("layer_norm", _fn, tuple(inputs))


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = as_tensor(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    inputs = [x]
    w_idx = b_idx = None
    if weight is not None:
        w_idx = len(inputs)
        inputs.append(as_tensor(weight))
    if bias is not None:
        b_idx = len(inputs)
        inputs.append(as_tensor(bias))

    def _fn(*arrs):
        a = arrs[0]
        af = a.astype(jnp.float32)
        if channel_last:
            af = jnp.moveaxis(af, -1, 1)
        shp = af.shape
        g = af.reshape(shp[0], num_groups, shp[1] // num_groups, *shp[2:])
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) / jnp.sqrt(var + epsilon)).reshape(shp)
        bshape = [1, shp[1]] + [1] * (len(shp) - 2)
        if w_idx is not None:
            out = out * arrs[w_idx].astype(jnp.float32).reshape(bshape)
        if b_idx is not None:
            out = out + arrs[b_idx].astype(jnp.float32).reshape(bshape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out.astype(a.dtype)
    return dispatch.apply("group_norm", _fn, tuple(inputs))


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9,
                  eps=1e-5, data_format="NCHW", name=None):
    x = as_tensor(x)
    inputs = [x]
    w_idx = b_idx = None
    if weight is not None:
        w_idx = len(inputs)
        inputs.append(as_tensor(weight))
    if bias is not None:
        b_idx = len(inputs)
        inputs.append(as_tensor(bias))

    def _fn(*arrs):
        a = arrs[0]
        af = a.astype(jnp.float32)
        axes = tuple(range(2, af.ndim))
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = (af - mean) / jnp.sqrt(var + eps)
        bshape = [1, af.shape[1]] + [1] * (af.ndim - 2)
        if w_idx is not None:
            out = out * arrs[w_idx].astype(jnp.float32).reshape(bshape)
        if b_idx is not None:
            out = out + arrs[b_idx].astype(jnp.float32).reshape(bshape)
        return out.astype(a.dtype)
    return dispatch.apply("instance_norm", _fn, tuple(inputs))


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = as_tensor(x)

    def _fn(a):
        sq = a * a
        half = size // 2
        ch = a.shape[1]
        pads = [(0, 0)] * a.ndim
        pads[1] = (half, size - half - 1)
        sq = jnp.pad(sq, pads)
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + jnp.take(sq, jnp.arange(i, i + ch), axis=1)
        return a / (k + alpha * acc) ** beta
    from ...ops._helpers import unary
    return unary("lrn", _fn, x)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (LLM-era extension; reference has fused rms_norm in
    fluid/operators/fused)."""
    x = as_tensor(x)
    inputs = [x]
    if weight is not None:
        inputs.append(as_tensor(weight))

    def _fn(a, *w):
        af = a.astype(jnp.float32)
        scale = jnp.sqrt(jnp.mean(af * af, axis=-1, keepdims=True) + epsilon)
        out = af / scale
        if w:
            out = out * w[0].astype(jnp.float32)
        return out.astype(a.dtype)
    return dispatch.apply("rms_norm", _fn, tuple(inputs))
