"""Normalization functionals.

Parity: `python/paddle/nn/functional/norm.py` over PHI batch_norm /
layer_norm / group_norm kernels (`paddle/phi/kernels/batch_norm_kernel.h`,
`layer_norm_kernel.h`). On TPU these are XLA-fused reductions +
elementwise — no cuDNN equivalent needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor
from ...ops._helpers import as_tensor


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    x = as_tensor(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ch_axis = x.ndim - 1 if channel_last else (1 if x.ndim > 1 else 0)
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_stats = (not training) if use_global_stats is None else \
        use_global_stats

    inputs = [x]
    w_idx = b_idx = None
    if weight is not None:
        w_idx = len(inputs)
        inputs.append(as_tensor(weight))
    if bias is not None:
        b_idx = len(inputs)
        inputs.append(as_tensor(bias))

    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]

    if use_stats:
        rm, rv = as_tensor(running_mean), as_tensor(running_var)
        inputs.extend([rm, rv])

        def _fn(*arrs):
            a = arrs[0]
            mean = arrs[-2].reshape(bshape)
            var = arrs[-1].reshape(bshape)
            out = (a - mean) / jnp.sqrt(var + epsilon)
            if w_idx is not None:
                out = out * arrs[w_idx].reshape(bshape)
            if b_idx is not None:
                out = out + arrs[b_idx].reshape(bshape)
            return out.astype(a.dtype)
        return dispatch.apply("batch_norm_infer", _fn, tuple(inputs))

    # training: compute batch stats; update running stats (stateful, on the
    # Tensor wrappers — traced arrays flow through during functional mode).
    # PERF: on the TPU backend, mixed-dtype (bf16 data + f32 stats)
    # backward is pathologically slow (~35x, measured); for bf16 inputs we
    # therefore keep the whole computation in bf16 (standard TPU practice
    # — the var uses E[x^2]-E[x]^2 whose grads lower cleanly, unlike
    # jnp.var's). fp32 inputs keep fp32 stats.
    def _fn(*arrs):
        a = arrs[0]
        cd = a.dtype if a.dtype == jnp.bfloat16 else jnp.float32
        af = a.astype(cd)
        mean = jnp.mean(af, axis=reduce_axes, keepdims=True)
        # centered two-pass variance: no E[x^2]-E[x]^2 cancellation (which
        # goes negative -> NaN in bf16), grads stay mean-shaped (fast)
        centered = af - mean
        var = jnp.mean(jnp.square(centered), axis=reduce_axes,
                       keepdims=True)
        out = centered * jax.lax.rsqrt(var + epsilon)
        if w_idx is not None:
            out = out * arrs[w_idx].astype(cd).reshape(bshape)
        if b_idx is not None:
            out = out + arrs[b_idx].astype(cd).reshape(bshape)
        return (out.astype(a.dtype),
                mean.reshape(-1).astype(jnp.float32),
                var.reshape(-1).astype(jnp.float32))

    out, batch_mean, batch_var = dispatch.apply(
        "batch_norm_train", _fn, tuple(inputs))
    if running_mean is not None:
        rm, rv = as_tensor(running_mean), as_tensor(running_var)
        # The reference kernel updates running_var with the *biased*
        # batch variance (paddle/phi/kernels/cpu/batch_norm_kernel.cc:125,
        # 152) — no n/(n-1) correction — so checkpoints eval identically.
        rm._data = (momentum * rm._data
                    + (1 - momentum) * batch_mean._data.astype(rm.dtype))
        rv._data = (momentum * rv._data
                    + (1 - momentum) * batch_var._data.astype(rv.dtype))
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    x = as_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))
    axes = tuple(range(x.ndim - n_axes, x.ndim))

    inputs = [x]
    w_idx = b_idx = None
    if weight is not None:
        w_idx = len(inputs)
        inputs.append(as_tensor(weight))
    if bias is not None:
        b_idx = len(inputs)
        inputs.append(as_tensor(bias))

    def _fn(*arrs):
        a = arrs[0]
        af = a.astype(jnp.float32)
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = (af - mean) / jnp.sqrt(var + epsilon)
        if w_idx is not None:
            out = out * arrs[w_idx].astype(jnp.float32)
        if b_idx is not None:
            out = out + arrs[b_idx].astype(jnp.float32)
        return out.astype(a.dtype)
    return dispatch.apply("layer_norm", _fn, tuple(inputs))


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = as_tensor(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    inputs = [x]
    w_idx = b_idx = None
    if weight is not None:
        w_idx = len(inputs)
        inputs.append(as_tensor(weight))
    if bias is not None:
        b_idx = len(inputs)
        inputs.append(as_tensor(bias))

    def _fn(*arrs):
        a = arrs[0]
        af = a.astype(jnp.float32)
        if channel_last:
            af = jnp.moveaxis(af, -1, 1)
        shp = af.shape
        g = af.reshape(shp[0], num_groups, shp[1] // num_groups, *shp[2:])
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) / jnp.sqrt(var + epsilon)).reshape(shp)
        bshape = [1, shp[1]] + [1] * (len(shp) - 2)
        if w_idx is not None:
            out = out * arrs[w_idx].astype(jnp.float32).reshape(bshape)
        if b_idx is not None:
            out = out + arrs[b_idx].astype(jnp.float32).reshape(bshape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out.astype(a.dtype)
    return dispatch.apply("group_norm", _fn, tuple(inputs))


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9,
                  eps=1e-5, data_format="NCHW", name=None):
    x = as_tensor(x)
    inputs = [x]
    w_idx = b_idx = None
    if weight is not None:
        w_idx = len(inputs)
        inputs.append(as_tensor(weight))
    if bias is not None:
        b_idx = len(inputs)
        inputs.append(as_tensor(bias))

    def _fn(*arrs):
        a = arrs[0]
        af = a.astype(jnp.float32)
        axes = tuple(range(2, af.ndim))
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = (af - mean) / jnp.sqrt(var + eps)
        bshape = [1, af.shape[1]] + [1] * (af.ndim - 2)
        if w_idx is not None:
            out = out * arrs[w_idx].astype(jnp.float32).reshape(bshape)
        if b_idx is not None:
            out = out + arrs[b_idx].astype(jnp.float32).reshape(bshape)
        return out.astype(a.dtype)
    return dispatch.apply("instance_norm", _fn, tuple(inputs))


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = as_tensor(x)

    def _fn(a):
        sq = a * a
        half = size // 2
        ch = a.shape[1]
        pads = [(0, 0)] * a.ndim
        pads[1] = (half, size - half - 1)
        sq = jnp.pad(sq, pads)
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + jnp.take(sq, jnp.arange(i, i + ch), axis=1)
        return a / (k + alpha * acc) ** beta
    from ...ops._helpers import unary
    return unary("lrn", _fn, x)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (LLM-era extension; reference has fused rms_norm in
    fluid/operators/fused)."""
    x = as_tensor(x)
    inputs = [x]
    if weight is not None:
        inputs.append(as_tensor(weight))

    def _fn(a, *w):
        af = a.astype(jnp.float32)
        scale = jnp.sqrt(jnp.mean(af * af, axis=-1, keepdims=True) + epsilon)
        out = af / scale
        if w:
            out = out * w[0].astype(jnp.float32)
        return out.astype(a.dtype)
    return dispatch.apply("rms_norm", _fn, tuple(inputs))
