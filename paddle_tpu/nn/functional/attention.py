"""Attention functionals: the long-context hot path.

Parity: `python/paddle/nn/functional/flash_attention.py:142` over the
reference's FlashAttention integration (`paddle/phi/kernels/flash_attn_kernel.h`,
`cmake/external/flashattn.cmake`) and `sparse_attention`
(`python/paddle/nn/functional/sparse_attention.py`).

TPU-native: `scaled_dot_product_attention` dispatches to a Pallas
flash-attention kernel on TPU (paddle_tpu/ops/pallas/flash_attention.py)
with an XLA fallback that the compiler fuses well on the MXU.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...ops._helpers import as_tensor


def _xla_attention(q, k, v, bias=None, causal=False, scale=None,
                   dropout_p=0.0, dropout_key=None):
    """Reference XLA attention: [B, S, H, D] layout (paddle flash_attention
    layout). Fast path: jax's fused flash-style attention (no [S,S] probs
    materialized — ~180x faster fwd+bwd on v5e at S=1024). General path
    (arbitrary bias rank / dropout) computes probs explicitly in fp32."""
    # Fast path constraints: jax's is_causal mask is top-left aligned, so
    # it only matches our bottom-right-aligned general path when q and k
    # have equal sequence length (KV-cache decode must use the general
    # path).
    if dropout_p == 0.0 and q.shape[-1] == k.shape[-1] and \
            (not causal or q.shape[1] == k.shape[1]):
        try:
            return jax.nn.dot_product_attention(
                q, k, v, bias=bias, is_causal=causal, scale=scale)
        except (ValueError, TypeError):
            pass  # e.g. unbroadcastable bias rank -> general path
    orig_dtype = q.dtype
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qf = q.astype(jnp.float32) * scale
    logits = jnp.einsum("bshd,bthd->bhst", qf, k.astype(jnp.float32))
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), dtype=bool), k=t - s)
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhst,bthd->bshd", probs, v.astype(jnp.float32))
    return out.astype(orig_dtype)


def _is_key_padding_mask(mask) -> bool:
    """Static (shape+dtype) test: is this mask a per-key boolean padding
    mask the splash kernel can take as segment ids?  [*, 1, 1, S] bool
    only — a 2-D mask means [S, S] in paddle's broadcast convention, and
    float masks are additive biases whose values must be honored exactly
    (ALiBi-style soft biases would be silently destroyed by any
    keep/drop binarization), so they always take the additive XLA
    path."""
    return (mask.dtype == jnp.bool_ and mask.ndim == 4
            and mask.shape[1] == 1 and mask.shape[2] == 1)


def _mask_to_keep(mask, batch):
    """[*, 1, 1, S] bool mask -> [B, S] int32 keep vector (True =
    attend), broadcast over a size-1 mask batch dim."""
    flat = mask.reshape(mask.shape[0], mask.shape[-1])
    return jnp.broadcast_to(flat, (batch, mask.shape[-1])).astype(
        jnp.int32)


def _bias_from_mask(mask):
    """Additive f32 bias from a bool or float mask (for the XLA path)."""
    if mask is None:
        return None
    if mask.dtype == jnp.bool_:
        return jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
    return mask.astype(jnp.float32)


def _attention_impl(q, k, v, bias, causal, scale, dropout_p, dropout_key,
                    use_pallas):
    if use_pallas and dropout_p == 0.0 \
            and q.shape[1] == k.shape[1] and q.shape[2] == k.shape[2] \
            and (bias is None or _is_key_padding_mask(bias)):
        # equal head counts only: GQA/MQA q/kv head mismatch takes the
        # XLA path (jax.nn.dot_product_attention broadcasts kv heads)
        from ...ops.pallas.flash_attention import (splash_mha,
                                                  splash_supported)
        if splash_supported(q.shape[1], q.shape[-1]):
            kv_keep = None if bias is None else _mask_to_keep(
                bias, q.shape[0])
            # [B, S, H, D] -> [B, H, S, D] kernel layout
            out = splash_mha(
                jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                jnp.swapaxes(v, 1, 2), causal=causal, scale=scale,
                kv_keep=kv_keep)
            return jnp.swapaxes(out, 1, 2)
    bias = _bias_from_mask(bias)
    return _xla_attention(q, k, v, bias, causal, scale, dropout_p,
                          dropout_key)


def _on_tpu(arr) -> bool:
    # splash (Pallas flash, fused backward) is the default on TPU —
    # trace-measured 2.1x faster fwd+bwd than XLA's fused attention at
    # [32,16,1024,64] (docs/gpt_perf_analysis.md). Opt out with
    # paddle.set_flags({"FLAGS_use_pallas_flash_attention": False}) or
    # PADDLE_TPU_PALLAS_FLASH=0.
    import os
    if os.environ.get("PADDLE_TPU_PALLAS_FLASH", "1") != "1":
        return False
    from ... import flags as _flags
    if not _flags.get_flags("FLAGS_use_pallas_flash_attention")[
            "FLAGS_use_pallas_flash_attention"]:
        return False
    from ...ops.pallas.flash_attention import _on_tpu_backend
    return _on_tpu_backend()


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    training=True, name=None):
    """paddle.nn.functional.flash_attention parity: inputs [B, S, H, D]."""
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    from ...core import random as rng
    dkey = rng.next_key() if (dropout > 0.0 and training) else None
    use_pallas = _on_tpu(q._data) and dkey is None

    def _fn(qa, ka, va):
        return _attention_impl(qa, ka, va, None, causal, None,
                               dropout if training else 0.0, dkey,
                               use_pallas)
    out = dispatch.apply("flash_attention", _fn, (q, k, v))
    if return_softmax:
        return out, None
    return out, None


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """[B, S, H, D] in/out — paddle 2.5+ SDPA API."""
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    inputs = [q, k, v]
    if attn_mask is not None:
        inputs.append(as_tensor(attn_mask))
    from ...core import random as rng
    dkey = rng.next_key() if (dropout_p > 0.0 and training) else None
    # [*,1,1,S] bool key-padding masks ride the splash kernel as
    # segment ids; float biases and dense per-query masks take the
    # exact additive XLA path, as does any attention dropout (probs
    # dropout cannot ride a fused flash kernel)
    m = inputs[3]._data if len(inputs) > 3 else None
    use_pallas = _on_tpu(q._data) and dropout_p == 0.0 and (
        m is None or _is_key_padding_mask(m))

    def _fn(qa, ka, va, *rest):
        bias = rest[0] if rest else None
        return _attention_impl(qa, ka, va, bias, is_causal, None,
                               dropout_p if training else 0.0, dkey,
                               use_pallas)
    return dispatch.apply("sdpa", _fn, tuple(inputs))


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Parity: `python/paddle/nn/functional/sparse_attention.py` —
    layout [B, H, S, D] with a per-(batch, head) CSR sparsity pattern.

    TPU-native realisation: the CSR pattern densifies into an additive
    mask consumed by the fused attention (XLA's flash-style kernel skips
    fully-masked blocks); a Pallas block-sparse kernel is the perf
    upgrade path.
    """
    q = as_tensor(query)
    k = as_tensor(key)
    v = as_tensor(value)
    offs = as_tensor(sparse_csr_offset)
    cols = as_tensor(sparse_csr_columns)
    extra = []
    kpm_idx = am_idx = None
    if key_padding_mask is not None:
        kpm_idx = len(extra)
        extra.append(as_tensor(key_padding_mask))
    if attn_mask is not None:
        am_idx = len(extra)
        extra.append(as_tensor(attn_mask))

    def _fn(qa, ka, va, off, col, *rest):
        B, H, S, D = qa.shape
        # dense bool mask [B, H, S, S] from CSR rows (padded column
        # entries map past the last offset and are dropped by jax's
        # out-of-bounds scatter semantics)

        def one_bh(off_bh, col_bh):
            # positions of each nnz entry -> (row, col) scatter
            rows = jnp.searchsorted(off_bh, jnp.arange(col_bh.shape[0]),
                                    side="right") - 1
            m = jnp.zeros((S, S), bool)
            return m.at[rows, col_bh].set(True)
        mask = jax.vmap(jax.vmap(one_bh))(off, col)
        bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
        if kpm_idx is not None:
            kpm = rest[kpm_idx]  # [B, S]: 0 masks the key position
            bias = bias + jnp.where(kpm[:, None, None, :] > 0.5, 0.0,
                                    -1e30)
        if am_idx is not None:
            bias = bias + rest[am_idx].astype(jnp.float32)
        # to [B, S, H, D] for the fused kernel
        qt = jnp.swapaxes(qa, 1, 2)
        kt = jnp.swapaxes(ka, 1, 2)
        vt = jnp.swapaxes(va, 1, 2)
        out = _xla_attention(qt, kt, vt, bias=bias, causal=False)
        return jnp.swapaxes(out, 1, 2)
    return dispatch.apply("sparse_attention", _fn,
                          (q, k, v, offs, cols, *extra))
