"""Pooling functionals over `jax.lax.reduce_window`.

Parity: `python/paddle/nn/functional/pooling.py` over PHI pool kernels
(`paddle/phi/kernels/pool_kernel.h`, `gpudnn/pool_kernel.cu`).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._helpers import as_tensor, unary
from .conv import _tuple


def _pool(x, kernel_size, stride, padding, n, reducer, init, channel_last,
          ceil_mode=False, count_include_pad=True, average=False,
          exclusive=True):
    x = as_tensor(x)
    k = _tuple(kernel_size, n)
    s = _tuple(stride if stride is not None else kernel_size, n)
    if isinstance(padding, str):
        pad_mode = padding.upper()
        pads = None
    else:
        pad_mode = None
        p = _tuple(padding, n) if not isinstance(padding, (list, tuple)) or \
            all(isinstance(v, int) for v in padding) else padding
        if isinstance(p, tuple) and len(p) == n:
            pads = [(v, v) for v in p]
        else:
            pads = [tuple(v) for v in p]

    def _fn(a):
        # channels-last internally (layout autotune; see conv.py)
        to_cl = not channel_last
        if to_cl:
            a = jnp.moveaxis(a, 1, -1)
        window = (1,) + k + (1,)
        strides_full = (1,) + s + (1,)
        pad_full = [(0, 0)] + (pads or [(0, 0)] * n) + [(0, 0)]
        pad_cfg = pad_mode if pad_mode is not None else pad_full
        out = jax.lax.reduce_window(
            a, init(a.dtype), reducer, window, strides_full,
            pad_cfg if isinstance(pad_cfg, str) else pad_cfg)
        if average:
            if exclusive and pads is not None and any(
                    p_ != (0, 0) for p_ in (pads or [])):
                ones = jnp.ones_like(a)
                counts = jax.lax.reduce_window(
                    ones, 0.0 if not jnp.issubdtype(a.dtype, jnp.integer)
                    else 0, jax.lax.add, window, strides_full, pad_cfg)
                out = out / counts
            else:
                out = out / float(np.prod(k))
        if to_cl:
            out = jnp.moveaxis(out, -1, 1)
        return out
    return unary("pool", _fn, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    def init(dt):
        return -jnp.inf if jnp.issubdtype(dt, jnp.floating) else \
            jnp.iinfo(dt).min
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.max, init,
                 channel_last=False, ceil_mode=ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    def init(dt):
        return -jnp.inf if jnp.issubdtype(dt, jnp.floating) else \
            jnp.iinfo(dt).min
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.max, init,
                 channel_last=(data_format == "NHWC"), ceil_mode=ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    def init(dt):
        return -jnp.inf if jnp.issubdtype(dt, jnp.floating) else \
            jnp.iinfo(dt).min
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.max, init,
                 channel_last=(data_format == "NDHWC"), ceil_mode=ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.add,
                 lambda dt: jnp.zeros((), dt).item() if False else 0.0,
                 channel_last=False, average=True, exclusive=exclusive,
                 ceil_mode=ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.add,
                 lambda dt: 0.0, channel_last=(data_format == "NHWC"),
                 average=True, exclusive=exclusive, ceil_mode=ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.add,
                 lambda dt: 0.0, channel_last=(data_format == "NDHWC"),
                 average=True, exclusive=exclusive, ceil_mode=ceil_mode)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", False)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max", False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max", False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max", False)


def _adaptive(x, output_size, n, mode, channel_last):
    x = as_tensor(x)
    out_sz = _tuple(output_size, n)

    def _fn(a):
        spatial = a.shape[2:2 + n] if not channel_last else a.shape[1:1 + n]
        # exact adaptive pooling when divisible; else mean over variable bins
        if all(s % o == 0 for s, o in zip(spatial, out_sz)):
            k = tuple(s // o for s, o in zip(spatial, out_sz))
            if channel_last:
                window = (1,) + k + (1,)
            else:
                window = (1, 1) + k
            red = jax.lax.max if mode == "max" else jax.lax.add
            init = (-jnp.inf if mode == "max" else 0.0)
            out = jax.lax.reduce_window(a, init, red, window, window,
                                        "VALID")
            if mode == "avg":
                out = out / float(np.prod(k))
            return out
        # general path: resize-style bins
        slices = []
        for dim_i, (s, o) in enumerate(zip(spatial, out_sz)):
            starts = [int(np.floor(i * s / o)) for i in range(o)]
            ends = [int(np.ceil((i + 1) * s / o)) for i in range(o)]
            slices.append((starts, ends))

        def pool_one(index):
            idx = [slice(None)] * a.ndim
            base = 1 if channel_last else 2
            for d, ii in enumerate(index):
                st, en = slices[d][0][ii], slices[d][1][ii]
                idx[base + d] = slice(st, en)
            patch = a[tuple(idx)]
            axes = tuple(range(base, base + n))
            return (jnp.max(patch, axis=axes) if mode == "max"
                    else jnp.mean(patch, axis=axes))
        import itertools
        outs = [pool_one(ix) for ix in itertools.product(
            *[range(o) for o in out_sz])]
        stacked = jnp.stack(outs, axis=-1)
        if channel_last:
            nb, c = a.shape[0], a.shape[-1]
            return stacked.reshape((nb, c) + tuple(out_sz)).transpose(
                (0,) + tuple(range(2, 2 + n)) + (1,))
        nb, c = a.shape[0], a.shape[1]
        return stacked.reshape((nb, c) + tuple(out_sz))
    return unary("adaptive_pool", _fn, x)
