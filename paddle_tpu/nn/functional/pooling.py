"""Pooling functionals over `jax.lax.reduce_window`.

Parity: `python/paddle/nn/functional/pooling.py` over PHI pool kernels
(`paddle/phi/kernels/pool_kernel.h`, `gpudnn/pool_kernel.cu`).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import layout as _layout
from ...ops._helpers import as_tensor, unary
from .conv import _tuple


def _ceil_extra(size, k, s, lo, hi):
    """Extra high-side padding so the last (partial) window is emitted:
    ceil((size+lo+hi-k)/s)+1 outputs instead of floor (PHI pool kernels'
    AdaptStartEndIndex ceil branch)."""
    span = size + lo + hi
    out_floor = (span - k) // s + 1
    out_ceil = -((span - k) // -s) + 1
    if out_ceil <= out_floor:
        return 0
    return (out_ceil - 1) * s + k - span


def _pool(x, kernel_size, stride, padding, n, reducer, init, channel_last,
          ceil_mode=False, count_include_pad=True, average=False,
          exclusive=True):
    x = as_tensor(x)
    k = _tuple(kernel_size, n)
    s = _tuple(stride if stride is not None else kernel_size, n)
    if isinstance(padding, str):
        pad_mode = padding.upper()
        pads = None
    else:
        pad_mode = None
        p = _tuple(padding, n) if not isinstance(padding, (list, tuple)) or \
            all(isinstance(v, int) for v in padding) else padding
        # tuple, not list: pads lands in _fn's closure and must stay
        # hashable for the memoized-vjp cache (dispatch.py)
        if isinstance(p, tuple) and len(p) == n and \
                all(isinstance(v, int) for v in p):
            pads = tuple((v, v) for v in p)
        else:
            pads = tuple(tuple(v) for v in p)

    # layout propagation (core/layout.py): a tagged input is already
    # physically channels-last — pool it in place and keep the tag.
    tagged = (n == 2 and not channel_last and x._layout is not None
              and _layout.enabled())
    if x._layout is not None and not tagged:
        x = _layout.materialize(x)
    to_cl = not channel_last and not tagged

    def _fn(a):
        # channels-last internally (layout autotune; see conv.py)
        if to_cl:
            a = jnp.moveaxis(a, 1, -1)
        window = (1,) + k + (1,)
        strides_full = (1,) + s + (1,)
        eff_pads = pads
        if ceil_mode and pads is not None:
            # pad the high side so ceil-mode's extra partial window
            # exists; the pad region stays out of avg divisors below
            eff_pads = tuple(
                (lo, hi + _ceil_extra(a.shape[1 + i], k[i], s[i], lo, hi))
                for i, (lo, hi) in enumerate(pads))
        pad_full = [(0, 0)] + list(eff_pads or [(0, 0)] * n) + [(0, 0)]
        pad_cfg = pad_mode if pad_mode is not None else pad_full
        out = jax.lax.reduce_window(
            a, init(a.dtype), reducer, window, strides_full,
            pad_cfg if isinstance(pad_cfg, str) else pad_cfg)
        if average:
            if exclusive and eff_pads is not None and any(
                    p_ != (0, 0) for p_ in eff_pads):
                # padding contributes the 0-init, so counts = number of
                # REAL elements per window (paddle exclusive=True)
                ones = jnp.ones_like(a)
                counts = jax.lax.reduce_window(
                    ones, 0.0 if not jnp.issubdtype(a.dtype, jnp.integer)
                    else 0, jax.lax.add, window, strides_full, pad_cfg)
                out = out / counts
            else:
                out = out / float(np.prod(k))
        if to_cl:
            out = jnp.moveaxis(out, -1, 1)
        return out
    out = unary("pool", _fn, x)
    if tagged:
        out._layout = _layout.NHWC
    return out


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    def init(dt):
        return -jnp.inf if jnp.issubdtype(dt, jnp.floating) else \
            jnp.iinfo(dt).min
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.max, init,
                 channel_last=False, ceil_mode=ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    def init(dt):
        return -jnp.inf if jnp.issubdtype(dt, jnp.floating) else \
            jnp.iinfo(dt).min
    if not return_mask:
        return _pool(x, kernel_size, stride, padding, 2, jax.lax.max,
                     init, channel_last=(data_format == "NHWC"),
                     ceil_mode=ceil_mode)
    assert data_format in ("NCHW", "NHWC"), \
        "return_mask supports NCHW / NHWC"
    nhwc_in = data_format == "NHWC"
    k = _tuple(kernel_size, 2)
    s = _tuple(stride if stride is not None else kernel_size, 2)
    p = _tuple(padding, 2)

    def _pool_with_mask(a):
        """One pass producing (pooled max, flat H*W argmax index) — the
        MaxPoolWithIndex kernel role, feeding max_unpool2d. The mask
        indexes the logical (unpadded, NCHW-ordered) H*W plane for both
        data formats; ceil_mode pads the high side with -inf so the
        partial windows exist but never win an argmax over real data."""
        if nhwc_in:
            a = jnp.moveaxis(a, -1, 1)
        n, c, h, w = a.shape
        ph = (p[0], p[0] + (_ceil_extra(h, k[0], s[0], p[0], p[0])
                            if ceil_mode else 0))
        pw = (p[1], p[1] + (_ceil_extra(w, k[1], s[1], p[1], p[1])
                            if ceil_mode else 0))
        av = jnp.pad(a.astype(jnp.float32),
                     ((0, 0), (0, 0), ph, pw),
                     constant_values=-jnp.inf)
        iv = jnp.pad(jnp.arange(h * w, dtype=jnp.int32
                                ).reshape(1, 1, h, w),
                     ((0, 0), (0, 0), ph, pw),
                     constant_values=-1)
        oh = (h + ph[0] + ph[1] - k[0]) // s[0] + 1
        ow = (w + pw[0] + pw[1] - k[1]) // s[1] + 1
        pv, pi = [], []
        for i in range(k[0]):
            for j in range(k[1]):
                pv.append(av[:, :, i:i + oh * s[0]:s[0],
                             j:j + ow * s[1]:s[1]])
                pi.append(iv[:, :, i:i + oh * s[0]:s[0],
                             j:j + ow * s[1]:s[1]])
        stacked_v = jnp.stack(pv, axis=2)          # [N,C,K,oh,ow]
        stacked_i = jnp.stack(pi, axis=2)          # [1,1,K,oh,ow]
        out = jnp.max(stacked_v, axis=2).astype(a.dtype)
        am = jnp.argmax(stacked_v, axis=2)[:, :, None]
        bi = jnp.broadcast_to(stacked_i,
                              (n, c) + stacked_i.shape[2:])
        mask = jnp.take_along_axis(bi, am, axis=2)[:, :, 0]
        if nhwc_in:
            out = jnp.moveaxis(out, 1, -1)
            mask = jnp.moveaxis(mask, 1, -1)
        return out, mask

    from ...core import dispatch
    return dispatch.apply("max_pool2d_with_mask", _pool_with_mask,
                          (as_tensor(x),))


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Inverse of max_pool2d(return_mask=True): scatter values back to
    their argmax positions (`paddle/phi/kernels/unpool_kernel.h`).
    Accepts the same data_format as the pooling that produced the mask
    (the mask always addresses the logical H*W plane)."""
    from ...core import dispatch
    x = as_tensor(x)
    indices = as_tensor(indices)
    nhwc_in = data_format == "NHWC"
    k = _tuple(kernel_size, 2)
    s = _tuple(stride if stride is not None else kernel_size, 2)
    p = _tuple(padding, 2)
    if nhwc_in:
        n, ih, iw, c = x.shape
    else:
        n, c, ih, iw = x.shape
    if output_size is None:
        if p[0] or p[1]:
            # the mask addresses the ORIGINAL input plane; the padded
            # default formula yields a smaller buffer and jax scatter
            # would silently drop out-of-range maxima
            raise ValueError(
                "max_unpool2d with padding>0 needs explicit output_size "
                "(the pooled-from input's spatial shape)")
        oh = (ih - 1) * s[0] - 2 * p[0] + k[0]
        ow = (iw - 1) * s[1] - 2 * p[1] + k[1]
    else:
        spatial = output_size[1:3] if nhwc_in and len(output_size) == 4 \
            else output_size[-2:]
        oh, ow = [int(v) for v in spatial]

    def _fn(a, idx):
        if nhwc_in:
            a = jnp.moveaxis(a, -1, 1)
            idx = jnp.moveaxis(idx, -1, 1)
        flat_v = a.reshape(n * c, ih * iw)
        flat_i = idx.reshape(n * c, ih * iw).astype(jnp.int32)
        out = jnp.zeros((n * c, oh * ow), a.dtype)
        rows = jnp.arange(n * c)[:, None]
        out = out.at[rows, flat_i].set(flat_v)
        out = out.reshape(n, c, oh, ow)
        if nhwc_in:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return dispatch.apply("max_unpool2d", _fn, (x, indices))


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    def init(dt):
        return -jnp.inf if jnp.issubdtype(dt, jnp.floating) else \
            jnp.iinfo(dt).min
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.max, init,
                 channel_last=(data_format == "NDHWC"), ceil_mode=ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.add,
                 lambda dt: jnp.zeros((), dt).item() if False else 0.0,
                 channel_last=False, average=True, exclusive=exclusive,
                 ceil_mode=ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.add,
                 lambda dt: 0.0, channel_last=(data_format == "NHWC"),
                 average=True, exclusive=exclusive, ceil_mode=ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.add,
                 lambda dt: 0.0, channel_last=(data_format == "NDHWC"),
                 average=True, exclusive=exclusive, ceil_mode=ceil_mode)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", False)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max", False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max", False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max", False)


def _adaptive(x, output_size, n, mode, channel_last):
    x = as_tensor(x)
    out_sz = _tuple(output_size, n)

    # layout propagation: pool the tagged (physically NHWC) array in
    # place; the (N,1,1,C)-physical output stays tagged and the
    # flatten/fc graph edge materializes it (a trivially small copy).
    tagged = (n == 2 and not channel_last and x._layout is not None
              and _layout.enabled())
    if x._layout is not None and not tagged:
        x = _layout.materialize(x)
    channel_last = channel_last or tagged

    def _fn(a):
        spatial = a.shape[2:2 + n] if not channel_last else a.shape[1:1 + n]
        # exact adaptive pooling when divisible; else mean over variable bins
        if all(s % o == 0 for s, o in zip(spatial, out_sz)):
            k = tuple(s // o for s, o in zip(spatial, out_sz))
            if channel_last:
                window = (1,) + k + (1,)
            else:
                window = (1, 1) + k
            red = jax.lax.max if mode == "max" else jax.lax.add
            init = (-jnp.inf if mode == "max" else 0.0)
            out = jax.lax.reduce_window(a, init, red, window, window,
                                        "VALID")
            if mode == "avg":
                out = out / float(np.prod(k))
            return out
        # general path: resize-style bins
        slices = []
        for dim_i, (s, o) in enumerate(zip(spatial, out_sz)):
            starts = [int(np.floor(i * s / o)) for i in range(o)]
            ends = [int(np.ceil((i + 1) * s / o)) for i in range(o)]
            slices.append((starts, ends))

        def pool_one(index):
            idx = [slice(None)] * a.ndim
            base = 1 if channel_last else 2
            for d, ii in enumerate(index):
                st, en = slices[d][0][ii], slices[d][1][ii]
                idx[base + d] = slice(st, en)
            patch = a[tuple(idx)]
            axes = tuple(range(base, base + n))
            return (jnp.max(patch, axis=axes) if mode == "max"
                    else jnp.mean(patch, axis=axes))
        import itertools
        outs = [pool_one(ix) for ix in itertools.product(
            *[range(o) for o in out_sz])]
        stacked = jnp.stack(outs, axis=-1)
        if channel_last:
            nb, c = a.shape[0], a.shape[-1]
            return stacked.reshape((nb, c) + tuple(out_sz)).transpose(
                (0,) + tuple(range(2, 2 + n)) + (1,))
        nb, c = a.shape[0], a.shape[1]
        return stacked.reshape((nb, c) + tuple(out_sz))
    out = unary("adaptive_pool", _fn, x)
    if tagged:
        out._layout = _layout.NHWC
    return out
