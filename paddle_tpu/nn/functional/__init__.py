"""paddle_tpu.nn.functional — parity with `python/paddle/nn/functional/`."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose,
    conv3d_transpose,
)
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import (  # noqa: F401
    flash_attention, scaled_dot_product_attention, sparse_attention,
)

# re-export a few tensor ops that paddle exposes under nn.functional
from ...ops.manipulation import one_hot, pad  # noqa: F401
from ...ops.math import sigmoid  # noqa: F401
from .vision_ext import (  # noqa: F401
    affine_grid, grid_sample, channel_shuffle, pixel_unshuffle,
    temporal_shift, log_loss, rrelu, gather_tree, margin_cross_entropy,
    spectral_norm, bilinear)
