"""Transformer layers.

Parity: `python/paddle/nn/layer/transformer.py` (MultiHeadAttention,
TransformerEncoder/Decoder, Transformer) — the reference's fused variants
(`paddle/fluid/operators/fused/fused_attention_op.cu`,
`fused_multi_transformer_*`) are subsumed by XLA fusion + the Pallas flash
attention kernel behind `F.scaled_dot_product_attention`.
"""
from __future__ import annotations

import collections

from ..layer_base import Layer
from ..container import LayerList
from .common import Linear, Dropout
from .norm import LayerNorm
from .. import functional as F
from ...ops import manipulation as manip
from ...ops._helpers import as_tensor


def _convert_attention_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    import jax.numpy as jnp
    from ... import ops
    attn_mask = as_tensor(attn_mask)
    if attn_mask.dtype == jnp.bool_:
        # key-padding-shaped bool masks ([*, 1, 1, S]) stay bool:
        # F.scaled_dot_product_attention folds them into the splash
        # flash kernel as segment ids (when attention dropout is 0 and
        # the shape tiles) instead of an additive bias
        from ..functional.attention import _is_key_padding_mask
        if _is_key_padding_mask(attn_mask._data):
            return attn_mask
        zero = ops.zeros_like(ops.cast(attn_mask, "float32"))
        return ops.where(attn_mask, zero, ops.full_like(zero, -1e9))
    return attn_mask.astype("float32")


class MultiHeadAttention(Layer):
    """paddle.nn.MultiHeadAttention: inputs [batch, seq, embed_dim]."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _shape(self, x):
        b, s = x.shape[0], x.shape[1]
        return manip.reshape(x, [b, s, self.num_heads, self.head_dim])

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._shape(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value))
        new_cache = None
        if isinstance(cache, self.Cache):
            k = manip.concat([cache.k, k], axis=1)
            v = manip.concat([cache.v, v], axis=1)
            new_cache = self.Cache(k, v)
        mask = _convert_attention_mask(attn_mask, None)
        if mask is not None and mask.ndim == 3:
            mask = manip.unsqueeze(mask, 1)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask,
            dropout_p=self.dropout if self.training else 0.0,
            training=self.training)
        b, s = out.shape[0], out.shape[1]
        out = manip.reshape(out, [b, s, self.embed_dim])
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(None)
        if cache is not None and new_cache is not None:
            outs.append(new_cache)
        return out if len(outs) == 1 else tuple(outs)

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value if value is not None else key))
            return self.StaticCache(k, v)
        from ...ops.creation import zeros
        b = key.shape[0]
        k = zeros([b, 0, self.num_heads, self.head_dim])
        v = zeros([b, 0, self.num_heads, self.head_dim])
        return self.Cache(k, v)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model, layer_norm_eps)
        self.norm2 = LayerNorm(d_model, layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout)
        self.activation = activation

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout_act(
            getattr(F, self.activation)(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList(
            [encoder_layer if i == 0 else copy.deepcopy(encoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, c = mod(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model, layer_norm_eps)
        self.norm2 = LayerNorm(d_model, layer_norm_eps)
        self.norm3 = LayerNorm(d_model, layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout)
        self.activation = activation

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, inc_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                            cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory,
                                                memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout_act(
            getattr(F, self.activation)(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (inc_cache, static_cache))


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList(
            [decoder_layer if i == 0 else copy.deepcopy(decoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, c = mod(output, memory, tgt_mask, memory_mask,
                                cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        from ...ops.creation import ones, tril
        from ... import ops
        m = ops.tril(ops.ones([length, length], "float32"))
        return ops.where(ops.equal(m, 0.0),
                         ops.full_like(m, -1e9), ops.zeros_like(m))
