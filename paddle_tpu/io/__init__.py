"""paddle_tpu.io — Dataset / DataLoader / samplers.

Parity: `python/paddle/io/` over the reference's reader stack
(`python/paddle/fluid/reader.py:275 DataLoader`,
`fluid/dataloader/` workers, C++ shared-mem plumbing
`imperative/data_loader.cc`, `memory/allocation/mmap_allocator`).

TPU-native: the loader is a host-side prefetching iterator (threads, not
forked workers — jax arrays transfer via device_put on the producer side);
the out-of-core `InMemoryDataset`/DataFeed capability for PS training lives
in paddle_tpu/ps/ (native engine).
"""
from __future__ import annotations

import itertools
import math
import queue
import threading

import numpy as np

from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = [t if isinstance(t, Tensor) else Tensor(t)
                        for t in tensors]
        n = self.tensors[0].shape[0]
        assert all(t.shape[0] == n for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t.numpy()[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, tuple) else (item,))
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        # paddle >= 2.5 allows fractions
        if all(0 < l < 1 for l in lengths):
            lengths = [int(math.floor(total * l)) for l in lengths]
            lengths[-1] = total - sum(lengths[:-1])
        else:
            raise ValueError("lengths must sum to dataset size")
    perm = np.random.permutation(total)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Parity: `python/paddle/fluid/dataloader/batch_sampler.py`
    DistributedBatchSampler — shards the dataset across dp ranks."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle
        from ..parallel import env as dist_env
        self.nranks = num_replicas if num_replicas is not None else \
            dist_env.get_world_size()
        self.local_rank = rank if rank is not None else dist_env.get_rank()
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n).tolist()
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
            self.epoch += 1
        indices += indices[:(self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (→ Tensors)."""
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([s.numpy() for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn([s[i] for s in batch])
                for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch])
                for k in sample}
    return batch


# ---------------------------------------------------------------------
# multiprocess workers (reference reader.py:275 + mmap_allocator shared
# memory). Workers are forked processes pulling index batches from a
# queue; collated numpy arrays return via SharedMemory segments (large
# arrays bypass pickle — the mmap_allocator role) with an order-restoring
# reorder buffer in the parent.

_SHM_MIN_BYTES = 1 << 16


def _strip_tensors(obj):
    """Tensor -> numpy for IPC; structure (incl. tuple-ness) preserved."""
    if isinstance(obj, Tensor):
        return np.asarray(obj.numpy())
    if isinstance(obj, tuple):
        return tuple(_strip_tensors(o) for o in obj)
    if isinstance(obj, list):
        return [_strip_tensors(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _strip_tensors(v) for k, v in obj.items()}
    return obj


def _to_shm(obj, shms):
    """Replace big ndarrays with ('__shm__', name, shape, dtype)."""
    from multiprocessing import shared_memory
    if isinstance(obj, np.ndarray) and obj.nbytes >= _SHM_MIN_BYTES:
        shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        # ownership transfers to the parent (which unlinks after copy-out)
        # — unregister from THIS process's resource tracker, or a worker
        # exiting before the parent attaches would unlink the segment
        # out from under it
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass
        np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)[...] = obj
        shms.append(shm)
        return ("__shm__", shm.name, obj.shape, str(obj.dtype))
    if isinstance(obj, tuple):
        # wrap user tuples so they can't collide with the shm marker
        return ("__tuple__", [_to_shm(o, shms) for o in obj])
    if isinstance(obj, list):
        return [_to_shm(o, shms) for o in obj]
    if isinstance(obj, dict):
        return {k: _to_shm(v, shms) for k, v in obj.items()}
    return obj


def _from_shm(obj):
    from multiprocessing import shared_memory
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__shm__":
        _, name, shape, dtype = obj
        shm = shared_memory.SharedMemory(name=name)
        arr = np.array(np.ndarray(shape, dtype, buffer=shm.buf))
        shm.close()
        shm.unlink()
        return Tensor(arr)
    if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == "__tuple__":
        return tuple(_from_shm(o) for o in obj[1])
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, list):
        return [_from_shm(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _from_shm(v) for k, v in obj.items()}
    return obj


def _release_shm(obj):
    """Unlink shm descriptors in an undelivered payload."""
    from multiprocessing import shared_memory
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__shm__":
        try:
            shm = shared_memory.SharedMemory(name=obj[1])
            shm.close()
            shm.unlink()
        except (FileNotFoundError, OSError):
            pass
        return
    if isinstance(obj, (list, tuple)):
        for o in obj:
            _release_shm(o)
    elif isinstance(obj, dict):
        for o in obj.values():
            _release_shm(o)


def _mp_worker_loop(dataset, index_q, data_q, collate_fn,
                    use_shared_memory, worker_init_fn, worker_id):
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        item = index_q.get()
        if item is None:
            return
        bid, idxs = item
        try:
            batch = collate_fn([dataset[i] for i in idxs])
            payload = _strip_tensors(batch)
            if use_shared_memory:
                shms = []
                payload = _to_shm(payload, shms)
                data_q.put((bid, payload, None))
                for shm in shms:
                    shm.close()  # parent owns unlink
            else:
                data_q.put((bid, payload, None))
        except Exception as e:  # propagate into the parent iterator
            data_q.put((bid, None, f"{type(e).__name__}: {e}"))


class DataLoader:
    """Parity: `python/paddle/fluid/reader.py:275`. num_workers=0 runs
    in-process (with thread prefetch when use_buffer_reader); num_workers
    > 0 forks worker processes that collate index batches and ship the
    arrays back through SharedMemory (the reference's multiprocess
    reader + mmap_allocator path). IterableDataset always runs
    in-process (worker sharding semantics are the map-style path's)."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.prefetch = max(2, prefetch_factor * max(num_workers, 1))
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        elif self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)
            self.batch_size = batch_size

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _gen_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def __iter__(self):
        if self.num_workers <= 0:
            yield from self._gen_batches()
            return
        if not self._iterable_mode:
            # fall back ONLY on setup failure — once batches have been
            # yielded, restarting on the thread path would silently
            # duplicate the epoch's data
            try:
                mp_iter = self._start_multiprocess()
            except (ImportError, OSError, ValueError) as e:
                import warnings
                warnings.warn(f"multiprocess DataLoader unavailable "
                              f"({e!r}); using thread prefetch")
            else:
                yield from mp_iter
                return
        q = queue.Queue(maxsize=self.prefetch)
        sentinel = object()

        def producer():
            try:
                for b in self._gen_batches():
                    q.put(b)
            finally:
                q.put(sentinel)
        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item

    def _start_multiprocess(self):
        """Setup (may raise -> caller falls back), returning the draining
        generator."""
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        index_q = ctx.Queue()
        data_q = ctx.Queue(maxsize=self.prefetch)
        workers = [
            ctx.Process(
                target=_mp_worker_loop,
                args=(self.dataset, index_q, data_q, self.collate_fn,
                      self.use_shared_memory, self.worker_init_fn, wid),
                daemon=True)
            for wid in range(self.num_workers)]
        for w in workers:
            w.start()
        n_batches = 0
        for bid, idxs in enumerate(self.batch_sampler):
            index_q.put((bid, list(idxs)))
            n_batches += 1
        for _ in workers:
            index_q.put(None)
        return self._drain_multiprocess(workers, data_q, n_batches)

    def _drain_multiprocess(self, workers, data_q, n_batches):
        reorder = {}
        try:
            next_bid = 0
            while next_bid < n_batches:
                while next_bid not in reorder:
                    bid, payload, err = data_q.get(
                        timeout=self.timeout or 120)
                    if err is not None:
                        raise RuntimeError(
                            f"DataLoader worker failed on batch {bid}: "
                            f"{err}")
                    reorder[bid] = payload
                yield _from_shm(reorder.pop(next_bid))
                next_bid += 1
        finally:
            for w in workers:
                if w.is_alive():
                    w.terminate()
            for w in workers:
                w.join(timeout=5)
            # unlink SharedMemory segments still queued or reordered —
            # on early break / worker error they would otherwise leak
            # in /dev/shm until interpreter exit
            import queue as _q
            while True:
                try:
                    _, payload, _err = data_q.get_nowait()
                except (_q.Empty, OSError):
                    break
                _release_shm(payload)
            for payload in reorder.values():
                _release_shm(payload)


def get_worker_info():
    return None


class DeviceCacheLoader:
    """Pin a (small) dataset's batches in device HBM after the first
    epoch — repeated epochs then feed with ZERO host->device transfers.

    The TPU-first input-pipeline pattern (tf.data `.cache()` on-device
    analogue): host->device bandwidth through a relay/DCN link is often
    the fit-loop bottleneck for small models; datasets that fit in HBM
    (MNIST: ~13 MB) should live there. Wraps any iterable loader:

        loader = DeviceCacheLoader(DataLoader(ds, batch_size=64))
        model.fit(loader, ...)

    Caching is ALL-OR-NOTHING: if the first epoch exceeds `max_bytes`
    the cache is discarded (with a warning) and every epoch streams
    from the base loader — a partial cache over a shuffling base would
    silently bias sampling (cached prefix replayed + a differently-
    shuffled remainder). Cached epochs replay the first epoch's batches
    (re-shuffled at batch granularity when `reshuffle=True`); a
    per-sample re-shuffle would need fresh host batches and defeat the
    cache.
    """

    def __init__(self, loader, max_bytes=512 * 1024 * 1024,
                 reshuffle=True, seed=0):
        self._loader = loader
        self._max_bytes = max_bytes
        self._cache = None
        self._overflowed = False
        self._reshuffle = reshuffle
        self._epoch = 0
        self._seed = seed

    def __len__(self):
        return len(self._loader)

    def __iter__(self):
        import jax.numpy as jnp
        if self._cache is not None:
            order = list(range(len(self._cache)))
            if self._reshuffle:
                import random as _random
                self._epoch += 1
                _random.Random(self._seed + self._epoch).shuffle(order)
            for i in order:
                yield self._cache[i]
            return
        if self._overflowed:
            yield from self._loader
            return
        cache = []
        used = 0
        for batch in self._loader:
            if cache is not None:
                items = tuple(
                    t._data if hasattr(t, "_data") else jnp.asarray(t)
                    for t in (batch if isinstance(batch, (list, tuple))
                              else [batch]))
                nbytes = sum(getattr(a, "nbytes", 0) for a in items)
                if used + nbytes <= self._max_bytes:
                    cache.append(items)
                    used += nbytes
                    yield items
                    continue
                import warnings
                warnings.warn(
                    f"DeviceCacheLoader: dataset exceeds max_bytes="
                    f"{self._max_bytes}; caching disabled (all epochs "
                    "stream from host — a partial cache would bias "
                    "sampling)")
                cache = None
                self._overflowed = True
            yield batch
        if cache is not None:
            self._cache = cache
