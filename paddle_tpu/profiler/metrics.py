"""Framework-wide metrics registry.

The observability counterpart of the reference's profiler statistics
stack (`python/paddle/profiler/profiler_statistic.py` aggregates spans
after the fact; here the framework keeps live counters the way a
serving stack would): a process-global, thread-safe registry of
Counter / Gauge / Histogram metrics with Prometheus-text and JSON
export.

Hot paths (core/dispatch.py, jit/trainer.py, parallel/collective.py,
parallel/pipeline_schedule.py, hapi) are instrumented against the
module-level ``_enabled`` flag so the eager path pays ONE attribute
read + branch when observability is off:

    from ..profiler import metrics as _metrics
    ...
    if _metrics._enabled:
        _metrics.DISPATCH_OPS.labels(op_name).inc()

Enable with ``metrics.enable()`` (or ``PADDLE_TPU_METRICS=1`` in the
environment), read with ``REGISTRY.snapshot()`` / ``to_prometheus()`` /
``to_json()``, and combine with host spans via
``paddle_tpu.profiler.summary()``.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time


# --------------------------------------------------------------- switch

_enabled = bool(os.environ.get("PADDLE_TPU_METRICS", ""))


def enable():
    """Turn on hot-path instrumentation process-wide."""
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def exponential_buckets(start: float, factor: float, count: int):
    """Fixed exponential histogram bucket upper bounds."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


# 1us .. ~4.2s in x4 steps: covers eager dispatch (~50us) through jit
# compiles (seconds) with 12 buckets
DEFAULT_TIME_BUCKETS = exponential_buckets(1e-6, 4.0, 12)


# -------------------------------------------------------------- metrics


class _Metric:
    """Base: a named metric with (optionally) labeled children."""

    type = "untyped"

    def __init__(self, name, help="", labelnames=()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children = {}
        if not self.labelnames:
            # unlabeled metric: a single default child shares the lock
            self._children[()] = self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values, **kv):
        """Child for one label-value combination (created on demand)."""
        if kv:
            if values:
                raise ValueError("pass label values positionally or by "
                                 "name, not both")
            try:
                values = tuple(kv[n] for n in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"unknown label {e} for metric {self.name!r} "
                    f"(labels: {self.labelnames})") from None
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes {len(self.labelnames)} "
                f"label value(s), got {len(values)}")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values,
                                                  self._make_child())
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"metric {self.name!r} is labeled {self.labelnames}; "
                "use .labels(...)")
        return self._children[()]

    def reset(self):
        with self._lock:
            if self.labelnames:
                self._children.clear()
            else:
                self._children = {(): self._make_child()}

    def samples(self):
        """[(labelvalues, child)] snapshot-stable list."""
        with self._lock:
            return list(self._children.items())


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n=1):
        if n < 0:
            raise ValueError("counters can only increase; use a Gauge")
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Counter(_Metric):
    type = "counter"

    def _make_child(self):
        return _CounterChild()

    def inc(self, n=1):
        self._default().inc(n)

    @property
    def value(self):
        return self._default().value


class _GaugeChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = float(v)

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        self.inc(-n)

    @property
    def value(self):
        return self._value


class Gauge(_Metric):
    type = "gauge"

    def _make_child(self):
        return _GaugeChild()

    def set(self, v):
        self._default().set(v)

    def inc(self, n=1):
        self._default().inc(n)

    def dec(self, n=1):
        self._default().dec(n)

    @property
    def value(self):
        return self._default().value


class _HistogramChild:
    __slots__ = ("buckets", "bucket_counts", "_sum", "_count", "_lock")

    def __init__(self, buckets):
        self.buckets = buckets               # upper bounds, ascending
        self.bucket_counts = [0] * (len(buckets) + 1)  # +1 => +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v):
        v = float(v)
        # linear scan: bucket lists are small (<=16) and fixed
        i = 0
        for i, ub in enumerate(self.buckets):
            if v <= ub:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self.bucket_counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def sum(self):
        return self._sum

    @property
    def count(self):
        return self._count

    def cumulative(self):
        """[(upper_bound, cumulative_count)] including +Inf."""
        out, acc = [], 0
        with self._lock:
            counts = list(self.bucket_counts)
        for ub, c in zip(list(self.buckets) + [math.inf], counts):
            acc += c
            out.append((ub, acc))
        return out


class Histogram(_Metric):
    type = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=None):
        self.buckets = tuple(buckets) if buckets is not None \
            else DEFAULT_TIME_BUCKETS
        if list(self.buckets) != sorted(self.buckets):
            raise ValueError("histogram buckets must be ascending")
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v):
        self._default().observe(v)

    @property
    def sum(self):
        return self._default().sum

    @property
    def count(self):
        return self._default().count


# ------------------------------------------------------------- registry


class MetricsRegistry:
    """Process-global name -> metric store. `counter`/`gauge`/`histogram`
    get-or-create (re-registration with a different type or labels is an
    error); `snapshot`/`to_prometheus`/`to_json` export; `reset` zeroes
    every value (registrations survive) for tests."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or \
                        m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.type} with labels {m.labelnames}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name):
        return self._metrics.get(name)

    def reset(self):
        """Zero every metric (keep registrations) — for tests."""
        with self._lock:
            for m in self._metrics.values():
                m.reset()

    def clear(self):
        """Drop all registrations (fresh registry)."""
        with self._lock:
            self._metrics.clear()

    # ------------------------------------------------------------ export

    def snapshot(self):
        """{name: {type, help, labels, values}} plain-python snapshot.
        Histogram values are {buckets: [[ub, cumcount]...], sum, count}.
        Label keys are rendered `a=x,b=y` ("" for unlabeled)."""
        out = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            values = {}
            for lv, child in m.samples():
                key = ",".join(f"{n}={v}"
                               for n, v in zip(m.labelnames, lv))
                if m.type == "histogram":
                    values[key] = {
                        "buckets": [[("+Inf" if ub == math.inf else ub),
                                     c] for ub, c in child.cumulative()],
                        "sum": child.sum,
                        "count": child.count,
                    }
                else:
                    values[key] = child.value
            out[m.name] = {"type": m.type, "help": m.help,
                           "labels": list(m.labelnames),
                           "values": values}
        return out

    def to_json(self, indent=None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4)."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: m.name)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.type}")
            for lv, child in sorted(m.samples()):
                lbl = _label_str(m.labelnames, lv)
                if m.type == "histogram":
                    for ub, c in child.cumulative():
                        le = "+Inf" if ub == math.inf else _fmt(ub)
                        blbl = _label_str(m.labelnames + ("le",),
                                          lv + (le,))
                        lines.append(f"{m.name}_bucket{blbl} {c}")
                    lines.append(
                        f"{m.name}_sum{lbl} {_fmt(child.sum)}")
                    lines.append(f"{m.name}_count{lbl} {child.count}")
                else:
                    lines.append(f"{m.name}{lbl} {_fmt(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def render_table(self) -> str:
        """Human-readable table of every non-zero sample (the metrics
        section of `profiler.summary()`)."""
        rows = []
        for name, m in sorted(self.snapshot().items()):
            for key, v in sorted(m["values"].items()):
                if m["type"] == "histogram":
                    if not v["count"]:
                        continue
                    mean = v["sum"] / v["count"]
                    val = (f"count={v['count']} sum={v['sum']:.6g} "
                           f"mean={mean:.6g}")
                else:
                    if not v:
                        continue
                    val = f"{v:.6g}"
                label = f"{name}{{{key}}}" if key else name
                rows.append((label, m["type"], val))
        if not rows:
            return "Metrics: (none recorded)"
        w = max(len(r[0]) for r in rows)
        sep = "-" * (w + 46)
        lines = [sep, "Metrics Summary", sep,
                 f"{'Name':{w}s}  {'Type':9s}  Value"]
        lines += [f"{n:{w}s}  {t:9s}  {v}" for n, t, v in rows]
        lines.append(sep)
        return "\n".join(lines)

    def chrome_counter_events(self):
        """Chrome-trace counter events (`ph: "C"`) for every scalar
        sample, timestamped now on the host-span clock — merged into
        `export_chrome_tracing` output next to RecordEvent spans."""
        ts = time.perf_counter() * 1e6
        pid = os.getpid()
        events = []
        for name, m in self.snapshot().items():
            if m["type"] == "histogram":
                for key, v in m["values"].items():
                    series = f"{name}{{{key}}}" if key else name
                    events.append({
                        "name": series, "ph": "C", "ts": ts, "pid": pid,
                        "args": {"count": v["count"], "sum": v["sum"]}})
                continue
            for key, v in m["values"].items():
                series = f"{name}{{{key}}}" if key else name
                events.append({"name": series, "ph": "C", "ts": ts,
                               "pid": pid, "args": {"value": v}})
        return events


def _label_str(names, values):
    if not names:
        return ""
    pairs = ",".join(f'{n}="{_escape(v)}"'
                     for n, v in zip(names, values))
    return "{" + pairs + "}"


def _escape(v):
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _fmt(v):
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


REGISTRY = MetricsRegistry()


# ----------------------------------------------- framework metric handles
#
# Pre-registered handles for the instrumented hot paths; exported metric
# names are part of the observability contract (docs/OBSERVABILITY.md,
# tools/metrics_dump.py greps them).

DISPATCH_OPS = REGISTRY.counter(
    "paddle_tpu_dispatch_ops_total",
    "Eager op dispatches through core.dispatch.apply", ("op",))
VJP_CACHE = REGISTRY.counter(
    "paddle_tpu_vjp_jit_cache_total",
    "VJP-jit cache events (hit/miss/fallback/eviction)", ("event",))
VJP_BACKWARD_SECONDS = REGISTRY.histogram(
    "paddle_tpu_vjp_backward_seconds",
    "Per-node backward time: trace (cache miss, includes jit trace) vs "
    "replay (cache hit) vs fallback (uncacheable closure)", ("mode",))
NAN_INF_EVENTS = REGISTRY.counter(
    "paddle_tpu_nan_inf_events_total",
    "NaN/Inf detections under FLAGS_check_nan_inf", ("op",))
JIT_COMPILES = REGISTRY.counter(
    "paddle_tpu_jit_compiles_total",
    "XLA compilations per jitted entry point", ("fn",))
JIT_COMPILE_SECONDS = REGISTRY.counter(
    "paddle_tpu_jit_compile_seconds_total",
    "Cumulative trace+compile wall seconds per jitted entry point",
    ("fn",))
COLLECTIVE_CALLS = REGISTRY.counter(
    "paddle_tpu_collective_calls_total",
    "Eager collective API calls", ("collective",))
COLLECTIVE_BYTES = REGISTRY.counter(
    "paddle_tpu_collective_bytes_total",
    "Payload bytes through collectives (eager: measured; compiled "
    "hybrid steps: analytic estimate)", ("collective",))
COLLECTIVE_SECONDS = REGISTRY.histogram(
    "paddle_tpu_collective_seconds",
    "Eager collective wall time", ("collective",))
GRAD_BUCKETS = REGISTRY.gauge(
    "paddle_tpu_grad_buckets",
    "Gradient all-reduce buckets per step for the bucketed reduction "
    "paths (eager fused_allreduce_gradients / compiled hybrid DP step)",
    ("path",))
PIPELINE_BUBBLE_TICKS = REGISTRY.gauge(
    "paddle_tpu_pipeline_stage_bubble_ticks",
    "Idle schedule ticks per pipeline stage for the compiled schedule",
    ("stage",))
PIPELINE_BUBBLE_RATIO = REGISTRY.gauge(
    "paddle_tpu_pipeline_bubble_ratio",
    "Schedule-level bubble fraction (idle slots / total slots)")
PIPELINE_STEP_SECONDS = REGISTRY.histogram(
    "paddle_tpu_pipeline_step_seconds",
    "Wall time of CompiledPipeline.loss_and_grads")
STEPS_PER_SEC = REGISTRY.gauge(
    "paddle_tpu_train_steps_per_sec",
    "Rolling training steps/sec (hapi fit loop)")
SAMPLES_PER_SEC = REGISTRY.gauge(
    "paddle_tpu_train_samples_per_sec",
    "Rolling training samples/sec (hapi fit loop)")
TOKENS_PER_SEC = REGISTRY.gauge(
    "paddle_tpu_train_tokens_per_sec",
    "Training tokens/sec (set by bench.py / LM training loops)")
HAPI_BATCHES = REGISTRY.counter(
    "paddle_tpu_hapi_batches_total",
    "Batches seen by the hapi callback loop", ("mode",))
HAPI_EPOCHS = REGISTRY.counter(
    "paddle_tpu_hapi_epochs_total",
    "Completed hapi fit epochs")
HOST_EVENTS_DROPPED = REGISTRY.counter(
    "paddle_tpu_profiler_host_events_dropped_total",
    "RecordEvent spans dropped by the bounded host ring buffer")

# ---- Pallas kernel autotuner (ISSUE 11): ops.pallas.autotune --------
KERNEL_AUTOTUNE_CACHE_HITS = REGISTRY.counter(
    "paddle_tpu_kernel_autotune_cache_hits_total",
    "Tuned-kernel config lookups served from the persistent cache "
    "(zero search cost)", ("kernel",))
KERNEL_AUTOTUNE_CACHE_MISSES = REGISTRY.counter(
    "paddle_tpu_kernel_autotune_cache_misses_total",
    "Tuned-kernel config lookups that fell back to the hand-picked "
    "default (no cached winner for the shape bucket)", ("kernel",))
KERNEL_AUTOTUNE_SEARCH_SECONDS = REGISTRY.counter(
    "paddle_tpu_kernel_autotune_search_seconds_total",
    "Wall seconds spent measuring kernel-variant candidates",
    ("kernel",))
KERNEL_AUTOTUNE_REJECTED_PARITY = REGISTRY.counter(
    "paddle_tpu_kernel_autotune_candidates_rejected_parity_total",
    "Kernel-variant candidates refused admission by the XLA-oracle "
    "parity gate (or by failing to run at all)", ("kernel",))

# ---- trace-discipline guards (ISSUE 12): analysis.guards ------------
COMPILE_WATCHDOG_BUDGET_EXCEEDED = REGISTRY.counter(
    "paddle_tpu_compile_watchdog_budget_exceeded_total",
    "Jit instances that compiled past their per-instance budget under "
    "analysis.guards.sanitize (a spec/signature mismatch forcing a "
    "silent recompile of a one-compile entry)", ("fn",))
TRANSFER_GUARD_TRIPS = REGISTRY.counter(
    "paddle_tpu_compile_watchdog_transfer_guard_trips_total",
    "jax transfer-guard errors (implicit device transfers) observed "
    "crossing an analysis.guards.sanitize boundary")

# ---- MoE routing (ISSUE 10): shared by the hybrid trainer
# ("train" path) and the serving mixed step ("serving" path) -----------
MOE_EXPERT_TOKENS = REGISTRY.counter(
    "paddle_tpu_moe_expert_tokens_total",
    "Tokens dispatched to each expert (post-capacity)",
    ("path", "expert"))
MOE_DROPPED_TOKENS = REGISTRY.counter(
    "paddle_tpu_moe_dropped_tokens_total",
    "(token, choice) routing assignments lost to capacity overflow "
    "(the token rides the residual path instead)", ("path",))
MOE_EXPERT_UTILIZATION = REGISTRY.gauge(
    "paddle_tpu_moe_expert_utilization",
    "Normalized entropy of the cumulative per-expert token "
    "distribution (1.0 = perfectly balanced, 0.0 = one expert takes "
    "everything)", ("path",))
MOE_AUX_LOSS = REGISTRY.gauge(
    "paddle_tpu_moe_aux_loss",
    "Latest GShard load-balance loss of the routed batch (1.0 = "
    "uniform routing)", ("path",))


def moe_utilization_entropy(counts):
    """Normalized entropy of a per-expert token-count vector in
    [0, 1] — the `paddle_tpu_moe_expert_utilization` gauge value (one
    definition shared by the trainer, the serving engine, bench.py and
    the moe_smoke contract)."""
    import numpy as _np
    c = _np.asarray(counts, _np.float64)
    total = c.sum()
    if total <= 0 or c.size <= 1:
        return 0.0
    p = c / total
    p = p[p > 0]
    return float(-(p * _np.log(p)).sum() / _np.log(c.size))


def record_moe_stats(path, counts, dropped, aux, utilization=None):
    """One emission path for a routed batch's MoE stats — shared by
    the hybrid trainer ("train") and the serving engine ("serving") so
    the counter/gauge semantics cannot drift. `utilization` overrides
    the entropy source (the engine passes its CUMULATIVE distribution;
    the trainer lets the per-step counts speak)."""
    import numpy as _np
    counts = _np.asarray(counts, _np.float64)
    for e, c in enumerate(counts):
        if c:
            MOE_EXPERT_TOKENS.labels(path, str(e)).inc(float(c))
    dropped = float(dropped)
    if dropped:
        MOE_DROPPED_TOKENS.labels(path).inc(dropped)
    MOE_AUX_LOSS.labels(path).set(float(aux))
    MOE_EXPERT_UTILIZATION.labels(path).set(
        moe_utilization_entropy(counts) if utilization is None
        else float(utilization))
