"""Throughput benchmark timer.

Parity: `python/paddle/profiler/timer.py:1` — the `benchmark()`
singleton hapi uses to report reader cost / batch cost / ips during
`Model.fit`. Hooked from `hapi/model.py` per step; `step_info()`
renders the rolling averages.
"""
from __future__ import annotations

import time

from . import metrics as _metrics


class _Stat:
    def __init__(self):
        self.total = 0.0
        self.count = 0
        self.window = []

    def add(self, v, win=20):
        self.total += v
        self.count += 1
        self.window.append(v)
        if len(self.window) > win:
            self.window.pop(0)

    @property
    def avg(self):
        return self.total / max(self.count, 1)

    @property
    def window_avg(self):
        return sum(self.window) / max(len(self.window), 1)


class Benchmark:
    def __init__(self):
        self.reset()

    def reset(self):
        self._reader = _Stat()
        self._batch = _Stat()
        self._ips = _Stat()
        self._last = None
        self._reader_start = None
        self.current_event = None

    # ---- hooks (reference timer.py Event protocol) ----
    def begin(self):
        self.reset()
        self._last = time.perf_counter()
        self._reader_start = self._last

    def before_reader(self):
        self._reader_start = time.perf_counter()

    def after_reader(self):
        if self._reader_start is not None:
            self._reader.add(time.perf_counter() - self._reader_start)

    def after_step(self, num_samples=None, num_steps=1):
        """num_steps > 1 when one dispatch covered a grouped flush of
        several train steps (hapi's run_many path)."""
        now = time.perf_counter()
        if self._last is not None:
            dt = now - self._last
            self._batch.add(dt)
            if num_samples and dt > 0:
                self._ips.add(num_samples / dt)
            if _metrics._enabled:
                # every fit path funnels through this hook, so the
                # throughput gauges cover per-step AND grouped dispatch
                b = self._batch.window_avg
                if b > 0:
                    _metrics.STEPS_PER_SEC.set(max(num_steps, 1) / b)
                _metrics.SAMPLES_PER_SEC.set(self._ips.window_avg)
        self._last = now
        self._reader_start = now

    step = after_step

    def step_info(self, unit="samples"):
        r = self._reader.window_avg
        b = self._batch.window_avg
        ips = self._ips.window_avg
        return (f"reader_cost: {r:.5f} s, batch_cost: {b:.5f} s, "
                f"ips: {ips:.3f} {unit}/s")

    # summary over the full run
    def report(self, unit="samples"):
        return {
            "reader_cost_avg": self._reader.avg,
            "batch_cost_avg": self._batch.avg,
            "ips_avg": self._ips.avg,
            "steps": self._batch.count,
            "unit": f"{unit}/s",
        }


_benchmark = Benchmark()


def benchmark() -> Benchmark:
    return _benchmark
