"""Profiler statistics/reporting tables.

Parity: `python/paddle/profiler/profiler_statistic.py:1` (SortedKeys,
the Overview / Operator Summary tables printed by `Profiler.summary`)
— built from the host-event recorder plus (optionally) the device
xplane trace, whose per-op times are the only trustworthy timing on
the axon relay.
"""
from __future__ import annotations

import collections
from enum import Enum


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4     # name parity; device == TPU here
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


_SORT_FIELD = {
    SortedKeys.CPUTotal: "total", SortedKeys.GPUTotal: "total",
    SortedKeys.CPUAvg: "avg", SortedKeys.GPUAvg: "avg",
    SortedKeys.CPUMax: "max", SortedKeys.GPUMax: "max",
    SortedKeys.CPUMin: "min", SortedKeys.GPUMin: "min",
}


def _aggregate(events):
    """events: [{name, dur(us), ...}] -> {name: stats dict}."""
    by_name = {}
    for e in events:
        st = by_name.setdefault(e["name"], {
            "calls": 0, "total": 0.0, "max": 0.0, "min": float("inf")})
        d = e["dur"] / 1e3  # us -> ms
        st["calls"] += 1
        st["total"] += d
        st["max"] = max(st["max"], d)
        st["min"] = min(st["min"], d)
    for st in by_name.values():
        st["avg"] = st["total"] / max(st["calls"], 1)
    return by_name


def _table(title, headers, rows, widths):
    sep = "-" * (sum(widths) + len(widths) * 2)
    lines = [sep, title, sep,
             "  ".join(h.ljust(w) if i == 0 else h.rjust(w)
                       for i, (h, w) in enumerate(zip(headers, widths)))]
    for row in rows:
        lines.append("  ".join(
            str(c)[:widths[0]].ljust(widths[0]) if i == 0
            else str(c).rjust(w)
            for i, (c, w) in enumerate(zip(row, widths))))
    lines.append(sep)
    return "\n".join(lines)


def host_statistic_table(events, sorted_by=SortedKeys.CPUTotal,
                         time_unit="ms", top_k=0):
    """The Operator-Summary-style table over recorded host spans."""
    stats = _aggregate(events)
    field = _SORT_FIELD.get(sorted_by, "total")
    items = sorted(stats.items(), key=lambda kv: -kv[1][field])
    if top_k:
        items = items[:top_k]
    gtotal = sum(st["total"] for _, st in stats.items()) or 1.0
    rows = [(name, st["calls"], f"{st['total']:.3f}",
             f"{st['avg']:.3f}", f"{st['max']:.3f}",
             f"{st['min'] if st['min'] != float('inf') else 0:.3f}",
             f"{100 * st['total'] / gtotal:.2f}%")
            for name, st in items]
    return _table(
        f"Host Event Summary (sorted by {field}, {time_unit})",
        ["Name", "Calls", "Total", "Avg", "Max", "Min", "Ratio"],
        rows, [44, 7, 11, 9, 9, 9, 8])


def device_statistic_table(trace_dir, top_k=30, n_steps=1):
    """Device-op table from the newest xplane trace under trace_dir."""
    from .xplane import load_xplane, device_op_times
    times = device_op_times(load_xplane(trace_dir))
    total = sum(times.values()) or 1
    rows = []
    for name, ns in times.most_common(top_k):
        short = name.split(" = ")[0].lstrip("%")
        rows.append((short, f"{ns / 1e6 / n_steps:.3f}",
                     f"{100 * ns / total:.2f}%"))
    return _table(
        f"Device (TPU) Op Summary — {sum(times.values()) / 1e6 / n_steps:.2f}"
        f" ms/step over {len(times)} ops",
        ["HLO op", "ms", "Ratio"], rows, [64, 11, 8])


def statistic_report(events, trace_dir=None, sorted_by=SortedKeys.CPUTotal,
                     top_k=30, n_steps=1):
    """Full report: host table + device table when a trace exists."""
    parts = [host_statistic_table(events, sorted_by, top_k=top_k)]
    if trace_dir is not None:
        try:
            parts.append(device_statistic_table(trace_dir, top_k=top_k,
                                                n_steps=n_steps))
        except Exception as e:  # no trace captured (CPU test mesh)
            parts.append(f"(no device trace: {e})")
    return "\n\n".join(parts)
