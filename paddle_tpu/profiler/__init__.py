"""paddle_tpu.profiler.

Parity: `python/paddle/profiler/` over the reference profiler
(`paddle/fluid/platform/profiler/` — HostTracer RecordEvent spans +
CudaTracer/CUPTI → chrome trace). TPU-native: host spans recorded here +
`jax.profiler` for the device timeline (XLA/TPU trace), exported as a
chrome-trace/perfetto file.
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from enum import Enum

from . import metrics
from .metrics import REGISTRY as metrics_registry  # noqa: F401


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class _HostEventRecorder:
    """Bounded ring-buffer span recorder (host_event_recorder.h parity).

    Keeps the newest `maxlen` spans; when full, the oldest span is
    dropped and counted (`.dropped` + the
    paddle_tpu_profiler_host_events_dropped_total metric) — an
    unbounded recorder would grow without limit across a long fit."""

    def __init__(self, maxlen=None):
        if maxlen is None:
            maxlen = int(os.environ.get(
                "PADDLE_TPU_PROFILER_EVENTS_MAX", 65536))
        self.maxlen = max(1, int(maxlen))
        self.events = collections.deque(maxlen=self.maxlen)
        self.dropped = 0
        self.lock = threading.Lock()

    def add(self, name, start, end, tid):
        with self.lock:
            if len(self.events) == self.maxlen:
                self.dropped += 1
                if metrics._enabled:
                    metrics.HOST_EVENTS_DROPPED.inc()
            self.events.append(
                {"name": name, "ph": "X", "ts": start * 1e6,
                 "dur": (end - start) * 1e6, "pid": os.getpid(),
                 "tid": tid})

    def clear(self):
        with self.lock:
            self.events.clear()
            self.dropped = 0


_recorder = _HostEventRecorder()
_recording = [False]

# Pluggable observability providers (ISSUE 16): subsystems that own
# their own event stores (serving.tracing's request traces + per-engine
# step flight recorders) register callables here instead of the
# profiler importing them — profiler must stay importable without the
# serving stack. Chrome sources return lists of trace-event dicts
# merged into export_chrome_tracing's file; summary sections return a
# text block (or "" to stay silent) appended to summary().
_chrome_sources = []
_summary_sections = []


def register_chrome_source(fn):
    if fn not in _chrome_sources:
        _chrome_sources.append(fn)


def register_summary_section(fn):
    if fn not in _summary_sections:
        _summary_sections.append(fn)


def _extra_chrome_events():
    events = []
    for fn in list(_chrome_sources):
        try:
            events.extend(fn() or [])
        except Exception:
            pass
    return events


def _extra_summary_sections():
    parts = []
    for fn in list(_summary_sections):
        try:
            text = fn()
        except Exception:
            continue
        if text:
            parts.append(text)
    return parts


class RecordEvent:
    """platform/profiler/event_tracing.h:49 parity — user span."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._start = None
        self._tid = None

    def begin(self):
        self._start = time.perf_counter()
        # the OPENING thread's real id: spans begun on worker threads
        # must land on their own trace row, and a span handed across
        # threads belongs to the thread that started it
        self._tid = threading.get_ident()

    def end(self):
        if self._start is not None and _recording[0]:
            _recorder.add(self.name, self._start, time.perf_counter(),
                          self._tid if self._tid is not None
                          else threading.get_ident())
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *a):
        self.end()


def make_scheduler(closed=0, ready=1, record=4, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        total = closed + ready + record
        pos = s % total if repeat == 0 or s < repeat * total else None
        if pos is None:
            return ProfilerState.CLOSED
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        path = os.path.join(
            dir_name, f"{worker_name or 'worker'}_{int(time.time())}.json")
        # host spans + one counter-event sample per metric series, so
        # the trace viewer shows dispatch/cache/collective counters on
        # the same timeline as the RecordEvent rows
        events = list(_recorder.events)
        if metrics._enabled:
            events += metrics.REGISTRY.chrome_counter_events()
        events += _extra_chrome_events()
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
    return handler


class Profiler:
    """python/paddle/profiler/profiler.py parity + jax device trace."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self.scheduler = scheduler or (lambda step: ProfilerState.RECORD)
        self.on_trace_ready = on_trace_ready
        self.step_num = 0
        self.timer_only = timer_only
        self._jax_tracing = False
        self._trace_dir = None

    def start(self):
        _recording[0] = True
        if not self.timer_only:
            try:
                import jax
                self._trace_dir = os.environ.get(
                    "PADDLE_PROFILER_DIR", "/tmp/paddle_tpu_trace")
                jax.profiler.start_trace(self._trace_dir)
                self._jax_tracing = True
            except Exception:
                self._jax_tracing = False

    def stop(self):
        _recording[0] = False
        if self._jax_tracing:
            import jax
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_tracing = False
        if self.on_trace_ready:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        self.step_num += 1

    def step_info(self, unit=None):
        return f"step {self.step_num}"

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        events = _recorder.events
        by_name = {}
        for e in events:
            agg = by_name.setdefault(e["name"], {"calls": 0, "total": 0.0})
            agg["calls"] += 1
            agg["total"] += e["dur"] / 1e3
        lines = [f"{'Name':40s} {'Calls':>8s} {'Total(ms)':>12s}"]
        for name, agg in sorted(by_name.items(),
                                key=lambda kv: -kv[1]["total"]):
            lines.append(f"{name:40s} {agg['calls']:>8d} "
                         f"{agg['total']:>12.3f}")
        out = "\n".join(lines)
        print(out)
        return out


from .statistic import (SortedKeys, host_statistic_table,  # noqa: E402
                        device_statistic_table, statistic_report)
from .timer import benchmark, Benchmark  # noqa: E402,F401


def _full_summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                  time_unit="ms"):
    """profiler_statistic.py-parity tables: host spans + device ops +
    (when instrumentation is on) the metrics registry snapshot."""
    out = statistic_report(
        list(_recorder.events),
        trace_dir=self._trace_dir,
        sorted_by=sorted_by or SortedKeys.CPUTotal)
    if metrics._enabled:
        out = out + "\n\n" + metrics.REGISTRY.render_table()
    extra = _extra_summary_sections()
    if extra:
        out = "\n\n".join([out] + extra)
    print(out)
    return out


Profiler.summary = _full_summary


def summary(sorted_by=None, trace_dir=None, top_k=30):
    """ONE merged observability report: host RecordEvent span tables,
    the metrics registry snapshot (dispatch counts, VJP-jit cache hit
    rate, jit compile time, collective bytes, throughput gauges), and —
    when `trace_dir` points at a jax.profiler capture — the device-plane
    op table. Module-level counterpart of `Profiler.summary` that works
    without a Profiler instance."""
    parts = [statistic_report(list(_recorder.events),
                              trace_dir=trace_dir,
                              sorted_by=sorted_by or SortedKeys.CPUTotal,
                              top_k=top_k)]
    if _recorder.dropped:
        parts.append(f"(host ring buffer dropped {_recorder.dropped} "
                     f"spans; raise PADDLE_TPU_PROFILER_EVENTS_MAX)")
    parts.append(metrics.REGISTRY.render_table())
    parts.extend(_extra_summary_sections())
    return "\n\n".join(parts)
