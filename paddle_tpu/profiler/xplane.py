"""Device-trace (xplane) parsing for profiler statistics.

The jax.profiler trace directory holds `*.xplane.pb` protos; the TPU
device plane's "XLA Ops" line is ground truth for per-op device time
(host timing through the axon relay is not; see
docs/gpt_perf_analysis.md "Setup"). Requires the pure-python protobuf
runtime for the xplane descriptor (set automatically).

Parity: the role of `paddle/fluid/platform/profiler/chrometracing_logger.cc`
+ `python/paddle/profiler/profiler_statistic.py`'s device-side tables.
"""
from __future__ import annotations

import collections
import glob
import os


def load_xplane(trace_dir):
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                          "python")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    paths = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True), key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no .xplane.pb under {trace_dir}")
    xs = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def device_op_times(xs):
    """{hlo_op_name: total_ns} over TPU device planes' XLA Ops lines."""
    out = collections.Counter()
    for plane in xs.planes:
        if "TPU" not in plane.name and "/device:" not in plane.name:
            continue
        ev_meta = plane.event_metadata
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                out[ev_meta[ev.metadata_id].name] += \
                    ev.duration_ps // 1000
    return out


def device_op_table(trace_dir, top_k=30, n_steps=1):
    """[(name, total_ms, calls)] for the newest trace under trace_dir."""
    times = device_op_times(load_xplane(trace_dir))
    rows = [(name, ns / 1e6 / n_steps, 1)
            for name, ns in times.most_common(top_k)]
    return rows
