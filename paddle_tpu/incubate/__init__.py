"""paddle_tpu.incubate — incubating APIs (`python/paddle/incubate/`).
MoE lives in paddle_tpu.incubate.distributed.models.moe (parity path).
"""
from . import nn  # noqa: F401
from . import autotune  # noqa: F401
from . import distributed  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from . import multiprocessing  # noqa: F401
from ..ops.extras3 import identity_loss  # noqa: F401
from .optimizer import ModelAverage  # noqa: F401


def softmax_mask_fuse(x, mask, name=None):
    """`fusion/fused_softmax_mask_kernel.h` — softmax(x + mask) fused
    (XLA fuses the add into the softmax reductions on TPU)."""
    import jax
    from ..core import dispatch
    from ..ops._helpers import as_tensor

    def f(a, m):
        return jax.nn.softmax(a + m.astype(a.dtype), axis=-1)
    return dispatch.apply("softmax_mask_fuse", f,
                          (as_tensor(x), as_tensor(mask)))


def softmax_mask_fuse_upper_triangle(x, name=None):
    """`fused_softmax_mask_upper_triangle` — causal-masked softmax."""
    import jax
    import jax.numpy as jnp
    from ..core import dispatch
    from ..ops._helpers import as_tensor

    def f(a):
        S, T = a.shape[-2], a.shape[-1]
        m = jnp.tril(jnp.ones((S, T), bool))
        return jax.nn.softmax(jnp.where(m, a, -1e30), axis=-1)
    return dispatch.apply("softmax_mask_fuse_upper_triangle", f,
                          (as_tensor(x),))


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """`incubate/operators/graph_send_recv.py:30` parity — the older
    name for the geometric send_u_recv gather/scatter-reduce."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index,
                       reduce_op=pool_type, out_size=out_size)


def segment_sum(data, segment_ids, name=None):
    """`incubate/tensor/math.py` parity (re-exported geometric op)."""
    from ..geometric import segment_sum as _f
    return _f(data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    from ..geometric import segment_mean as _f
    return _f(data, segment_ids)


def segment_max(data, segment_ids, name=None):
    from ..geometric import segment_max as _f
    return _f(data, segment_ids)


def segment_min(data, segment_ids, name=None):
    from ..geometric import segment_min as _f
    return _f(data, segment_ids)
