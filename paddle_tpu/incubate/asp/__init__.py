"""ASP — 2:4 structured sparsity.

Parity: `python/paddle/incubate/asp/` (`calculate_density`,
`prune_model` with mask algorithms mask_1d/mask_2d_greedy/mask_2d_best,
`decorate` masking optimizer). On TPU the mask is applied as an
elementwise multiply the compiler fuses into the matmul producer.
"""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor


def calculate_density(x):
    arr = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
    return float((arr != 0).sum() / arr.size)


def _mask_1d(weight, n=2, m=4):
    """Keep the n largest-|w| of every m consecutive elements."""
    w = weight.reshape(-1, m)
    idx = np.argsort(-np.abs(w), axis=1)[:, :n]
    mask = np.zeros_like(w, dtype=np.float32)
    np.put_along_axis(mask, idx, 1.0, axis=1)
    return mask.reshape(weight.shape)


def create_mask(weight, func_name="mask_1d", n=2, m=4):
    if func_name not in ("mask_1d",):
        raise NotImplementedError(
            f"mask algorithm {func_name!r} not implemented yet; "
            "mask_1d is available (mask_2d_greedy/mask_2d_best planned)")
    arr = weight.numpy() if isinstance(weight, Tensor) else \
        np.asarray(weight)
    pad = (-arr.size) % m
    flat = np.concatenate([arr.reshape(-1),
                           np.zeros(pad, arr.dtype)]) if pad else \
        arr.reshape(-1)
    mask = _mask_1d(flat, n, m)
    if pad:
        mask = mask[:arr.size]
    return mask.reshape(arr.shape)


def check_sparsity(x, n=2, m=4):
    arr = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
    pad = (-arr.size) % m
    flat = np.concatenate([arr.reshape(-1), np.zeros(pad, arr.dtype)])
    groups = flat.reshape(-1, m)
    return bool(((groups != 0).sum(axis=1) <= n).all())


_masks = {}


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply 2:4 masks to every Linear/Conv weight in the model
    (layers named via set_excluded_layers are skipped, asp.py parity)."""
    from ...nn.layers.common import Linear
    from ...nn.layers.conv import _ConvNd
    for name, layer in model.named_sublayers(include_self=True):
        if name in _excluded:
            continue
        if isinstance(layer, (Linear, _ConvNd)):
            w = layer.weight
            mask = create_mask(w, mask_algo, n, m)
            w.set_value(w.numpy() * mask)
            _masks[id(w)] = mask
    return _masks


def decorate(optimizer):
    """OptimizerWithSparsityGuarantee parity (`fluid/contrib/sparsity/
    asp.py:1`): re-apply the pruning masks after every update so
    sparsity survives training. Covers BOTH execution paths:
    - eager: optimizer.step is wrapped;
    - compiled (hapi fused step): the masks are published on
      `optimizer._asp_masks`; jit/trainer.py multiplies them into each
      updated parameter inside the compiled executable.
    """
    orig_step = optimizer.step
    optimizer._asp_masks = _masks

    def step():
        orig_step()
        if optimizer._parameter_list:
            for p in optimizer._parameter_list:
                mask = _masks.get(id(p))
                if mask is not None:
                    p.set_value(p.numpy() * mask)
    optimizer.step = step
    return optimizer


_excluded = set()


def set_excluded_layers(param_names=None, main_program=None):
    """Exclude sublayers (by named_sublayers name) from prune_model."""
    for n in (param_names or []):
        _excluded.add(n)


def reset_excluded_layers(main_program=None):
    _excluded.clear()
