"""Incubate optimizers — parity: `python/paddle/incubate/optimizer/`
(LookAhead, ModelAverage; DistributedFusedLamb's fused capability is the
default fused step in paddle_tpu.optimizer)."""
from __future__ import annotations

import numpy as np

from ...optimizer.optimizer import Optimizer


class LookAhead(Optimizer):
    """lookahead.py parity: wraps an inner optimizer; every k steps the
    slow weights move alpha toward the fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._la_step = 0
        # delegate bookkeeping to the inner optimizer
        self._parameter_list = inner_optimizer._parameter_list
        # slow weights anchor to the params at CREATION (reference
        # lookahead.py), not lazily at the first sync
        self._slow = {id(p): p.numpy().copy()
                      for p in (self._parameter_list or [])}

    def step(self):
        self.inner_optimizer.step()
        self._la_step += 1
        if self._la_step % self.k:
            return
        for p in self._parameter_list or []:
            slow = self._slow[id(p)]
            slow += self.alpha * (p.numpy() - slow)
            p.set_value(slow)

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return None, None

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        return {"inner": self.inner_optimizer.state_dict(),
                "la_step": self._la_step}

    def set_state_dict(self, sd):
        self.inner_optimizer.set_state_dict(sd.get("inner", {}))
        self._la_step = sd.get("la_step", 0)


class ModelAverage(Optimizer):
    """model_average.py parity: maintains a running average of params;
    apply()/restore() swap the averaged weights in and out."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(parameters=parameters)
        self.average_window_rate = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        # windowed accumulation (reference scheme): a current partial sum
        # plus the previous completed window; when the partial exceeds
        # max_average_window it rolls over, bounding the averaging window
        # to [max_window, 2*max_window) recent steps.
        self._sum_cur = {}
        self._num_cur = {}
        self._sum_prev = {}
        self._num_prev = {}
        self._backup = {}

    def step(self):
        for p in self._parameter_list or []:
            key = id(p)
            if key not in self._sum_cur:
                self._sum_cur[key] = np.zeros(p.shape, np.float64)
                self._num_cur[key] = 0
                self._sum_prev[key] = np.zeros(p.shape, np.float64)
                self._num_prev[key] = 0
            self._sum_cur[key] += p.numpy().astype(np.float64)
            self._num_cur[key] += 1
            if self._num_cur[key] >= self.max_average_window:
                self._sum_prev[key] = self._sum_cur[key]
                self._num_prev[key] = self._num_cur[key]
                self._sum_cur[key] = np.zeros(p.shape, np.float64)
                self._num_cur[key] = 0

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            for p in self._parameter_list or []:
                key = id(p)
                total = (self._num_cur.get(key, 0)
                         + self._num_prev.get(key, 0))
                if total:
                    self._backup[key] = p.numpy().copy()
                    avg = (self._sum_cur[key] + self._sum_prev[key]) \
                        / total
                    p.set_value(avg.astype(np.float32))
            try:
                yield
            finally:
                if need_restore:
                    self.restore()
        return ctx()

    def restore(self, executor=None):
        for p in self._parameter_list or []:
            key = id(p)
            if key in self._backup:
                p.set_value(self._backup.pop(key))


class DistributedFusedLamb(Optimizer):
    """`distributed_fused_lamb.py` parity: LAMB where the whole param
    set updates as ONE fused step with gradient all-reduce across dp.

    TPU-native form: `paddle_tpu.optimizer.Lamb` ALREADY runs the fused
    whole-param-set jitted update (the reference needed a dedicated CUDA
    kernel for this); under data parallelism the grad reduction is fused
    into the compiled step by GSPMD. This class keeps the reference's
    constructor surface (clip_after_allreduce, is_grad_scaled_by_nranks)
    and delegates to Lamb."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, nproc_per_node=None,
                 use_master_param_norm=True, gradient_accumulation_steps=1,
                 use_master_acc_grad=True, name=None):
        from ...optimizer import Lamb
        self._inner = Lamb(
            learning_rate=learning_rate,
            lamb_weight_decay=lamb_weight_decay,
            beta1=beta1, beta2=beta2, epsilon=epsilon,
            parameters=parameters, grad_clip=grad_clip,
            exclude_from_weight_decay_fn=exclude_from_weight_decay_fn)

    def __getattr__(self, name):
        try:
            inner = self.__dict__["_inner"]
        except KeyError:
            # copy/pickle probe dunders before __dict__ exists — must be
            # AttributeError, not KeyError
            raise AttributeError(name) from None
        return getattr(inner, name)

    def step(self):
        return self._inner.step()

    def clear_grad(self, set_to_zero=True):
        return self._inner.clear_grad(set_to_zero)
