"""MoELayer — parity: moe_layer.py `MoELayer(gate, experts, ...)`.

Fixed-shape capacity dispatch (ISSUE 10): with a `capacity_factor`
the gate picks top-k experts per token,
`parallel.moe_utils.capacity_dispatch` builds the one-hot `[T, k, C]`
dispatch/combine masks, and each expert runs ONLY its `[C, d]`
capacity buffer; overflowed (token, choice) pairs are dropped (the
surrounding residual carries them). The uncapped default keeps the
reference's dense every-expert evaluation (no drops, no O(T^2 k)
masks — see the class docstring). The expert-parallel all_to_all
happens when the surrounding step is compiled over a mesh with the
experts sharded (hybrid_gpt's `_moe_ffn` path over the "ep" axis);
eager single-controller execution evaluates the local experts
directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....nn.layer_base import Layer
from .....nn.container import LayerList
from .....core.tensor import Tensor
from .....core import dispatch
from .....ops._helpers import as_tensor
from .gate import NaiveGate, SwitchGate, GShardGate


class MoELayer(Layer):
    """moe_layer.py:MoELayer parity: inp [B, S, d] -> [B, S, d].

    `capacity_factor` bounds each expert's per-batch token buffer at
    `ceil(factor * T * k / E)` slots (the fixed-shape dispatch the
    compiled paths use). The default (None) is UNCAPPED and runs the
    reference's dense every-expert evaluation instead: same compute
    as capacity dispatch at C = T but without materializing the
    O(T^2 k) slot masks, and no token can ever drop — this layer
    returns the combine directly with no residual of its own, so the
    every-token semantics are preserved unless a caller that wraps
    the layer in a residual block explicitly opts into capping.
    `last_stats` carries the latest routing statistics
    ({counts [E], dropped, capacity})."""

    def __init__(self, d_model, experts=None, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0,
                 capacity_factor=None, **kwargs):
        super().__init__()
        self.d_model = d_model
        if isinstance(gate, dict):
            gtype = gate.get("type", "gshard")
            topk = gate.get("top_k", 2)
            n_exp = len(experts)
            cls = {"naive": NaiveGate, "switch": SwitchGate,
                   "gshard": GShardGate}[gtype]
            gate = cls(d_model, n_exp, topk=topk)
        self.gate = gate
        self.experts = experts if isinstance(experts, LayerList) \
            else LayerList(experts)
        self.num_expert = len(self.experts)
        self.capacity_factor = None if capacity_factor is None \
            else float(capacity_factor)
        self.last_stats = None

    def forward(self, inp):
        from .....parallel import moe_utils
        inp = as_tensor(inp)
        shape = inp.shape
        d = shape[-1]
        from ..... import ops
        x = ops.reshape(inp, [-1, d])  # [T, d]
        gate_val, gate_idx = self.gate(x)  # [T, k], [T, k]
        E = self.num_expert
        T = x.shape[0]
        k = gate_val.shape[-1]
        gv, gi, xa = as_tensor(gate_val), as_tensor(gate_idx), \
            as_tensor(x)

        if self.capacity_factor is None:
            # uncapped: every expert evaluates every token and the
            # gate mixes — identical math to C = T capacity dispatch
            # without the [T, k, T] slot masks
            expert_outs = [ops.unsqueeze(exp(xa), 1)
                           for exp in self.experts]
            stacked = ops.concat(expert_outs, axis=1)       # [T, E, d]

            def _mix(val, idx, outs):
                oh = jax.nn.one_hot(idx, E, dtype=outs.dtype)  # [T,k,E]
                w = jnp.einsum("tk,tke->te", val.astype(outs.dtype),
                               oh)
                counts = jnp.sum(oh.astype(jnp.float32), axis=(0, 1))
                return (jnp.einsum("te,ted->td", w, outs), counts,
                        jnp.zeros((), jnp.float32))

            out, counts, dropped = dispatch.apply(
                "moe_combine", _mix, (gv, gi, as_tensor(stacked)))
            self.last_stats = {"counts": counts, "dropped": dropped,
                               "capacity": T}
            return ops.reshape(out, shape)

        C = moe_utils.expert_capacity(T, E, k, self.capacity_factor)

        def _dispatch(xd, val, idx):
            plan = moe_utils.capacity_dispatch(val, idx, E, C,
                                               dtype=xd.dtype)
            buf = moe_utils.dispatch_tokens(xd, plan)       # [E, C, d]
            return (buf, plan.comb, plan.e_oh, plan.counts,
                    plan.dropped)

        buf, comb, e_oh, counts, dropped = dispatch.apply(
            "moe_dispatch", _dispatch, (xa, gv, gi))
        # each expert consumes ONLY its capacity buffer (C tokens)
        expert_outs = [ops.unsqueeze(exp(buf[e]), 0)
                       for e, exp in enumerate(self.experts)]
        eout = ops.concat(expert_outs, axis=0)              # [E, C, d]

        def _combine(eo, cb, eh):
            return jnp.einsum("tkc,tke,ecd->td", cb, eh,
                              eo.astype(cb.dtype))

        out = dispatch.apply("moe_combine", _combine,
                             (as_tensor(eout), as_tensor(comb),
                              as_tensor(e_oh)))
        self.last_stats = {"counts": counts, "dropped": dropped,
                           "capacity": C}
        return ops.reshape(out, shape)
