"""MoE user API.

Parity: `python/paddle/incubate/distributed/models/moe/` (`MoELayer`
(moe_layer.py), gates: NaiveGate/GShardGate/SwitchGate, comm via
global_scatter/global_gather ops `collective/global_scatter_op.cu.cc`).

TPU-native: routing/dispatch/combine come from the shared fixed-shape
capacity router in `parallel.moe_utils` (one-hot einsums; also behind
`parallel/hybrid_gpt._moe_ffn` and the serving mixed step — see
docs/MOE.md); this module provides the layer/gate class surface over
it. Inside a compiled sharded step with the dedicated "ep" mesh axis
the `[E, C, d]` dispatch tensors ride `lax.all_to_all` on ICI; on one
chip each expert runs its capacity buffer locally.
"""
from .gate import NaiveGate, GShardGate, SwitchGate, BaseGate  # noqa
from .moe_layer import MoELayer  # noqa
