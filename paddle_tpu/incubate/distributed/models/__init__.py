from . import moe  # noqa
