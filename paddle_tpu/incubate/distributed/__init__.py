from . import models  # noqa
