"""`paddle.incubate.multiprocessing` parity
(`python/paddle/incubate/multiprocessing/__init__.py`): tensor-aware
multiprocessing with shared-memory transport.

TPU-native form: device arrays are host-fetched once and shipped via
`multiprocessing.shared_memory` (the same transport the multiprocess
DataLoader workers use, `io/__init__.py`); a reductions registry makes
`paddle.Tensor` picklable across processes.
"""
from __future__ import annotations

import multiprocessing
from multiprocessing import *  # noqa: F401,F403
from multiprocessing import shared_memory

import numpy as np


def _rebuild_tensor(shm_name, shape, dtype):
    # consumer owns the segment: copy out, then unlink (the io/
    # DataLoader shm transport's ownership-transfer pattern) — without
    # this every pickled tensor leaks a /dev/shm segment
    try:
        shm = shared_memory.SharedMemory(name=shm_name)
    except FileNotFoundError:
        raise RuntimeError(
            "paddle Tensor shm blob already consumed: each serialized "
            "tensor is single-use (ownership transfers to the first "
            "loader, which unlinks the segment); re-pickle for every "
            "consumer") from None
    try:
        arr = np.ndarray(shape, dtype=dtype, buffer=shm.buf).copy()
    finally:
        shm.close()
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
    from ...core.tensor import Tensor
    return Tensor(arr)


def _reduce_tensor(t):
    arr = np.asarray(t._data)
    shm = shared_memory.SharedMemory(create=True, size=max(arr.nbytes, 1))
    # ownership transfers to the consumer (which unlinks after copy-out)
    # — unregister from THIS process's resource tracker, or the producer
    # exiting first would unlink the segment out from under the consumer
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass
    np.ndarray(arr.shape, dtype=arr.dtype, buffer=shm.buf)[...] = arr
    name = shm.name
    shm.close()
    return _rebuild_tensor, (name, arr.shape, arr.dtype.str)


_registered = False


def init_reductions():
    """Register the shared-memory pickler for paddle Tensors. EXPLICIT
    opt-in (call before shipping Tensors through mp queues): the
    registration is process-global and single-use-consume semantics
    would surprise code using plain pickling — notably the in-tree
    DataLoader workers, which have their own shm transport
    (`io/__init__.py`)."""
    global _registered
    if _registered:
        return
    from multiprocessing import reduction
    from ...core.tensor import Tensor
    reduction.ForkingPickler.register(Tensor, _reduce_tensor)
    _registered = True
