"""Autoregressive generation over the fused decode stack.

Capability parity: the serving loop the reference runs through
`FusedMultiTransformer`'s `cache_kvs`/`time_step` protocol
(`python/paddle/incubate/nn/layer/fused_transformer.py:1382`,
`paddle/fluid/operators/fused/fused_multi_transformer_op.cu` —
PaddleNLP's `generate()` drives it).

TPU-native shape discipline — everything is compiled exactly once:

* the prompt is right-padded to a power-of-two bucket, masked with
  `seq_lens`;
* the KV cache is one fixed-shape tensor covering prompt + new tokens;
* decode runs either as ONE `lax.scan` executable over all steps
  (default; zero host round-trips) or as a python loop over a single
  jitted step (streaming / early EOS exit) — both trace once because
  token/cache/position shapes never change.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...jit.functional import instrumented_jit
from ...ops._helpers import as_tensor
# the sampling head + shape-bucket discipline are shared with the
# continuous-batching engine; they live in serving.batcher (kept
# importable here under their historical names)
from ...serving.batcher import (
    SamplingConfig,
    next_pow2 as _next_pow2,
    round_up as _round_up,
    select_token as _select_token,
)
from ...serving.draft import (
    accept_length as _accept_length,
    ngram_propose as _ngram_propose,
)


class GenerationMixin:
    """Adds `generate()` to a causal-LM layer.

    The subclass provides the pure cores (arrays in, arrays out):
      * `_gen_tensors()` -> list[Tensor]  — every array the cores need
      * `_prefill_core(arrays, ids, seq_lens, cache)`
            ids [B, S_pad] -> (last_logits [B, V], new_cache)
      * `_decode_core(arrays, token, positions, cache)`
            token [B], positions [B] -> (logits [B, V], new_cache)
      * `_gen_cache(batch, s_max, dtype)` -> cache array
    """

    def _gen_fns(self, shape_key, sc, eos_id, max_new_tokens, use_scan,
                 uniform, draft_k=0):
        cache = getattr(self, "_gen_fn_cache", None)
        if cache is None:
            cache = self._gen_fn_cache = {}
        # prefill/decode_step depend only on shapes + sampling config —
        # keying them on max_new_tokens/eos would recompile multi-second
        # XLA executables when only the generation length changes
        base_key = (shape_key, sc, uniform)
        key = (shape_key, sc, eos_id, max_new_tokens, use_scan, uniform,
               draft_k)
        if key in cache:
            return cache[key]
        B, s_bucket, s_max, cache_dtype = shape_key
        eos = -1 if eos_id is None else int(eos_id)

        def prefill(arrays, ids, seq_lens, rng):
            kv = self._gen_cache(B, s_max, cache_dtype)
            logits, kv = self._prefill_core(arrays, ids, seq_lens, kv)
            tok = _select_token(logits, rng, sc)
            return tok, kv

        def decode_step(arrays, kv, tok, positions, rng):
            # `positions` is a scalar when every row shares the prompt
            # length (the common serving case) — the cache write is then
            # one dynamic_update_slice instead of a batched scatter
            logits, kv = self._decode_core(arrays, tok, positions, kv)
            nxt = _select_token(logits, rng, sc)
            return kv, nxt

        def decode_scan(arrays, kv, tok, seq_lens, rng):
            finished0 = tok == eos if eos >= 0 else jnp.zeros(
                tok.shape, bool)
            pos0 = seq_lens[0] if uniform else seq_lens

            def step(carry, i):
                kv, tok, finished, rng = carry
                rng, sub = jax.random.split(rng)
                kv, nxt = decode_step(arrays, kv, tok, pos0 + i, sub)
                if eos >= 0:
                    nxt = jnp.where(finished, jnp.int32(eos), nxt)
                    finished = finished | (nxt == eos)
                return (kv, nxt, finished, rng), nxt

            (kv, _, _, _), toks = jax.lax.scan(
                step, (kv, tok, finished0, rng),
                jnp.arange(max_new_tokens - 1, dtype=jnp.int32))
            # the final cache is returned so the donated input cache can
            # alias it — otherwise XLA must copy the cache into the loop
            return jnp.concatenate([tok[:, None], toks.T], axis=1), kv

        shared = cache.get(("base", base_key))
        if shared is None:
            shared = {
                "prefill": instrumented_jit(prefill, "gen_prefill"),
                "decode_step": instrumented_jit(
                    decode_step, "gen_decode_step", donate_argnums=(1,)),
            }
            cache[("base", base_key)] = shared
        fns = {
            **shared,
            # donate the cache: without it XLA must preserve the input
            # buffer and copies the full cache into the scan carry
            # (measured as a GB-scale `copy(kv)` temp on a 350M config)
            "decode_scan": instrumented_jit(
                decode_scan, "gen_decode_scan", donate_argnums=(1,)),
        }
        if draft_k > 0:
            # verify_step depends only on shapes — like prefill/decode
            # it is cached independently of max_new_tokens/eos so a
            # generation-length change never re-compiles it
            vkey = ("verify", shape_key, draft_k)
            vfn = cache.get(vkey)
            if vfn is None:
                def verify_step(arrays, kv, tokens, positions):
                    # tokens [B, K] at positions[b] + j; greedy argmax
                    # over every scored position — the host accepts the
                    # longest prefix where draft j+1 equals the argmax
                    # after j
                    logits, kv = self._verify_core(arrays, tokens,
                                                   positions, kv)
                    nxt = jnp.argmax(logits.astype(jnp.float32),
                                     axis=-1).astype(jnp.int32)  # [B,K]
                    return kv, nxt

                vfn = cache[vkey] = instrumented_jit(
                    verify_step, "gen_verify_step", donate_argnums=(1,))
            fns["verify_step"] = vfn
        cache[key] = fns
        return fns

    def generate(self, input_ids, max_new_tokens=32,
                 decode_strategy="greedy", temperature=1.0, top_k=0,
                 top_p=1.0, eos_token_id=None, seed=None, use_scan=True,
                 cache_dtype=None, seq_lens=None, draft_k=0,
                 draft_ngram=3):
        """Returns (ids [B, max_new_tokens], gen_lens [B]). `gen_lens`
        is each row's ACTUAL generated length — up to and including its
        first EOS (max_new_tokens when the row never emits EOS or no
        `eos_token_id` is given); positions past it are EOS padding.
        Greedy or sampling; compiled prefill + compiled decode (see
        module doc).

        `seq_lens` [B] gives each row's true (unpadded) prompt length for
        ragged right-padded batches; without it every row is assumed to
        span the full prompt width (pad tokens would be attended).

        `draft_k > 0` turns on speculative decoding (greedy only): a
        host-side prompt-lookup draft (`serving.draft.ngram_propose`,
        trailing n-grams up to `draft_ngram`) proposes `draft_k` tokens
        per step and ONE compiled verify step scores all of them,
        emitting the longest sequential-greedy prefix plus the model's
        correction — between 1 and draft_k+1 tokens per step, always
        token-identical to `draft_k=0`. The win scales with how
        repetitive the text is (each accepted draft token saves one
        full latency-bound decode step)."""
        ids = as_tensor(input_ids)
        ids_np = np.asarray(ids.numpy(), np.int32)
        if ids_np.ndim == 1:
            ids_np = ids_np[None]
        B, S = ids_np.shape
        maxpos = getattr(self, "max_position_embeddings", None)
        if maxpos is not None and S + max_new_tokens > maxpos:
            raise ValueError(
                f"prompt ({S}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_position_embeddings ({maxpos}); late "
                "positions would silently share one position embedding")
        draft_k = int(draft_k)
        if draft_k > 0 and decode_strategy != "greedy":
            raise ValueError(
                "speculative decoding (draft_k > 0) verifies against the "
                "greedy continuation; sampling strategies need rejection "
                "sampling, which is not implemented — use "
                "decode_strategy='greedy' or draft_k=0")
        s_bucket = _next_pow2(S)
        # 128 keeps the sequence-minor cache layout pad-free (lane dim);
        # speculation needs draft_k columns of slack past the horizon
        # (the last verify step writes draft K/V beyond the final token)
        s_max = _round_up(s_bucket + max_new_tokens + draft_k, 128)
        dt = cache_dtype or getattr(self, "_gen_cache_dtype", "bfloat16")
        sc = SamplingConfig("greedy" if decode_strategy == "greedy"
                            else "sampling", float(temperature),
                            int(top_k), float(top_p))
        if seq_lens is not None:
            lens_np = np.asarray(
                seq_lens.numpy() if isinstance(seq_lens, Tensor)
                else seq_lens, np.int32).reshape(-1)
            if lens_np.shape != (B,):
                raise ValueError(
                    f"seq_lens must have shape [{B}], got "
                    f"{lens_np.shape}")
            if (lens_np < 1).any() or (lens_np > S).any():
                raise ValueError("seq_lens entries must lie in [1, "
                                 f"{S}]")
        elif hasattr(self, "_seq_lens_of"):
            lens_np = np.asarray(self._seq_lens_of(ids_np), np.int32)
        else:
            lens_np = np.full((B,), S, np.int32)
        uniform = bool((lens_np == lens_np[0]).all())
        shape_key = (B, s_bucket, s_max, str(dt))
        fns = self._gen_fns(shape_key, sc, eos_token_id, max_new_tokens,
                            use_scan, uniform, draft_k)
        # cast float params to the compute dtype ONCE — an .astype left
        # inside the decode step re-converts (and re-reads) the full
        # array every token (measured: the f32 lm_head alone is ~100MB
        # of per-step convert traffic on a 350M config)
        cdt = jnp.dtype(getattr(self, "_compute_dtype", "float32"))
        arrays = [a.astype(cdt)
                  if a.dtype in (jnp.float32, jnp.float64) else a
                  for a in (t._data for t in self._gen_tensors())]
        padded = np.zeros((B, s_bucket), np.int32)
        padded[:, :S] = ids_np
        seq_lens = jnp.asarray(lens_np)
        if seed is None:
            # draw from the framework RNG so paddle.seed() governs
            # sampling and repeated calls differ (reference generate()
            # semantics)
            from ...core import random as rng_mod
            rng = rng_mod.next_key()
        else:
            rng = jax.random.PRNGKey(int(seed))
        rng, sub = jax.random.split(rng)
        tok, kv = fns["prefill"](arrays, jnp.asarray(padded), seq_lens,
                                 sub)
        if max_new_tokens == 1:
            ids = tok[:, None]
            return Tensor(ids), Tensor(_gen_lens_jnp(ids, eos_token_id))
        if draft_k > 0:
            return self._generate_speculative(
                fns, arrays, kv, tok, ids_np, lens_np, max_new_tokens,
                eos_token_id, draft_k, draft_ngram)
        if use_scan:
            toks, _ = fns["decode_scan"](arrays, kv, tok, seq_lens, rng)
            return Tensor(toks), Tensor(_gen_lens_jnp(toks,
                                                      eos_token_id))
        # python loop (streaming / early-exit) over the one jitted step:
        # stops as soon as EVERY row has emitted EOS (checked before
        # each step, so an all-EOS prefill token runs zero decode steps)
        out = [np.asarray(tok)]
        finished = (out[0] == eos_token_id) if eos_token_id is not None \
            else np.zeros((B,), bool)
        for i in range(max_new_tokens - 1):
            if eos_token_id is not None and finished.all():
                break
            rng, sub = jax.random.split(rng)
            pos = (seq_lens[0] + jnp.int32(i)) if uniform \
                else seq_lens + jnp.int32(i)
            kv, tok = fns["decode_step"](arrays, kv, tok, pos, sub)
            t_np = np.asarray(tok)
            if eos_token_id is not None:
                t_np = np.where(finished, eos_token_id, t_np)
                finished |= t_np == eos_token_id
            out.append(t_np)
        toks = np.stack(out, axis=1)
        if toks.shape[1] < max_new_tokens and eos_token_id is not None:
            pad = np.full((B, max_new_tokens - toks.shape[1]),
                          eos_token_id, np.int32)
            toks = np.concatenate([toks, pad], axis=1)
        return Tensor(jnp.asarray(toks)), \
            Tensor(jnp.asarray(_gen_lens_np(toks, eos_token_id)))

    def _generate_speculative(self, fns, arrays, kv, tok, ids_np,
                              lens_np, max_new_tokens, eos_token_id,
                              draft_k, draft_ngram):
        """Greedy speculative loop over the ONE compiled verify step.

        Every iteration feeds [last_token, d_1..d_draft_k] at per-row
        positions and emits the longest prefix where draft j equals the
        model's argmax after j-1, plus the model's own next token — so
        each row advances 1..draft_k+1 tokens and the output is exactly
        the sequential greedy continuation. Rejected draft K/V columns
        need no explicit rollback: the next step's draft_k+1-wide write
        starts at the first invalid position and always covers them
        before any query can attend that range."""
        B = ids_np.shape[0]
        tok_np = np.asarray(tok)
        outs = [[int(tok_np[b])] for b in range(B)]
        seqs = [[int(t) for t in ids_np[b, :int(lens_np[b])]]
                + [outs[b][0]] for b in range(B)]
        finished = [eos_token_id is not None
                    and outs[b][0] == eos_token_id for b in range(B)]

        def active(b):
            return not finished[b] and len(outs[b]) < max_new_tokens

        K = draft_k + 1
        step_toks = np.zeros((B, K), np.int32)
        pos0 = np.zeros((B,), np.int32)
        self.last_accept_counts = []   # per-step emitted counts (bench)
        while any(active(b) for b in range(B)):
            for b in range(B):
                pos0[b] = len(seqs[b]) - 1
                step_toks[b, 0] = seqs[b][-1]
                if active(b):
                    step_toks[b, 1:] = _ngram_propose(
                        seqs[b], draft_k, max_ngram=draft_ngram)
                else:
                    # frozen rows re-feed their last token in place
                    step_toks[b, 1:] = seqs[b][-1]
            kv, nxt = fns["verify_step"](arrays, kv,
                                         jnp.asarray(step_toks),
                                         jnp.asarray(pos0))
            nxt_np = np.asarray(nxt)
            emitted = []
            for b in range(B):
                if not active(b):
                    continue
                g = nxt_np[b]
                m = _accept_length(step_toks[b], g)
                emit = [int(t) for t in g[:m + 1]]
                emit = emit[:max_new_tokens - len(outs[b])]
                if eos_token_id is not None and eos_token_id in emit:
                    emit = emit[:emit.index(eos_token_id) + 1]
                    finished[b] = True
                outs[b].extend(emit)
                seqs[b].extend(emit)
                emitted.append(len(emit))
            self.last_accept_counts.append(emitted)
        pad = eos_token_id if eos_token_id is not None else 0
        toks = np.asarray(
            [outs[b] + [pad] * (max_new_tokens - len(outs[b]))
             for b in range(B)], np.int32)
        return Tensor(jnp.asarray(toks)), \
            Tensor(jnp.asarray(_gen_lens_np(toks, eos_token_id)))


def _gen_lens_np(toks, eos_id):
    """[B, M] generated ids -> [B] int32 actual lengths (first EOS
    inclusive; M when absent)."""
    B, M = toks.shape
    if eos_id is None:
        return np.full((B,), M, np.int32)
    hit = toks == eos_id
    first = np.argmax(hit, axis=1)
    return np.where(hit.any(axis=1), first + 1, M).astype(np.int32)


def _gen_lens_jnp(toks, eos_id):
    """Device-side twin of `_gen_lens_np` (scan path: no host sync)."""
    B, M = toks.shape
    if eos_id is None:
        return jnp.full((B,), M, jnp.int32)
    hit = toks == eos_id
    first = jnp.argmax(hit, axis=1)
    return jnp.where(hit.any(axis=1), first + 1, M).astype(jnp.int32)
