"""Autoregressive generation over the fused decode stack.

Capability parity: the serving loop the reference runs through
`FusedMultiTransformer`'s `cache_kvs`/`time_step` protocol
(`python/paddle/incubate/nn/layer/fused_transformer.py:1382`,
`paddle/fluid/operators/fused/fused_multi_transformer_op.cu` —
PaddleNLP's `generate()` drives it).

TPU-native shape discipline — everything is compiled exactly once:

* the prompt is right-padded to a power-of-two bucket, masked with
  `seq_lens`;
* the KV cache is one fixed-shape tensor covering prompt + new tokens;
* decode runs either as ONE `lax.scan` executable over all steps
  (default; zero host round-trips) or as a python loop over a single
  jitted step (streaming / early EOS exit) — both trace once because
  token/cache/position shapes never change.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...ops._helpers import as_tensor
# the sampling head + shape-bucket discipline are shared with the
# continuous-batching engine; they live in serving.batcher (kept
# importable here under their historical names)
from ...serving.batcher import (
    SamplingConfig,
    next_pow2 as _next_pow2,
    round_up as _round_up,
    select_token as _select_token,
)


class GenerationMixin:
    """Adds `generate()` to a causal-LM layer.

    The subclass provides the pure cores (arrays in, arrays out):
      * `_gen_tensors()` -> list[Tensor]  — every array the cores need
      * `_prefill_core(arrays, ids, seq_lens, cache)`
            ids [B, S_pad] -> (last_logits [B, V], new_cache)
      * `_decode_core(arrays, token, positions, cache)`
            token [B], positions [B] -> (logits [B, V], new_cache)
      * `_gen_cache(batch, s_max, dtype)` -> cache array
    """

    def _gen_fns(self, shape_key, sc, eos_id, max_new_tokens, use_scan,
                 uniform):
        cache = getattr(self, "_gen_fn_cache", None)
        if cache is None:
            cache = self._gen_fn_cache = {}
        # prefill/decode_step depend only on shapes + sampling config —
        # keying them on max_new_tokens/eos would recompile multi-second
        # XLA executables when only the generation length changes
        base_key = (shape_key, sc, uniform)
        key = (shape_key, sc, eos_id, max_new_tokens, use_scan, uniform)
        if key in cache:
            return cache[key]
        B, s_bucket, s_max, cache_dtype = shape_key
        eos = -1 if eos_id is None else int(eos_id)

        def prefill(arrays, ids, seq_lens, rng):
            kv = self._gen_cache(B, s_max, cache_dtype)
            logits, kv = self._prefill_core(arrays, ids, seq_lens, kv)
            tok = _select_token(logits, rng, sc)
            return tok, kv

        def decode_step(arrays, kv, tok, positions, rng):
            # `positions` is a scalar when every row shares the prompt
            # length (the common serving case) — the cache write is then
            # one dynamic_update_slice instead of a batched scatter
            logits, kv = self._decode_core(arrays, tok, positions, kv)
            nxt = _select_token(logits, rng, sc)
            return kv, nxt

        def decode_scan(arrays, kv, tok, seq_lens, rng):
            finished0 = tok == eos if eos >= 0 else jnp.zeros(
                tok.shape, bool)
            pos0 = seq_lens[0] if uniform else seq_lens

            def step(carry, i):
                kv, tok, finished, rng = carry
                rng, sub = jax.random.split(rng)
                kv, nxt = decode_step(arrays, kv, tok, pos0 + i, sub)
                if eos >= 0:
                    nxt = jnp.where(finished, jnp.int32(eos), nxt)
                    finished = finished | (nxt == eos)
                return (kv, nxt, finished, rng), nxt

            (kv, _, _, _), toks = jax.lax.scan(
                step, (kv, tok, finished0, rng),
                jnp.arange(max_new_tokens - 1, dtype=jnp.int32))
            # the final cache is returned so the donated input cache can
            # alias it — otherwise XLA must copy the cache into the loop
            return jnp.concatenate([tok[:, None], toks.T], axis=1), kv

        shared = cache.get(("base", base_key))
        if shared is None:
            shared = {
                "prefill": jax.jit(prefill),
                "decode_step": jax.jit(decode_step, donate_argnums=(1,)),
            }
            cache[("base", base_key)] = shared
        fns = {
            **shared,
            # donate the cache: without it XLA must preserve the input
            # buffer and copies the full cache into the scan carry
            # (measured as a GB-scale `copy(kv)` temp on a 350M config)
            "decode_scan": jax.jit(decode_scan, donate_argnums=(1,)),
        }
        cache[key] = fns
        return fns

    def generate(self, input_ids, max_new_tokens=32,
                 decode_strategy="greedy", temperature=1.0, top_k=0,
                 top_p=1.0, eos_token_id=None, seed=None, use_scan=True,
                 cache_dtype=None, seq_lens=None):
        """Returns (ids [B, max_new_tokens], gen_lens [B]). `gen_lens`
        is each row's ACTUAL generated length — up to and including its
        first EOS (max_new_tokens when the row never emits EOS or no
        `eos_token_id` is given); positions past it are EOS padding.
        Greedy or sampling; compiled prefill + compiled decode (see
        module doc).

        `seq_lens` [B] gives each row's true (unpadded) prompt length for
        ragged right-padded batches; without it every row is assumed to
        span the full prompt width (pad tokens would be attended)."""
        ids = as_tensor(input_ids)
        ids_np = np.asarray(ids.numpy(), np.int32)
        if ids_np.ndim == 1:
            ids_np = ids_np[None]
        B, S = ids_np.shape
        maxpos = getattr(self, "max_position_embeddings", None)
        if maxpos is not None and S + max_new_tokens > maxpos:
            raise ValueError(
                f"prompt ({S}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_position_embeddings ({maxpos}); late "
                "positions would silently share one position embedding")
        s_bucket = _next_pow2(S)
        # 128 keeps the sequence-minor cache layout pad-free (lane dim)
        s_max = _round_up(s_bucket + max_new_tokens, 128)
        dt = cache_dtype or getattr(self, "_gen_cache_dtype", "bfloat16")
        sc = SamplingConfig("greedy" if decode_strategy == "greedy"
                            else "sampling", float(temperature),
                            int(top_k), float(top_p))
        if seq_lens is not None:
            lens_np = np.asarray(
                seq_lens.numpy() if isinstance(seq_lens, Tensor)
                else seq_lens, np.int32).reshape(-1)
            if lens_np.shape != (B,):
                raise ValueError(
                    f"seq_lens must have shape [{B}], got "
                    f"{lens_np.shape}")
            if (lens_np < 1).any() or (lens_np > S).any():
                raise ValueError("seq_lens entries must lie in [1, "
                                 f"{S}]")
        elif hasattr(self, "_seq_lens_of"):
            lens_np = np.asarray(self._seq_lens_of(ids_np), np.int32)
        else:
            lens_np = np.full((B,), S, np.int32)
        uniform = bool((lens_np == lens_np[0]).all())
        shape_key = (B, s_bucket, s_max, str(dt))
        fns = self._gen_fns(shape_key, sc, eos_token_id, max_new_tokens,
                            use_scan, uniform)
        # cast float params to the compute dtype ONCE — an .astype left
        # inside the decode step re-converts (and re-reads) the full
        # array every token (measured: the f32 lm_head alone is ~100MB
        # of per-step convert traffic on a 350M config)
        cdt = jnp.dtype(getattr(self, "_compute_dtype", "float32"))
        arrays = [a.astype(cdt)
                  if a.dtype in (jnp.float32, jnp.float64) else a
                  for a in (t._data for t in self._gen_tensors())]
        padded = np.zeros((B, s_bucket), np.int32)
        padded[:, :S] = ids_np
        seq_lens = jnp.asarray(lens_np)
        if seed is None:
            # draw from the framework RNG so paddle.seed() governs
            # sampling and repeated calls differ (reference generate()
            # semantics)
            from ...core import random as rng_mod
            rng = rng_mod.next_key()
        else:
            rng = jax.random.PRNGKey(int(seed))
        rng, sub = jax.random.split(rng)
        tok, kv = fns["prefill"](arrays, jnp.asarray(padded), seq_lens,
                                 sub)
        if max_new_tokens == 1:
            ids = tok[:, None]
            return Tensor(ids), Tensor(_gen_lens_jnp(ids, eos_token_id))
        if use_scan:
            toks, _ = fns["decode_scan"](arrays, kv, tok, seq_lens, rng)
            return Tensor(toks), Tensor(_gen_lens_jnp(toks,
                                                      eos_token_id))
        # python loop (streaming / early-exit) over the one jitted step:
        # stops as soon as EVERY row has emitted EOS (checked before
        # each step, so an all-EOS prefill token runs zero decode steps)
        out = [np.asarray(tok)]
        finished = (out[0] == eos_token_id) if eos_token_id is not None \
            else np.zeros((B,), bool)
        for i in range(max_new_tokens - 1):
            if eos_token_id is not None and finished.all():
                break
            rng, sub = jax.random.split(rng)
            pos = (seq_lens[0] + jnp.int32(i)) if uniform \
                else seq_lens + jnp.int32(i)
            kv, tok = fns["decode_step"](arrays, kv, tok, pos, sub)
            t_np = np.asarray(tok)
            if eos_token_id is not None:
                t_np = np.where(finished, eos_token_id, t_np)
                finished |= t_np == eos_token_id
            out.append(t_np)
        toks = np.stack(out, axis=1)
        if toks.shape[1] < max_new_tokens and eos_token_id is not None:
            pad = np.full((B, max_new_tokens - toks.shape[1]),
                          eos_token_id, np.int32)
            toks = np.concatenate([toks, pad], axis=1)
        return Tensor(jnp.asarray(toks)), \
            Tensor(jnp.asarray(_gen_lens_np(toks, eos_token_id)))


def _gen_lens_np(toks, eos_id):
    """[B, M] generated ids -> [B] int32 actual lengths (first EOS
    inclusive; M when absent)."""
    B, M = toks.shape
    if eos_id is None:
        return np.full((B,), M, np.int32)
    hit = toks == eos_id
    first = np.argmax(hit, axis=1)
    return np.where(hit.any(axis=1), first + 1, M).astype(np.int32)


def _gen_lens_jnp(toks, eos_id):
    """Device-side twin of `_gen_lens_np` (scan path: no host sync)."""
    B, M = toks.shape
    if eos_id is None:
        return jnp.full((B,), M, jnp.int32)
    hit = toks == eos_id
    first = jnp.argmax(hit, axis=1)
    return jnp.where(hit.any(axis=1), first + 1, M).astype(jnp.int32)
