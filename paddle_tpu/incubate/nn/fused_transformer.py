"""Fused transformer family — the LLM-serving stack.

Parity: `python/paddle/incubate/nn/layer/fused_transformer.py`
(`FusedMultiHeadAttention` :196, `FusedFeedForward` :489,
`FusedTransformerEncoderLayer` :720, `FusedMultiTransformer` :1016,
`FusedMultiTransformerINT8` :1464, `FusedMoELayer` :1766,
`FusedMultiTransformerMoe` :1934, `FusedMultiTransformerMoeWeightOnly`
:2645) and the CUDA kernels behind them
(`paddle/fluid/operators/fused/fused_multi_transformer_op.cu`,
`fused_multi_transformer_moe_op.cu`,
`paddle/phi/kernels/weight_only_linear_kernel.h`).

TPU-native design (not a port):

* **Stacked weights + `lax.scan` over layers.** The reference keeps
  per-layer ParameterLists and launches one fused kernel per layer; here
  each weight family is ONE stacked parameter with a leading `[L]` axis
  and the whole stack runs as a single `lax.scan` — one XLA compilation
  regardless of depth, weights stay resident, and orbax checkpoints them
  as single arrays.
* **Fixed-shape KV cache.** `gen_cache` returns a `[L, 2, B, S_max, H, Dh]`
  tensor. Prefill writes positions `[0, S)` with a masked write; decode
  writes position `time_step` via `lax.dynamic_update_slice` (scalar
  step) or a batched-index update (per-row `seq_lens`). Shapes never
  change, so a jitted decode step compiles exactly once — the
  reference's `cache_kvs` + `time_step` protocol
  (`fused_transformer.py:1382`) without per-step reallocation.
* **Weight-only int8** stores `int8` weights + per-out-channel scales;
  the dequant is fused by XLA into the bf16 MXU matmul (HBM-bandwidth
  win, the point of `weight_only_linear_kernel.h`).
* **MoE** uses the dense one-hot dispatch with capacity (same scheme as
  `parallel/hybrid_gpt._moe_ffn`, ref `global_scatter_op.cu.cc`); pass
  `ep_axis` to ride an expert-parallel mesh axis via `lax.all_to_all`.
* **TP**: pass `mp_axis` when calling inside `shard_map` — row-parallel
  outputs are `lax.psum`ed over that axis (the reference's `ring_id`
  allreduce).
"""
from __future__ import annotations

import contextlib
import dataclasses
import math

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core import random as rng_mod
from ...core.tensor import Tensor
from ...nn.layer_base import Layer
from ...nn.layers.common import Linear
from ...nn import functional as F
from ...ops._helpers import as_tensor


# ---------------------------------------------------------------------------
# pure-jax core (shared by eager forward, prefill, decode and generate())
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _MTConfig:
    num_layers: int
    num_heads: int
    head_dim: int
    dim_ff: int
    epsilon: float = 1e-5
    normalize_before: bool = True
    activation: str = "gelu"
    dropout_rate: float = 0.0
    quant_bits: int = 0            # 0 = float weights, 8 = weight-only int8
    moe_quant_bits: int = 0        # expert-stack override: 0 = follow
    #                                quant_bits, 8 = int8, 4 = packed
    #                                int4 (two nibbles/byte, fp16
    #                                scales — ops.pallas.grouped_matmul)
    num_experts: int = 0           # 0 = dense FFN
    moe_topk: int = 2
    capacity_factor: float = 1.25
    mp_axis: str | None = None     # lax.psum axis for TP row-parallel outs
    ep_axis: str | None = None     # lax.all_to_all axis for MoE dispatch
    ep_size: int = 1

    @property
    def embed_dim(self):
        return self.num_heads * self.head_dim


def _act(cfg, x):
    if cfg.activation == "relu":
        return jax.nn.relu(x)
    # exact (erf) gelu to match nn.functional.gelu's default
    return jax.nn.gelu(x, approximate=False)


def _ln(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def _mm(cfg, x, w, scale):
    """x @ w with optional weight-only int8 dequant (scale per out-chan).

    XLA fuses the dequant into the dot — the weight is read from HBM as
    int8 (the reference's `weight_only_linear_kernel.h` capability)."""
    if scale is None:
        return jnp.matmul(x, w.astype(x.dtype))
    qmax = float(2 ** (cfg.quant_bits - 1) - 1)
    wf = w.astype(x.dtype) * (scale.astype(x.dtype) / qmax)
    return jnp.matmul(x, wf)


def _maybe_psum(cfg, x):
    if cfg.mp_axis is not None:
        return jax.lax.psum(x, cfg.mp_axis)
    return x


def _dropout(cfg, x, key, training):
    if not training or cfg.dropout_rate <= 0.0 or key is None:
        return x
    keep = 1.0 - cfg.dropout_rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def _lora_delta(x, a, b, oh):
    """Multi-LoRA delta for one hooked projection (serving mixed step,
    `serving/adapters.py`): `x [..., d_in]` with T total rows, slot
    tensors `a [K, d_in, r]` / `b [K, r, d_out]` (adapter slot 0 is
    the all-zero NULL adapter), `oh [T, K]` the per-token adapter
    one-hot. Returns `x @ a[aid] @ b[aid]` per token, fixed-shape:

    * the A contraction computes ALL K rank-r projections
      (`td,kdr->tkr` — K*T*d*r flops, tiny next to the dense d x d
      matmul for K*r << d) and the one-hot selects — no `[T, d, r]`
      gather is ever materialized;
    * the B side masks the selected `[T, r]` back through the one-hot
      (`[T, K, r]`, small) so the contraction collapses k and r at
      once.

    fp32 accumulation, cast back at the end. One-hot rows are exact
    {0,1}, so a token's delta is bit-independent of how many adapter
    slots the engine was built with — and the null slot's delta is
    exactly 0.0, which keeps slot-0 tokens token-identical to an
    adapter-free engine (alpha/r scaling is folded into B at load
    time)."""
    lead = x.shape[:-1]
    xf = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
    ohf = oh.astype(jnp.float32)
    ya = jnp.einsum("td,kdr->tkr", xf, a.astype(jnp.float32))
    y = jnp.einsum("tkr,tk->tr", ya, ohf)
    yk = y[:, None, :] * ohf[:, :, None]                  # [T, K, r]
    z = jnp.einsum("tkr,krd->td", yk, b.astype(jnp.float32))
    return z.reshape(*lead, b.shape[-1]).astype(x.dtype)


def _ffn_dense(cfg, pl, h, lora_oh=None):
    f = _mm(cfg, h, pl["ffn1_w"], pl.get("ffn1_s"))
    if lora_oh is not None and "lora_ffn1_a" in pl:
        f = f + _lora_delta(h, pl["lora_ffn1_a"], pl["lora_ffn1_b"],
                            lora_oh)
    f = f + pl["ffn1_b"].astype(f.dtype)
    f = _act(cfg, f)
    g = _mm(cfg, f, pl["ffn2_w"], pl.get("ffn2_s"))
    if lora_oh is not None and "lora_ffn2_a" in pl:
        # row-parallel under TP: A holds this shard's F/tp slice, so
        # the delta is a partial sum that joins the SAME psum the base
        # matmul already pays (the _maybe_psum right below)
        g = g + _lora_delta(f, pl["lora_ffn2_a"], pl["lora_ffn2_b"],
                            lora_oh)
    g = _maybe_psum(cfg, g)
    return g + pl["ffn2_b"].astype(g.dtype)


def _moe_bits(cfg):
    """Effective expert-stack weight-only bits: the `moe_quant_bits`
    override when set (int4/int8 experts under an int8 — or float —
    attention stack), else the stack-wide `quant_bits`."""
    return cfg.moe_quant_bits or cfg.quant_bits


def _grouped_path_enabled(cfg, pl):
    """True when the expert FFN matmuls run the Pallas grouped-expert
    kernel (ops.pallas.grouped_matmul) instead of the one-hot einsum
    oracle — TPU backend (or kernel-test interpret mode) with
    MXU-alignable feature axes; `PADDLE_TPU_GROUPED_MATMUL=0` or a
    CPU backend keeps the reference path. Static at trace time.
    int4-packed expert weights hold HALF their logical contraction
    rows, so the alignment check doubles them back first."""
    from ...ops.pallas import grouped_matmul as _gmm
    packed = _moe_bits(cfg) == 4 and pl.get("ffn1_s") is not None
    d_in = pl["ffn1_w"].shape[-2] * (2 if packed else 1)
    d_ff = pl["ffn1_w"].shape[-1]
    return _gmm.grouped_matmul_enabled(d_in, d_ff)


def _expert_matmuls(cfg, pl, expert_in):
    """The two stacked expert contractions ([E_loc, C', D] capacity
    buffers -> expert outputs) with weight-only dequant fused in —
    grouped Pallas kernel when enabled, einsum oracle otherwise.
    Expert quantization bits come from `_moe_bits` (int4 experts use
    qmax=7 and the nibble-packed kernel/dequant)."""
    cd = expert_in.dtype
    if _grouped_path_enabled(cfg, pl):
        from ...ops.pallas.grouped_matmul import grouped_expert_matmul
        qmax = float(2 ** (_moe_bits(cfg) - 1) - 1) if _moe_bits(cfg) \
            else 127.0
        f = grouped_expert_matmul(expert_in, pl["ffn1_w"],
                                  pl.get("ffn1_s"), qmax=qmax,
                                  out_dtype=cd)
        f = _act(cfg, f + pl["ffn1_b"][:, None, :].astype(cd))
        return grouped_expert_matmul(f, pl["ffn2_w"],
                                     pl.get("ffn2_s"), qmax=qmax,
                                     out_dtype=cd)
    f = jnp.einsum("ecd,edf->ecf", expert_in,
                   _deq(cfg, pl["ffn1_w"], pl.get("ffn1_s"), cd))
    f = _act(cfg, f + pl["ffn1_b"][:, None, :].astype(cd))
    return jnp.einsum("ecf,efd->ecd", f,
                      _deq(cfg, pl["ffn2_w"], pl.get("ffn2_s"), cd))


def _expert_ffn(cfg, pl, expert_in):
    """Stacked expert FFN on [E_loc, C', D] capacity buffers (weight-
    only dequant fused into the matmuls when scales are present)."""
    cd = expert_in.dtype
    eout = _expert_matmuls(cfg, pl, expert_in)
    return eout + pl["ffn2_b"][:, None, :].astype(cd)


def _ffn_moe(cfg, pl, h):
    """Top-k capacity-factor MoE FFN (parallel.moe_utils routing core).

    `h` [B, S, D]. Experts stacked [E, D, F] / [E, F, D] (locally
    `[E_loc]` when ep_axis is set: tokens sharded over ep_axis, the
    [E, C, D] dispatch tensors ride all_to_all to the expert owners —
    the training-style exchange). Returns (out, balance_aux_loss);
    capacity-dropped (token, choice) pairs contribute 0 and the
    caller's residual carries them."""
    from ...parallel import moe_utils
    B, S, D = h.shape
    T = B * S
    E = cfg.num_experts
    cd = h.dtype
    xt = h.reshape(T, D)
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                        pl["gate_w"].astype(jnp.float32))
    C = moe_utils.expert_capacity(T, E, cfg.moe_topk,
                                  cfg.capacity_factor)
    axes = (cfg.ep_axis,) if (cfg.ep_axis is not None
                              and cfg.ep_size > 1) else None
    grouped = _grouped_path_enabled(cfg, pl)
    r = moe_utils.top_k_routing(logits, cfg.moe_topk, C, axes=axes,
                                dtype=cd, build_masks=not grouped)
    if grouped:
        dispatched = moe_utils.dispatch_tokens_indexed(
            xt.astype(cd), r.plan, E, C)
    else:
        dispatched = moe_utils.dispatch_tokens(xt.astype(cd), r.plan)
    if axes:
        expert_in = moe_utils.all_to_all_dispatch(dispatched,
                                                  cfg.ep_axis,
                                                  cfg.ep_size)
    else:
        expert_in = dispatched
    eout = _expert_ffn(cfg, pl, expert_in)
    if axes:
        eout = moe_utils.all_to_all_combine(eout, cfg.ep_axis,
                                            cfg.ep_size)
    if grouped:
        out = moe_utils.combine_tokens_indexed(eout, r.plan)
    else:
        out = moe_utils.combine_tokens(eout, r.plan)
    return out.reshape(B, S, D), r.balance_loss


def _ffn_moe_tokens(cfg, pl, h, valid):
    """Serving-side MoE FFN on the flat `[T, D]` mixed-step token axis.

    Per-token top-k routing with FIXED expert-capacity slots: T is the
    engine's static token budget, so the `[E, C, D]` dispatch tensors
    are compile-time constants — routing churn, capacity overflow and
    padding slots never change a compiled shape (the one-compile
    rule). `valid` [T] masks padding tokens out of routing, capacity
    claims and statistics. Overflowed (token, choice) pairs contribute
    0 and the layer's residual connection carries the token through —
    degradation, never a recompile.

    Expert parallelism (`cfg.ep_axis` + `cfg.ep_size > 1`, the
    TPServingEngine TP x EP mesh): the token set is REPLICATED across
    shards, so dispatch degenerates from all_to_all to slicing this
    rank's resident experts out of the (identical) dispatch tensor;
    each shard runs E/ep experts at capacity C and the combine psums
    partial mixtures over the ep axis. Expert FFN matmuls are
    row-parallel over `cfg.mp_axis` exactly like `_ffn_dense`.

    Returns (out [T, D], stats {counts [E], dropped, aux}) — stats are
    identical on every shard (replicated tokens), so no psum."""
    from ...parallel import moe_utils
    T, D = h.shape
    E = cfg.num_experts
    cd = h.dtype
    logits = jnp.einsum("td,de->te", h.astype(jnp.float32),
                        pl["gate_w"].astype(jnp.float32))
    C = moe_utils.expert_capacity(T, E, cfg.moe_topk,
                                  cfg.capacity_factor)
    grouped = _grouped_path_enabled(cfg, pl)
    r = moe_utils.top_k_routing(logits, cfg.moe_topk, C, valid=valid,
                                dtype=cd, build_masks=not grouped)
    ep = cfg.ep_size if cfg.ep_axis is not None else 1
    E_loc = E // ep
    rank = jax.lax.axis_index(cfg.ep_axis) if ep > 1 else 0
    if grouped:
        # index-based dispatch (ISSUE 11): the capacity assignment is
        # ONE [E, C] token-index table + a gather — no [T, k, C] /
        # [T, k, E] one-hot is ever materialized — and the expert
        # matmuls run the grouped Pallas kernel on the dense [E_loc,
        # C, D] buffers. Under ep the shard slices its resident
        # experts' index rows before gathering, exactly like the
        # e_oh slice on the einsum path.
        tos = moe_utils.dispatch_indices(r.plan, E, C)
        if ep > 1:
            tos = jax.lax.dynamic_slice_in_dim(tos, rank * E_loc,
                                               E_loc, axis=0)
        local_in = moe_utils.dispatch_tokens_indexed(
            h, r.plan, E_loc, C, indices=tos)
        eout = _expert_matmuls(cfg, pl, local_in)
        eout = _maybe_psum(cfg, eout)
        eout = eout + pl["ffn2_b"][:, None, :].astype(cd)
        out = moe_utils.combine_tokens_indexed(
            eout, r.plan, e_offset=rank * E_loc, num_local=E_loc)
    else:
        if ep > 1:
            # slice this shard's resident experts out of the one-hot
            # FIRST and dispatch only their [E/ep, C, D] buffers —
            # dispatching all E and slicing after would spend ep-times
            # the einsum work
            e_oh_loc = jax.lax.dynamic_slice_in_dim(
                r.plan.e_oh, rank * E_loc, E_loc, axis=2)
        else:
            e_oh_loc = r.plan.e_oh
        local_in = moe_utils.dispatch_tokens(h, r.plan, e_oh=e_oh_loc)
        f = jnp.einsum("ecd,edf->ecf", local_in,
                       _deq(cfg, pl["ffn1_w"], pl.get("ffn1_s"), cd))
        f = _act(cfg, f + pl["ffn1_b"][:, None, :].astype(cd))
        eout = jnp.einsum("ecf,efd->ecd", f,
                          _deq(cfg, pl["ffn2_w"], pl.get("ffn2_s"), cd))
        # row-parallel over mp (each shard holds an F/tp slice), bias
        # once after the reduction
        eout = _maybe_psum(cfg, eout)
        eout = eout + pl["ffn2_b"][:, None, :].astype(cd)
        out = jnp.einsum("tkc,tke,ecd->td", r.plan.comb, e_oh_loc, eout)
    if ep > 1:
        out = jax.lax.psum(out, cfg.ep_axis)
    stats = {"counts": r.plan.counts, "dropped": r.plan.dropped,
             "aux": r.balance_loss}
    return out, stats


def _deq(cfg, w, scale, dtype):
    """Expert-stack weight dequant for the einsum path (`_deq` is only
    ever applied to ffn1_w/ffn2_w expert weights, so its bits come
    from `_moe_bits`); int4-packed weights unpack first."""
    if scale is None:
        return w.astype(dtype)
    bits = _moe_bits(cfg)
    if bits == 4:
        from ...ops.pallas.grouped_matmul import unpack_int4
        w = unpack_int4(w, axis=-2)
    qmax = float(2 ** (bits - 1) - 1)
    return w.astype(dtype) * (scale[:, None, :].astype(dtype) / qmax)


def _qkv(cfg, pl, h, lora_oh=None):
    """h [B, S, D] -> q, k, v each [B, S, H, Dh] (H is the local head
    count under TP). `lora_oh` [B*S, K] adds the multi-LoRA delta to
    the fused projection pre-bias (serving mixed step; B is replicated
    there, so the sharded lora_qkv_b — shard-major-permuted like
    qkv_w — lands each shard's head slice)."""
    B, S, _ = h.shape
    qkv = _mm(cfg, h, pl["qkv_w"], pl.get("qkv_s"))
    if lora_oh is not None and "lora_qkv_a" in pl:
        qkv = qkv + _lora_delta(h, pl["lora_qkv_a"], pl["lora_qkv_b"],
                                lora_oh)
    qkv = qkv + pl["qkv_b"].astype(qkv.dtype)
    H = cfg.num_heads
    qkv = qkv.reshape(B, S, 3, H, cfg.head_dim)
    return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]


def _sdp(q, k, v, mask):
    """softmax(q k^T / sqrt(d) + mask) v, f32 accumulation.

    q [B, Sq, H, Dh]; k/v [B, Sk, H, Dh]; mask broadcastable to
    [B, H, Sq, Sk] (additive, -inf for disallowed)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        logits = logits + mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _sdp_chunked(q, k, v, mask, q_block=256):
    """Query-block-chunked attention for long prefills: never
    materializes the [B, H, S, S] logits (2.1GB f32 per layer at
    S=1024, B=32 — enough to OOM the chip); peak temp is
    [B, H, q_block, S]."""
    B, S, H, Dh = q.shape
    scale = 1.0 / math.sqrt(Dh)
    nb = S // q_block

    def blk(_, i):
        qs = i * q_block
        qb = jax.lax.dynamic_slice_in_dim(q, qs, q_block, axis=1)
        lg = jnp.einsum("bqhd,bkhd->bhqk", qb, k).astype(jnp.float32)
        lg = lg * scale
        if mask is not None:
            mb = jax.lax.dynamic_slice_in_dim(
                jnp.broadcast_to(mask, mask.shape[:2] + (S, S)),
                qs, q_block, axis=2)
            lg = lg + mb.astype(jnp.float32)
        p = jax.nn.softmax(lg, axis=-1).astype(q.dtype)
        return _, jnp.einsum("bhqk,bkhd->bqhd", p, v)

    _, obs = jax.lax.scan(blk, 0, jnp.arange(nb))
    return jnp.moveaxis(obs, 0, 1).reshape(B, S, H, Dh)


def _causal_mask(S, dtype=jnp.float32):
    i = jnp.arange(S)[:, None]
    j = jnp.arange(S)[None, :]
    return jnp.where(j <= i, 0.0, -1e9).astype(dtype)[None, None]


def _write_cache(cache_l, k, v, start):
    """cache_l = (k_cache [B, H, Dh, S_max], v_cache [B, H, S_max, Dh]);
    k/v fresh [B, S, H, Dh]; start scalar.

    K and V live in SEPARATE arrays, each in the layout its attention
    einsum prefers: `q·K` contracts Dh (sublanes) with S on lanes —
    `[.., Dh, S]` tiles pad-free; `p·V` contracts S (sublanes) with Dh
    on lanes — `[.., S, Dh]`. One interleaved `[2, ...]` tensor forces
    XLA to pick a single compromise layout and (measured on a 350M
    config) relayout-copy the ENTIRE cache every decode step."""
    ck, cv = cache_l
    ck = jax.lax.dynamic_update_slice(
        ck, k.transpose(0, 2, 3, 1).astype(ck.dtype), (0, 0, 0, start))
    cv = jax.lax.dynamic_update_slice(
        cv, v.transpose(0, 2, 1, 3).astype(cv.dtype), (0, 0, start, 0))
    return ck, cv


def _layer_body(cfg, x, pl, cache_l, mode, step, seq_lens, attn_mask,
                drop_keys, training):
    """One transformer layer. cache_l [2, B, S_max, H, Dh] or None."""
    residual = x
    h = _ln(x, pl["ln_s"], pl["ln_b"], cfg.epsilon) \
        if cfg.normalize_before else x
    q, k, v = _qkv(cfg, pl, h)
    B, S = q.shape[0], q.shape[1]
    new_cache = cache_l
    if mode == "forward":
        mask = _causal_mask(S) if attn_mask is None else attn_mask
        attn = _sdp(q, k, v, mask)
    elif mode == "prefill":
        mask = _causal_mask(S)
        if seq_lens is not None:
            key_valid = jnp.arange(S)[None, :] < seq_lens[:, None]
            mask = mask + jnp.where(key_valid, 0.0,
                                    -1e9)[:, None, None, :]
        if attn_mask is not None:
            mask = mask + attn_mask
        if S >= 512 and S % 256 == 0:
            attn = _sdp_chunked(q, k, v, mask)
        else:
            attn = _sdp(q, k, v, mask)
        new_cache = _write_cache(cache_l, k, v, 0)
    else:
        # decode is unrolled (_decode_stack), never scanned through here
        raise AssertionError("decode mode is handled by _decode_stack")
    attn = attn.reshape(B, S, cfg.num_heads * cfg.head_dim)
    out = _mm(cfg, attn, pl["out_w"], pl.get("out_s"))
    out = _maybe_psum(cfg, out)
    out = out + pl["out_b"].astype(out.dtype)
    out = _dropout(cfg, out, drop_keys[0] if drop_keys else None, training)
    x = residual + out
    if not cfg.normalize_before:
        x = _ln(x, pl["ln_s"], pl["ln_b"], cfg.epsilon)
    residual = x
    h = _ln(x, pl["ffn_ln_s"], pl["ffn_ln_b"], cfg.epsilon) \
        if cfg.normalize_before else x
    aux = jnp.zeros((), jnp.float32)
    if cfg.num_experts > 0:
        f, aux = _ffn_moe(cfg, pl, h)
    else:
        f = _ffn_dense(cfg, pl, h)
    f = _dropout(cfg, f, drop_keys[1] if drop_keys else None, training)
    x = residual + f
    if not cfg.normalize_before:
        x = _ln(x, pl["ffn_ln_s"], pl["ffn_ln_b"], cfg.epsilon)
    return x, new_cache, aux


def _decode_layer(cfg, x, pl, ckf, cvf, li, step, valid):
    """One decode/verify layer operating on the FULL stacked caches.

    `x` is [B, K, D]: K == 1 is plain decode, K > 1 is the multi-token
    speculative *verify* step — K consecutive positions starting at
    `step` are written and scored in one pass (each query attends keys
    at positions <= its own, so draft token j sees drafts 0..j-1 —
    exactly the sequential-greedy semantics).

    The fresh K/V columns are written straight into `ckf`/`cvf` at
    (layer, step..step+K-1) via dynamic_update_slice (scalar step) or a
    batched scatter (per-row steps) — O(K columns) writes; the layer
    reads fuse into the attention einsums."""
    B, K = x.shape[0], x.shape[1]
    residual = x
    h = _ln(x, pl["ln_s"], pl["ln_b"], cfg.epsilon) \
        if cfg.normalize_before else x
    q, k, v = _qkv(cfg, pl, h)                      # [B, K, H, Dh]
    if step.ndim == 0:
        kcol = k.transpose(0, 2, 3, 1)[None].astype(ckf.dtype)
        vcol = v.transpose(0, 2, 1, 3)[None].astype(cvf.dtype)
        ckf = jax.lax.dynamic_update_slice(ckf, kcol, (li, 0, 0, 0, step))
        cvf = jax.lax.dynamic_update_slice(cvf, vcol, (li, 0, 0, step, 0))
    else:
        # per-row positions: scatter K columns per row into the full
        # cache (a gather + whole-slice rewrite would move the entire
        # layer cache per token)
        rows = jnp.arange(B)[:, None]
        pos = step[:, None] + jnp.arange(K)[None, :]      # [B, K]
        # advanced indices (li, rows, pos) broadcast to [B, K] and land
        # first: both targets index as [B, K, H, Dh], matching k/v
        ckf = ckf.at[li, rows, :, :, pos].set(k.astype(ckf.dtype))
        cvf = cvf.at[li, rows, :, pos, :].set(v.astype(cvf.dtype))
    scale = 1.0 / math.sqrt(q.shape[-1])
    ck = jax.lax.dynamic_index_in_dim(ckf, li, 0, keepdims=False)
    cv = jax.lax.dynamic_index_in_dim(cvf, li, 0, keepdims=False)
    ck = ck.astype(q.dtype)                     # [B, H, Dh, S_max]
    cv = cv.astype(q.dtype)                     # [B, H, S_max, Dh]
    logits = jnp.einsum("bkhd,bhds->bhks", q, ck)
    logits = logits.astype(jnp.float32) * scale
    logits = jnp.where(valid[:, None], logits, -1e9)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    attn = jnp.einsum("bhks,bhsd->bkhd", p, cv)
    attn = attn.reshape(B, K, cfg.num_heads * cfg.head_dim)
    out = _mm(cfg, attn, pl["out_w"], pl.get("out_s"))
    out = _maybe_psum(cfg, out)
    out = out + pl["out_b"].astype(out.dtype)
    x = residual + out
    if not cfg.normalize_before:
        x = _ln(x, pl["ln_s"], pl["ln_b"], cfg.epsilon)
    residual = x
    h = _ln(x, pl["ffn_ln_s"], pl["ffn_ln_b"], cfg.epsilon) \
        if cfg.normalize_before else x
    aux = jnp.zeros((), jnp.float32)
    if cfg.num_experts > 0:
        f, aux = _ffn_moe(cfg, pl, h)
    else:
        f = _ffn_dense(cfg, pl, h)
    x = residual + f
    if not cfg.normalize_before:
        x = _ln(x, pl["ffn_ln_s"], pl["ffn_ln_b"], cfg.epsilon)
    return x, ckf, cvf, aux


def _decode_stack(cfg, params, x, cache, step):
    """Run the decode/verify stack as ONE `lax.scan` over layers.

    The round-5 roofline analysis (docs/decode_int8_analysis.md) showed
    the unrolled decode step — 24 layers x ~15 tiny [B, 1, D] ops, ~360
    dispatched micro-ops per token — running ~2x above its HBM roofline
    at B<=8: latency-bound, not bandwidth-bound. Scanning the stacked
    weights collapses the step into one compiled loop body (the same
    shape discipline the training stack and the serving mixed step
    already use).

    The full stacked caches ride in the scan CARRY (aliased in place by
    XLA) and each iteration touches only its own layer: the K/V column
    write is a dynamic_update_slice / scatter at (layer, step..step+K-1)
    and the attention read is a dynamic_index_in_dim of that layer's
    slice. Passing per-layer cache slices as scan xs/ys instead would
    re-stack the whole cache every step (measured ~4x the useful
    traffic on a 350M config — the reason the old stack was unrolled).

    `PADDLE_TPU_DECODE_UNROLL=1` restores the unrolled stack for A/B
    measurement. The flag is read at TRACE time and is not part of any
    jit cache key: set it before the process's first decode trace (run
    each A/B side in its own process) — toggling it after an executable
    is cached has no effect."""
    import os
    ckf, cvf = cache
    B, K = x.shape[0], x.shape[1]
    S_max = ckf.shape[-1]
    offs = jnp.arange(K)
    if step.ndim == 0:
        last = step + offs                                  # [K]
        valid = jnp.arange(S_max)[None, None, :] <= last[None, :, None]
    else:
        last = step[:, None] + offs[None, :]                # [B, K]
        valid = jnp.arange(S_max)[None, None, :] <= last[:, :, None]
    if os.environ.get("PADDLE_TPU_DECODE_UNROLL"):
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.num_layers):
            pli = {kk: vv[i] for kk, vv in params.items()}
            x, ckf, cvf, aux = _decode_layer(cfg, x, pli, ckf, cvf,
                                             jnp.int32(i), step, valid)
            aux_total = aux_total + aux
        return x, (ckf, cvf), aux_total

    def body(carry, xs):
        h, ckf, cvf = carry
        pli, li = xs
        h, ckf, cvf, aux = _decode_layer(cfg, h, pli, ckf, cvf, li,
                                         step, valid)
        return (h, ckf, cvf), aux

    (x, ckf, cvf), auxs = jax.lax.scan(
        body, (x, ckf, cvf),
        (params, jnp.arange(cfg.num_layers)))
    return x, (ckf, cvf), jnp.sum(auxs)


def _run_stack(cfg, params, x, cache, mode, step, seq_lens, attn_mask,
               rng_key, training):
    """Run the layer stack: `lax.scan` for forward/prefill (one
    compilation regardless of depth), unrolled for decode (see
    `_decode_layer`). `params` dict of [L, ...] arrays; `cache` a
    (k, v) pair of stacked arrays or None. Returns
    (x, new_cache, aux_sum)."""
    if mode == "decode":
        return _decode_stack(cfg, params, x, cache, step)
    L = cfg.num_layers
    if rng_key is not None and training and cfg.dropout_rate > 0:
        rng_key = jnp.asarray(rng_key)
        keys = jax.random.split(rng_key, L * 2).reshape(
            (L, 2) + rng_key.shape)
    else:
        keys = jnp.zeros((L, 0), jnp.uint32)
    if cache is None:
        cache = (jnp.zeros((L, 0), x.dtype), jnp.zeros((L, 0), x.dtype))

    def body(h, xs):
        pl, ck_l, cv_l, kk = xs
        dk = (kk[0], kk[1]) if kk.size else None
        h, new_c, aux = _layer_body(cfg, h, pl,
                                    (ck_l, cv_l) if ck_l.size else None,
                                    mode, step, seq_lens, attn_mask, dk,
                                    training)
        if new_c is None:
            new_c = (jnp.zeros((0,), h.dtype), jnp.zeros((0,), h.dtype))
        return h, (new_c, aux)

    x, (new_cache, auxs) = jax.lax.scan(
        body, x, (params, cache[0], cache[1], keys))
    return x, new_cache, jnp.sum(auxs)


# ---------------------------------------------------------------------------
# simple fused layers (real implementations, not shims)
# ---------------------------------------------------------------------------

class FusedBiasDropoutResidualLayerNorm(Layer):
    """ln(residual + dropout(x + bias)) — ref `fused_transformer.py:86`.
    XLA fuses the chain; the class carries the ln params + bias."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr,
            default_initializer=_ones_init)
        self.ln_bias = self.create_parameter(
            [embed_dim], is_bias=True)

    def forward(self, x, residual):
        y = x + self.linear_bias
        y = F.dropout(y, self.dropout_rate, training=self.training)
        return F.layer_norm(residual + y, [self.embed_dim], self.ln_scale,
                            self.ln_bias, self._epsilon)


def _ones_init(shape, dtype):
    return jnp.ones(shape, dtype)


class FusedMultiHeadAttention(Layer):
    """Fused-QKV attention — ref `fused_transformer.py:196`. One
    [D, 3D] projection; attention runs through the framework's
    flash/XLA path."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            [embed_dim, 3 * embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3 * embed_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr,
            default_initializer=_ones_init)
        self.pre_ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr,
            default_initializer=_ones_init)
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if cache is not None:
            raise NotImplementedError(
                "FusedMultiHeadAttention does not implement the reference "
                "cache_kv incremental-decode protocol; use "
                "FusedMultiTransformer's caches/time_step protocol for "
                "decode (incubate.nn.fused_transformer.FusedMultiTransformer)")
        from ...ops import manipulation as manip
        x = as_tensor(query)
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, [self.embed_dim], self.pre_ln_scale,
                             self.pre_ln_bias, self._epsilon)
        qkv = F.linear(x, self.qkv_weight, self.qkv_bias)
        b, s = qkv.shape[0], qkv.shape[1]
        qkv = manip.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q = manip.squeeze(qkv[:, :, 0:1], axis=2)
        k = manip.squeeze(qkv[:, :, 1:2], axis=2)
        v = manip.squeeze(qkv[:, :, 2:3], axis=2)
        if attn_mask is not None:
            attn_mask = as_tensor(attn_mask)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            training=self.training)
        out = manip.reshape(out, [b, s, self.embed_dim])
        out = F.linear(out, self.linear_weight, self.linear_bias)
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = F.layer_norm(out, [self.embed_dim], self.ln_scale,
                               self.ln_bias, self._epsilon)
        return out


class FusedFeedForward(Layer):
    """ref `fused_transformer.py:489` — pre/post-LN FFN with residual."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.d_model = d_model
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        self.activation = activation
        self._epsilon = epsilon
        self.linear1 = Linear(d_model, dim_feedforward,
                              linear1_weight_attr, linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model,
                              linear2_weight_attr, linear2_bias_attr)
        self.ln1_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr, default_initializer=_ones_init)
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], attr=ln2_scale_attr, default_initializer=_ones_init)
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, src, cache=None):
        x = as_tensor(src)
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, [self.d_model], self.ln1_scale,
                             self.ln1_bias, self._epsilon)
        act = F.relu if self.activation == "relu" else F.gelu
        h = act(self.linear1(x))
        h = F.dropout(h, self.act_dropout_rate, training=self.training)
        h = self.linear2(h)
        h = F.dropout(h, self.dropout_rate, training=self.training)
        out = residual + h
        if not self.normalize_before:
            out = F.layer_norm(out, [self.d_model], self.ln2_scale,
                               self.ln2_bias, self._epsilon)
        return out


class FusedTransformerEncoderLayer(Layer):
    """ref `fused_transformer.py:720` — attention + FFN blocks above."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout_rate = dropout_rate if attn_dropout_rate is None \
            else attn_dropout_rate
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before,
            qkv_weight_attr=weight_attr, qkv_bias_attr=bias_attr,
            linear_weight_attr=weight_attr, linear_bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
            linear1_weight_attr=weight_attr, linear1_bias_attr=bias_attr,
            linear2_weight_attr=weight_attr, linear2_bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


# ---------------------------------------------------------------------------
# FusedMultiTransformer — the serving decode stack
# ---------------------------------------------------------------------------

_PARAM_ORDER = ("ln_s", "ln_b", "qkv_w", "qkv_b", "out_w", "out_b",
                "ffn_ln_s", "ffn_ln_b", "gate_w",
                "ffn1_w", "ffn1_b", "ffn2_w", "ffn2_b",
                "qkv_s", "out_s", "ffn1_s", "ffn2_s")


class FusedMultiTransformer(Layer):
    """Multi-layer GPT decoder stack with fixed-shape KV cache — ref
    `fused_transformer.py:1016` + `fused_multi_transformer_op.cu`.

    Modes (`forward(src, attn_mask, caches, seq_lens, time_step)`):
      * no cache      — causal encoder pass (training / scoring)
      * cache, step None — prefill: full pass + cache write at [0, S)
      * cache + step  — decode: src [B, 1, D], write at `step`, attend
        over cache[: step+1]; shapes static, so jit compiles once.

    Weights are stacked `[num_layers, ...]` parameters (see module doc).
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, qkv_weight_attrs=None,
                 qkv_bias_attrs=None, linear_weight_attrs=None,
                 linear_bias_attrs=None, ffn_ln_scale_attrs=None,
                 ffn_ln_bias_attrs=None, ffn1_weight_attrs=None,
                 ffn1_bias_attrs=None, ffn2_weight_attrs=None,
                 ffn2_bias_attrs=None, epsilon=1e-5, num_layers=-1,
                 nranks=1, trans_qkvw=True, ring_id=-1, name=None,
                 dtype=None):
        super().__init__()
        if num_layers < 0 and isinstance(qkv_weight_attrs, (list, tuple)):
            num_layers = len(qkv_weight_attrs)
        assert num_layers > 0, "num_layers must be given"
        _ignored_attrs = {
            "ln_scale_attrs": ln_scale_attrs, "ln_bias_attrs": ln_bias_attrs,
            "qkv_bias_attrs": qkv_bias_attrs,
            "linear_weight_attrs": linear_weight_attrs,
            "linear_bias_attrs": linear_bias_attrs,
            "ffn_ln_scale_attrs": ffn_ln_scale_attrs,
            "ffn_ln_bias_attrs": ffn_ln_bias_attrs,
            "ffn1_weight_attrs": ffn1_weight_attrs,
            "ffn1_bias_attrs": ffn1_bias_attrs,
            "ffn2_weight_attrs": ffn2_weight_attrs,
            "ffn2_bias_attrs": ffn2_bias_attrs}
        _passed = [k for k, v in _ignored_attrs.items() if v is not None]
        if qkv_weight_attrs is not None:
            _passed.append("qkv_weight_attrs")
        if _passed:
            import warnings
            warnings.warn(
                "FusedMultiTransformer uses stacked [num_layers, ...] "
                "parameters; per-layer attrs are not applied "
                f"(ignored: {', '.join(sorted(_passed))}). The stacked "
                "qkv layout is [L, D, 3*H*Dh] (the per-layer "
                "trans_qkvw=False layout) regardless of `trans_qkvw`. "
                "Load reference per-layer checkpoints through "
                "GPTForGeneration.from_pretraining, or assign the stacked "
                "parameters directly.", stacklevel=2)
        assert embed_dim % num_heads == 0
        # TP: local shard sizes (ref divides heads/ffn by nranks)
        assert num_heads % nranks == 0 and dim_feedforward % nranks == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads // nranks
        self.head_dim = embed_dim // num_heads
        self.dim_feedforward = dim_feedforward // nranks
        self.num_layers = num_layers
        self.nranks = nranks
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.activation = activation
        self._epsilon = epsilon
        self._mp_axis = None     # set by TP wrappers for in-shard psum
        L, D = num_layers, embed_dim
        Hl = self.num_heads
        Fl = self.dim_feedforward
        inner = Hl * self.head_dim
        self.ln_scales = self.create_parameter(
            [L, D], default_initializer=_ones_init)
        self.ln_biases = self.create_parameter([L, D], is_bias=True)
        self.qkv_weights = self.create_parameter(
            [L, D, 3 * inner], default_initializer=_scaled_normal(D, "qkv"))
        self.qkv_biases = self.create_parameter([L, 3 * inner],
                                                is_bias=True)
        self.linear_weights = self.create_parameter(
            [L, inner, D], default_initializer=_scaled_normal(inner, "out"))
        self.linear_biases = self.create_parameter([L, D], is_bias=True)
        self.ffn_ln_scales = self.create_parameter(
            [L, D], default_initializer=_ones_init)
        self.ffn_ln_biases = self.create_parameter([L, D], is_bias=True)
        self.ffn1_weights = self.create_parameter(
            [L, D, Fl], default_initializer=_scaled_normal(D, "ffn1"))
        self.ffn1_biases = self.create_parameter([L, Fl], is_bias=True)
        self.ffn2_weights = self.create_parameter(
            [L, Fl, D], default_initializer=_scaled_normal(Fl, "ffn2"))
        self.ffn2_biases = self.create_parameter([L, D], is_bias=True)

    # -- config / params ----------------------------------------------------
    def _cfg(self, mp_axis=None, ep_axis=None, training=False):
        return _MTConfig(
            num_layers=self.num_layers, num_heads=self.num_heads,
            head_dim=self.head_dim, dim_ff=self.dim_feedforward,
            epsilon=self._epsilon, normalize_before=self.normalize_before,
            activation=self.activation,
            dropout_rate=self.dropout_rate if training else 0.0,
            quant_bits=getattr(self, "_quant_bits", 0),
            moe_quant_bits=getattr(self, "_moe_quant_bits", 0),
            num_experts=getattr(self, "_num_experts", 0),
            moe_topk=getattr(self, "_moe_topk", 2),
            capacity_factor=getattr(self, "_capacity_factor", 1.25),
            mp_axis=mp_axis or self._mp_axis, ep_axis=ep_axis)

    def _param_tensors(self):
        """Ordered (names, tensors) matching `_PARAM_ORDER` (missing
        entries skipped)."""
        m = {"ln_s": self.ln_scales, "ln_b": self.ln_biases,
             "qkv_w": self.qkv_weights, "qkv_b": self.qkv_biases,
             "out_w": self.linear_weights, "out_b": self.linear_biases,
             "ffn_ln_s": self.ffn_ln_scales,
             "ffn_ln_b": self.ffn_ln_biases,
             "ffn1_w": self.ffn1_weights, "ffn1_b": self.ffn1_biases,
             "ffn2_w": self.ffn2_weights, "ffn2_b": self.ffn2_biases}
        for extra in ("gate_w", "qkv_s", "out_s", "ffn1_s", "ffn2_s"):
            t = getattr(self, "_" + extra, None)
            if t is not None:
                m[extra] = t
        names = [n for n in _PARAM_ORDER if n in m]
        return names, [m[n] for n in names]

    # -- cache --------------------------------------------------------------
    def gen_cache(self, batch_size, max_seq_len, dtype=None):
        """(k_cache [L, B, H, Dh, S_max], v_cache [L, B, H, S_max, Dh])
        zeros — stacked over layers for `lax.scan`, K/V split so each
        attention einsum reads its preferred TPU layout (see
        `_write_cache`; the reference returns a python list of
        `[2, B, H, S_max, Dh]` per layer). Pick `max_seq_len` as a
        multiple of 128 for a pad-free K layout."""
        dtype = dtype or "float32"
        L, B = self.num_layers, batch_size
        H, Dh = self.num_heads, self.head_dim
        return (Tensor(jnp.zeros((L, B, H, Dh, max_seq_len),
                                 jnp.dtype(dtype))),
                Tensor(jnp.zeros((L, B, H, max_seq_len, Dh),
                                 jnp.dtype(dtype))))

    @staticmethod
    def _unpack_caches(caches):
        """Accept the (k, v) pair from gen_cache (Tensors or arrays)."""
        k, v = caches
        k = k._data if isinstance(k, Tensor) else jnp.asarray(k)
        v = v._data if isinstance(v, Tensor) else jnp.asarray(v)
        return k, v

    # -- forward ------------------------------------------------------------
    def forward(self, src, attn_mask=None, caches=None, seq_lens=None,
                beam_offset=None, time_step=None):
        if beam_offset is not None:
            raise NotImplementedError("beam_offset: use generate()'s "
                                      "batched beams instead")
        src = as_tensor(src)
        cfg = self._cfg(training=self.training)
        names, tensors = self._param_tensors()
        inputs = [src] + list(tensors)
        n_fixed = len(inputs)
        mode = "forward"
        cache_arr = None
        if caches is not None:
            cache_arr = self._unpack_caches(caches)
            mode = "decode" if time_step is not None else "prefill"
            inputs.append(Tensor(cache_arr[0]))
            inputs.append(Tensor(cache_arr[1]))
        if mode == "decode":
            if attn_mask is not None:
                raise NotImplementedError(
                    "attn_mask in decode mode: the cache mask is derived "
                    "from positions — pass per-row positions via "
                    "time_step/seq_lens instead")
            if seq_lens is not None:
                # reference cache_kvs protocol: per-row current lengths —
                # use them as the per-row write/attend positions
                time_step = seq_lens
                seq_lens = None
        if seq_lens is not None:
            seq_lens = as_tensor(seq_lens)
            inputs.append(seq_lens)
        if time_step is not None:
            ts = as_tensor(time_step)
            inputs.append(ts)
        if attn_mask is not None:
            attn_mask = as_tensor(attn_mask)
            inputs.append(attn_mask)
        has_cache = cache_arr is not None
        has_lens = seq_lens is not None
        has_step = time_step is not None
        has_mask = attn_mask is not None
        key = rng_mod.next_key() if (self.training and
                                     self.dropout_rate > 0) else None
        if key is not None:
            inputs.append(Tensor(key))
        training = self.training

        def _fn(x, *rest):
            params = dict(zip(names, rest[:len(names)]))
            i = len(names)
            cache = step = lens = mask = kk = None
            if has_cache:
                cache = (rest[i], rest[i + 1]); i += 2
            if has_lens:
                lens = rest[i]; i += 1
            if has_step:
                step = rest[i].astype(jnp.int32); i += 1
            if has_mask:
                mask = rest[i]; i += 1
            if key is not None:
                kk = rest[i]; i += 1
            if mode == "decode" and step is not None and step.ndim > 0 \
                    and step.size == 1:
                step = step.reshape(())
            out, new_cache, aux = _run_stack(
                cfg, params, x, cache, mode, step, lens, mask, kk,
                training)
            if has_cache:
                return out, new_cache[0], new_cache[1]
            return out

        out = dispatch.apply("fused_multi_transformer", _fn,
                             tuple(inputs))
        if has_cache:
            out, new_k, new_v = out
            return out, (new_k, new_v)
        return out

    # -- functional entry for generate() ------------------------------------
    def bind_core(self):
        """Returns (names, tensors, core_fn) where
        core_fn(param_arrays, x, cache, mode, step, seq_lens) is pure —
        used by `generation.py` to build jitted prefill/decode steps."""
        cfg = self._cfg()
        names, tensors = self._param_tensors()

        def core(arrays, x, cache, mode, step=None, seq_lens=None,
                 attn_mask=None):
            params = dict(zip(names, arrays))
            return _run_stack(cfg, params, x, cache, mode, step,
                              seq_lens, attn_mask, None, False)
        return names, tensors, core


_skip_weight_init = [False]


@contextlib.contextmanager
def _zero_init():
    """Used by from_float: the constructed model's weights are about to
    be overwritten, so don't pay a full random init + quantize."""
    _skip_weight_init[0] = True
    try:
        yield
    finally:
        _skip_weight_init[0] = False


def _scaled_normal(fan_in, salt=""):
    def init(shape, dtype):
        if _skip_weight_init[0]:
            return jnp.zeros(shape, dtype)
        std = 1.0 / math.sqrt(fan_in)
        # deterministic per-(family, shape) seed keeps init reproducible
        # without touching the global paddle seed state; the salt keeps
        # same-shaped weight families (e.g. out-proj vs ffn2 when
        # dim_ff == embed_dim) from being byte-identical
        import zlib
        seed = zlib.crc32(f"{salt}:{tuple(shape)}".encode()) % (2 ** 31)
        key = jax.random.PRNGKey(seed)
        return (jax.random.normal(key, shape, jnp.float32) * std
                ).astype(dtype)
    return init


class FusedMultiTransformerWeightOnly(FusedMultiTransformer):
    """Weight-only int8 variant — ref `FusedMultiTransformerINT8`
    (`fused_transformer.py:1464`) / `weight_only_linear_kernel.h`.

    Matmul weights live as int8 buffers + per-out-channel fp32 scales;
    the dequant fuses into the bf16 dot. On TPU the win is HBM
    bandwidth during decode, which is exactly when the op is
    bandwidth-bound. Build with `from_float(model)`."""

    def __init__(self, *args, quant_bits=8, **kw):
        super().__init__(*args, **kw)
        self._quant_bits = quant_bits
        self._quantize_param("qkv_weights", "qkv")
        self._quantize_param("linear_weights", "out")
        self._quantize_param("ffn1_weights", "ffn1")
        self._quantize_param("ffn2_weights", "ffn2")

    def _quantize_param(self, attr, key):
        w = getattr(self, attr)
        q, s = _quantize_stack(w._data, self._quant_bits)
        # drop the float parameter; register int8 weight + scale buffers
        del self._parameters[attr]
        self.register_buffer(attr, Tensor(q))
        self.register_buffer(key + "_scales", Tensor(s))

    @property
    def _qkv_s(self):
        return self.qkv_scales

    @property
    def _out_s(self):
        return self.out_scales

    @property
    def _ffn1_s(self):
        return self.ffn1_scales

    @property
    def _ffn2_s(self):
        return self.ffn2_scales

    @classmethod
    def from_float(cls, model: FusedMultiTransformer, quant_bits=8):
        if isinstance(model, FusedMultiTransformerMoe):
            raise TypeError(
                "from_float on a MoE stack: build "
                "FusedMultiTransformerMoeWeightOnly directly")
        with _zero_init():
            new = cls(model.embed_dim, model.num_heads * model.nranks,
                      model.dim_feedforward * model.nranks,
                      dropout_rate=model.dropout_rate,
                      activation=model.activation,
                      normalize_before=model.normalize_before,
                      epsilon=model._epsilon, num_layers=model.num_layers,
                      nranks=model.nranks, quant_bits=quant_bits)
        for name in ("ln_scales", "ln_biases", "qkv_biases",
                     "linear_biases", "ffn_ln_scales", "ffn_ln_biases",
                     "ffn1_biases", "ffn2_biases"):
            getattr(new, name)._data = getattr(model, name)._data
        for wname, key in (("qkv_weights", "qkv"),
                           ("linear_weights", "out"),
                           ("ffn1_weights", "ffn1"),
                           ("ffn2_weights", "ffn2")):
            q, s = _quantize_stack(getattr(model, wname)._data, quant_bits)
            getattr(new, wname)._data = q
            getattr(new, key + "_scales")._data = s
        return new


# alias: the reference's activation-int8 class; on TPU the MXU path is
# bf16 so the supported quantization is weight-only (documented stance)
FusedMultiTransformerINT8 = FusedMultiTransformerWeightOnly


def _quantize_stack(w, bits):
    """[L, In, Out] -> int8 [L, In, Out] + scales [L, Out]."""
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(w), axis=-2), 1e-9)
    q = jnp.clip(jnp.round(w / scale[:, None, :] * qmax), -qmax, qmax
                 ).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


class FusedMultiTransformerMoe(FusedMultiTransformer):
    """MoE FFN in every layer — ref `fused_transformer.py:1934`
    (`fused_multi_transformer_moe_op.cu`). Dense top-k dispatch with
    capacity; set `ep_axis`/`ep_size` to shard experts over a mesh axis
    (the all_to_all rides ICI, ref `global_scatter_op.cu.cc`)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, epsilon=1e-5, num_layers=-1,
                 nranks=1, num_expert=4, top_k=2, capacity_factor=1.25,
                 ep_axis=None, ep_size=1, **kw):
        # build the dense stack first (gives attention params), then
        # replace the FFN params with expert-stacked ones
        super().__init__(embed_dim, num_heads, dim_feedforward,
                         dropout_rate=dropout_rate, activation=activation,
                         normalize_before=normalize_before,
                         epsilon=epsilon, num_layers=num_layers,
                         nranks=nranks, **kw)
        self._num_experts = num_expert
        self._moe_topk = top_k
        self._capacity_factor = capacity_factor
        self._ep_axis = ep_axis
        self._ep_size = ep_size
        L, D = self.num_layers, self.embed_dim
        Fl = self.dim_feedforward
        E_loc = num_expert // max(1, ep_size)
        del self._parameters["ffn1_weights"]
        del self._parameters["ffn1_biases"]
        del self._parameters["ffn2_weights"]
        del self._parameters["ffn2_biases"]
        self.gate_weights = self.create_parameter(
            [L, D, num_expert], default_initializer=_scaled_normal(D, "gate"))
        self.ffn1_weights = self.create_parameter(
            [L, E_loc, D, Fl], default_initializer=_scaled_normal(D, "ffn1"))
        self.ffn1_biases = self.create_parameter([L, E_loc, Fl],
                                                 is_bias=True)
        self.ffn2_weights = self.create_parameter(
            [L, E_loc, Fl, D], default_initializer=_scaled_normal(Fl, "ffn2"))
        self.ffn2_biases = self.create_parameter([L, E_loc, D],
                                                 is_bias=True)

    @property
    def _gate_w(self):
        return self.gate_weights

    def _cfg(self, mp_axis=None, ep_axis=None, training=False):
        cfg = super()._cfg(mp_axis, ep_axis or self._ep_axis, training)
        return dataclasses.replace(cfg, ep_size=self._ep_size)


class FusedMultiTransformerMoeWeightOnly(FusedMultiTransformerMoe):
    """ref `fused_transformer.py:2645` — MoE stack with weight-only
    int8 attention + expert weights.

    `moe_quant_bits=4` (ISSUE 14) stores the EXPERT stacks int4:
    nibble-packed along the contraction axis (two weights per byte,
    `ops.pallas.grouped_matmul.pack_int4`) with per-(expert,
    out-channel) fp16 scales, while the attention weights keep
    `quant_bits` (int8) — the expert stacks are where a big MoE's
    bytes live, so this is the knob that makes it fit fewer chips."""

    def __init__(self, *args, quant_bits=8, moe_quant_bits=None, **kw):
        super().__init__(*args, **kw)
        self._quant_bits = quant_bits
        self._moe_quant_bits = int(moe_quant_bits or 0)
        ebits = self._moe_quant_bits or quant_bits
        if ebits not in (4, 8):
            raise ValueError(
                f"expert weight-only bits must be 4 or 8, got {ebits}")
        for attr, key in (("qkv_weights", "qkv"),
                          ("linear_weights", "out")):
            w = getattr(self, attr)
            q, s = _quantize_stack(w._data, quant_bits)
            del self._parameters[attr]
            self.register_buffer(attr, Tensor(q))
            self.register_buffer(key + "_scales", Tensor(s))
        for attr, key in (("ffn1_weights", "ffn1"),
                          ("ffn2_weights", "ffn2")):
            w = getattr(self, attr)
            q, s = _quantize_expert_stack(w._data, ebits)
            del self._parameters[attr]
            self.register_buffer(attr, Tensor(q))
            self.register_buffer(key + "_scales", Tensor(s))

    @property
    def _qkv_s(self):
        return self.qkv_scales

    @property
    def _out_s(self):
        return self.out_scales

    @property
    def _ffn1_s(self):
        return self.ffn1_scales

    @property
    def _ffn2_s(self):
        return self.ffn2_scales


FusedMultiTransformerMoeINT8 = FusedMultiTransformerMoeWeightOnly


def _quantize_expert_stack(w, bits):
    """[L, E, In, Out] -> int8 + fp32 scales [L, E, Out]; `bits=4`
    returns the nibble-PACKED [L, E, In/2, Out] layout with fp16
    scales instead (`ops.pallas.grouped_matmul.quantize_int4_experts`
    — the kernel and `_deq` both speak that format)."""
    if bits == 4:
        from ...ops.pallas.grouped_matmul import quantize_int4_experts
        return quantize_int4_experts(w)
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(w), axis=-2), 1e-9)
    q = jnp.clip(jnp.round(w / scale[:, :, None, :] * qmax), -qmax, qmax
                 ).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


class FusedMoELayer(Layer):
    """Single-layer MoE FFN — ref `fused_transformer.py:1766`
    (`FusedMoELayer`): gate + expert FFNs, top-k dispatch."""

    def __init__(self, d_model, dim_feedforward, num_expert=4, top_k=2,
                 capacity_factor=1.25, activation="gelu", ep_axis=None,
                 ep_size=1):
        super().__init__()
        self.d_model = d_model
        self.cfg = _MTConfig(
            num_layers=1, num_heads=1, head_dim=d_model,
            dim_ff=dim_feedforward, activation=activation,
            num_experts=num_expert, moe_topk=top_k,
            capacity_factor=capacity_factor, ep_axis=ep_axis,
            ep_size=ep_size)
        E_loc = num_expert // max(1, ep_size)
        self.gate_weight = self.create_parameter(
            [d_model, num_expert], default_initializer=_scaled_normal(
                d_model, "gate"))
        self.ffn1_weight = self.create_parameter(
            [E_loc, d_model, dim_feedforward],
            default_initializer=_scaled_normal(d_model, "ffn1"))
        self.ffn1_bias = self.create_parameter(
            [E_loc, dim_feedforward], is_bias=True)
        self.ffn2_weight = self.create_parameter(
            [E_loc, dim_feedforward, d_model],
            default_initializer=_scaled_normal(dim_feedforward, "ffn2"))
        self.ffn2_bias = self.create_parameter([E_loc, d_model],
                                               is_bias=True)
        self.last_aux_loss = None

    def forward(self, x):
        x = as_tensor(x)
        cfg = self.cfg
        inputs = (x, self.gate_weight, self.ffn1_weight, self.ffn1_bias,
                  self.ffn2_weight, self.ffn2_bias)

        def _fn(xa, gw, w1, b1, w2, b2):
            pl = {"gate_w": gw, "ffn1_w": w1, "ffn1_b": b1,
                  "ffn2_w": w2, "ffn2_b": b2}
            squeeze = xa.ndim == 2
            if squeeze:
                xa = xa[None]
            out, aux = _ffn_moe(cfg, pl, xa)
            if squeeze:
                out = out[0]
            return out, aux
        out, aux = dispatch.apply("fused_moe", _fn, inputs)
        self.last_aux_loss = aux
        return out
