"""Fused layers (`python/paddle/incubate/nn/layer/fused_transformer.py`).
On TPU, "fused" is what XLA does to the unfused graph; these classes keep
the reference API and map onto the standard layers + flash attention.
"""
from __future__ import annotations

from ...nn.layers.transformer import (TransformerEncoderLayer,
                                      MultiHeadAttention)


class FusedMultiHeadAttention(MultiHeadAttention):
    pass


class FusedTransformerEncoderLayer(TransformerEncoderLayer):
    pass


class FusedFeedForward:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "use nn.TransformerEncoderLayer; XLA fuses the FFN")
