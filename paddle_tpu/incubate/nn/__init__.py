"""Fused layers — the LLM-serving stack.

Parity: `python/paddle/incubate/nn/layer/fused_transformer.py`. Real
TPU-native implementations live in `fused_transformer.py` (stacked
weights + `lax.scan`, fixed-shape KV cache, weight-only int8, MoE) and
`generation.py` (compiled greedy/sampling decode).
"""
from __future__ import annotations

from .fused_transformer import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm,
    FusedMultiHeadAttention,
    FusedFeedForward,
    FusedTransformerEncoderLayer,
    FusedMultiTransformer,
    FusedMultiTransformerWeightOnly,
    FusedMultiTransformerINT8,
    FusedMultiTransformerMoe,
    FusedMultiTransformerMoeWeightOnly,
    FusedMultiTransformerMoeINT8,
    FusedMoELayer,
)
from .generation import GenerationMixin, SamplingConfig  # noqa: F401


from ... import nn as _nn


class FusedLinear(_nn.Linear):
    """`incubate/nn/layer/fused_linear.py:19` parity: matmul+bias as one
    fused op. On TPU `nn.Linear` already compiles to a single fused XLA
    matmul+bias (the reference needed the fused_gemm_epilogue CUDA
    kernel). `transpose_weight=True` (a storage-order knob for that
    kernel, which also transposes checkpoints) is refused rather than
    silently producing transposed state_dict semantics."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 transpose_weight=False, bias_attr=None, name=None):
        if transpose_weight:
            raise NotImplementedError(
                "FusedLinear(transpose_weight=True) stores the weight "
                "as [out, in] in the reference checkpoints; load such "
                "checkpoints by transposing, or use the default layout")
        super().__init__(in_features, out_features,
                         weight_attr=weight_attr, bias_attr=bias_attr)
