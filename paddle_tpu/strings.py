"""String tensors + string kernels.

Parity: `paddle/phi/core/string_tensor.h` (pstring arrays) and the
strings kernel family (`paddle/phi/kernels/strings/` —
strings_lower/strings_upper with UTF-8 handling) plus the
faster_tokenizer custom op the reference ships for NLP serving
(`paddle/fluid/operators/fused/` fork focus). TPU-native stance: strings
are HOST data in the reference too (strings kernels are CPU-only);
here they live as numpy object arrays feeding int token tensors into
the compiled path — the tokenizer emits `Tensor[int32]`, which is where
the TPU program starts.
"""
from __future__ import annotations

import numpy as np

from .core.tensor import Tensor


class StringTensor:
    """A shaped array of (unicode) strings — phi::StringTensor parity."""

    def __init__(self, data, name=None):
        arr = np.asarray(data, dtype=object)
        self._data = arr
        self.name = name

    @property
    def shape(self):
        return list(self._data.shape)

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data.tolist()!r})"

    def __eq__(self, other):
        if isinstance(other, StringTensor):
            return bool((self._data == other._data).all())
        return NotImplemented


def to_string_tensor(data, name=None):
    return StringTensor(data, name)


def _map(fn, x: StringTensor) -> StringTensor:
    out = np.empty(x._data.shape, dtype=object)
    flat_in = x._data.reshape(-1)
    flat_out = out.reshape(-1)
    for i, s in enumerate(flat_in):
        flat_out[i] = fn(s)
    return StringTensor(out)


def lower(x, use_utf8_encoding=True):
    """strings_lower kernel parity (python str.lower is full-unicode)."""
    return _map(str.lower, x)


def upper(x, use_utf8_encoding=True):
    return _map(str.upper, x)


class FasterTokenizer:
    """Vocabulary-driven whitespace + greedy-wordpiece tokenizer
    (faster_tokenizer op capability): StringTensor batch ->
    (input_ids, seq_len) int32 Tensors, padded, ready for a compiled
    encoder."""

    def __init__(self, vocab, do_lower_case=True, unk_token="[UNK]",
                 cls_token="[CLS]", sep_token="[SEP]", pad_token="[PAD]",
                 max_seq_len=128):
        self.vocab = dict(vocab)
        self.do_lower_case = do_lower_case
        self.unk = unk_token
        self.cls = cls_token
        self.sep = sep_token
        self.pad = pad_token
        self.max_seq_len = max_seq_len

    def _wordpiece(self, word):
        if word in self.vocab:
            return [word]
        pieces, start = [], 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk]
            pieces.append(cur)
            start = end
        return pieces

    def __call__(self, text):
        if isinstance(text, StringTensor):
            texts = [str(s) for s in text._data.reshape(-1)]
        elif isinstance(text, str):
            texts = [text]
        else:
            texts = [str(s) for s in text]
        ids_rows, lens = [], []
        for t in texts:
            if self.do_lower_case:
                t = t.lower()
            toks = [self.cls]
            for w in t.split():
                toks.extend(self._wordpiece(w))
            toks.append(self.sep)
            if len(toks) > self.max_seq_len:
                # truncation preserves the special-token frame
                toks = toks[: self.max_seq_len - 1] + [self.sep]
            ids = [self.vocab.get(tok, self.vocab.get(self.unk, 0))
                   for tok in toks]
            lens.append(len(ids))
            ids_rows.append(ids)
        width = max(lens)
        pad_id = self.vocab.get(self.pad, 0)
        out = np.full((len(ids_rows), width), pad_id, np.int32)
        for i, row in enumerate(ids_rows):
            out[i, : len(row)] = row
        return (Tensor(out),
                Tensor(np.asarray(lens, np.int32)))


def empty(shape, name=None):
    """`strings/strings_empty_kernel.h` — uninitialised StringTensor."""
    if np.isscalar(shape):
        shape = [int(shape)]
    arr = np.full(tuple(int(s) for s in shape), "", dtype=object)
    return StringTensor(arr)
