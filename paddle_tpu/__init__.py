"""paddle_tpu — a TPU-native deep learning framework with PaddlePaddle's
capabilities, built on JAX/XLA/Pallas/pjit.

Reference: miaoli06/Paddle (see SURVEY.md). The user surface mirrors
`python/paddle/__init__.py`; the execution engine is XLA.
"""
from .core import dtype as _dtype_mod
from .core.dtype import (
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, set_default_dtype, get_default_dtype,
    finfo, iinfo,
)
from .core.tensor import Tensor, Parameter
from .core.lod import (LoDTensor, create_lod_tensor,  # noqa: F401
                       sequence_pool)
from .core.autograd import (no_grad, enable_grad, grad,  # noqa: F401
                            is_grad_enabled, set_grad_enabled)
from .core.place import (
    CPUPlace, TPUPlace, CUDAPlace, set_device, get_device,
    is_compiled_with_cuda, is_compiled_with_tpu,
)
from .core.random import seed, get_rng_state, set_rng_state

from .ops import *  # noqa: F401,F403 — tensor op namespace (paddle.* ops)
from . import ops

# subpackages (populated progressively; import order matters)
from . import nn  # noqa
from . import optimizer  # noqa
from . import amp  # noqa
from . import io  # noqa
from . import metric  # noqa
from . import vision  # noqa
from . import jit  # noqa
from . import static  # noqa
from . import parallel as distributed  # noqa — paddle.distributed parity
from . import parallel  # noqa
from . import hapi  # noqa
from .hapi.model import Model  # noqa
from .framework_io import save, load  # noqa
from . import profiler  # noqa
from . import incubate  # noqa
from . import device  # noqa
from . import distribution  # noqa
from . import regularizer  # noqa
from . import sparse  # noqa
from . import fft  # noqa
from .ops import linalg  # noqa — paddle.linalg namespace
from . import models  # noqa
from . import autograd_api as autograd  # noqa — paddle.autograd
from . import onnx  # noqa
from . import inference  # noqa
from . import serving  # noqa — continuous-batching engine
from . import hub  # noqa
from . import quantization  # noqa
from . import text  # noqa
from . import strings  # noqa
from . import utils  # noqa
from . import audio  # noqa
from . import geometric  # noqa
from . import signal  # noqa
from . import version  # noqa
from . import sysconfig  # noqa
from .batch import batch  # noqa
from .device import get_cudnn_version, disable_signal_handler  # noqa
from .hapi import callbacks  # noqa — paddle.callbacks
from .hapi.dynamic_flops import flops  # noqa — paddle.flops
from .flags import set_flags, get_flags  # noqa
from .nn.clip import (ClipGradByValue, ClipGradByNorm,  # noqa
                      ClipGradByGlobalNorm)

import sys as _sys
_sys.modules[__name__ + ".distributed"] = distributed
_sys.modules[__name__ + ".autograd"] = autograd

DataParallel = distributed.DataParallel

__version__ = "0.1.0"


def disable_static():
    """Dygraph is the default and only eager mode; kept for parity."""


def enable_static():
    raise NotImplementedError(
        "paddle_tpu is eager-first; use paddle_tpu.jit.to_static for "
        "compiled execution (SURVEY.md §7.5: whole-step jax.jit subsumes "
        "the static Program/Executor stack)"
    )


def in_dynamic_mode():
    return True


def summary(net, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes)
