"""PS RPC transport: TCP server/client over the native tables.

Parity: the brpc PS service pair (`paddle/fluid/distributed/ps/service/
brpc_ps_server.h` / `brpc_ps_client.h`, wire proto `sendrecv.proto`) and
`PSClient`/`PSServer` (`ps_client.h:63`, `server.h:62`). The storage and
the SGD rules are the native C++ engine (ps/csrc); this module is the
wire: a length-prefixed binary protocol over TCP, one thread per
connection (the brpc threading model scaled down). Shards-by-key routing
across multiple servers matches the reference's table sharding
(`MemorySparseTable` shard_num semantics).

Message format: [u32 len][u8 op][u32 table_id][payload]
ops: 0 PULL_SPARSE (payload: u32 n, u64*n keys) -> f32 n*dim
     1 PUSH_SPARSE (payload: u32 n, u64*n keys, f32 n*dim grads) -> u8 ok
     2 PULL_DENSE  (payload: -) -> u32 n, f32*n
     3 PUSH_DENSE  (payload: u32 n, f32*n grads) -> u8 ok
     4 SAVE        (payload: u16 len, path) -> u8 ok
     5 BARRIER     -> u8 ok
     6 STOP        -> u8 ok
     7 DENSE_ADD   (payload: u32 n, f32*n delta) -> u32 n, f32*n merged
       (geo-async dense mode: server merges the trainer's delta and
       returns the merged params in one round trip)
     8 KV_SET      (payload: u16 klen, key, u32 vlen, val) -> u8 ok
     9 KV_GET      (payload: u16 klen, key) -> u8 found, u32 vlen, val
    10 KV_LIST     (payload: u16 plen, prefix) -> u32 cnt,
       cnt x (u16 klen, key, u32 vlen, val)
       (server-side KV namespace: the FL coordinator's client-info /
       strategy exchange — CoordinatorClient/FLCommunicator parity —
       and a TCPStore-style rendezvous primitive)
    11 PUSH_SPARSE_V2 (payload: u32 n, u32 width, u8 flags, u64*n keys,
       f32 n*width grads, then per flags bit0..bit3: f32*n shows,
       f32*n clicks, i32*n mf_dims, f32*n slots) -> u8 ok
       (CTR accessor statistics travel with the gradient so remote
       ctr_double/ctr_dymf tables mature exactly like local ones —
       sendrecv.proto's PushSparseParam show/click semantics)

Fault tolerance: the client transparently reconnects a broken server
socket and retries the request ONCE (brpc_ps_client reconnect parity;
pushes are at-least-once on retry, like the reference's async push).
"""
from __future__ import annotations

import socket
import socketserver
import struct
import threading

import numpy as np

from .table import MemorySparseTable, MemoryDenseTable

(PULL_SPARSE, PUSH_SPARSE, PULL_DENSE, PUSH_DENSE, SAVE, BARRIER, STOP,
 DENSE_ADD, KV_SET, KV_GET, KV_LIST, PUSH_SPARSE_V2) = range(12)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _send_msg(sock, payload: bytes):
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_msg(sock) -> bytes:
    (n,) = struct.unpack("<I", _recv_exact(sock, 4))
    return _recv_exact(sock, n)


class PSServer:
    """One PS shard server process. Tables registered by id."""

    def __init__(self, host="127.0.0.1", port=0):
        self._tables = {}
        # count-based trainer rendezvous (BarrierTable parity): BARRIER
        # carries the participant count; connections block until all arrive
        self._barrier_cond = threading.Condition()
        self._barrier_count = 0
        self._barrier_generation = 0
        self._kv = {}
        self._kv_lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                try:
                    while True:
                        msg = _recv_msg(sock)
                        if not outer._handle(sock, msg):
                            break
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = None

    def register_sparse_table(self, table_id, dim=8, sgd_rule="adagrad",
                              learning_rate=0.05, initial_range=0.02,
                              accessor="ctr", embedx_threshold=10.0):
        t = MemorySparseTable(dim, sgd_rule, learning_rate, initial_range,
                              accessor=accessor,
                              embedx_threshold=embedx_threshold)
        self._tables[table_id] = t
        return t

    def register_dense_table(self, table_id, size, sgd_rule="adam",
                             learning_rate=0.01):
        t = MemoryDenseTable(size, sgd_rule, learning_rate)
        self._tables[table_id] = t
        return t

    def _handle(self, sock, msg) -> bool:
        op, table_id = struct.unpack("<BI", msg[:5])
        body = msg[5:]
        if op == STOP:
            _send_msg(sock, b"\x01")
            threading.Thread(target=self._server.shutdown,
                             daemon=True).start()
            return False
        if op == BARRIER:
            (n_participants,) = struct.unpack("<I", body[:4]) if body \
                else (1,)
            with self._barrier_cond:
                gen = self._barrier_generation
                self._barrier_count += 1
                if self._barrier_count >= n_participants:
                    self._barrier_count = 0
                    self._barrier_generation += 1
                    self._barrier_cond.notify_all()
                else:
                    self._barrier_cond.wait_for(
                        lambda: self._barrier_generation != gen,
                        timeout=300)
            _send_msg(sock, b"\x01")
            return True
        if op == KV_SET:
            (klen,) = struct.unpack("<H", body[:2])
            key = body[2:2 + klen].decode()
            (vlen,) = struct.unpack("<I", body[2 + klen:6 + klen])
            val = body[6 + klen:6 + klen + vlen]
            with self._kv_lock:
                self._kv[key] = val
            _send_msg(sock, b"\x01")
            return True
        if op == KV_GET:
            (klen,) = struct.unpack("<H", body[:2])
            key = body[2:2 + klen].decode()
            with self._kv_lock:
                val = self._kv.get(key)
            if val is None:
                _send_msg(sock, b"\x00" + struct.pack("<I", 0))
            else:
                _send_msg(sock, b"\x01" + struct.pack("<I", len(val))
                          + val)
            return True
        if op == KV_LIST:
            (plen,) = struct.unpack("<H", body[:2])
            prefix = body[2:2 + plen].decode()
            with self._kv_lock:
                items = [(k, v) for k, v in self._kv.items()
                         if k.startswith(prefix)]
            out = struct.pack("<I", len(items))
            for k, v in items:
                kb = k.encode()
                out += struct.pack("<H", len(kb)) + kb
                out += struct.pack("<I", len(v)) + v
            _send_msg(sock, out)
            return True
        table = self._tables[table_id]
        if op == PULL_SPARSE:
            (n,) = struct.unpack("<I", body[:4])
            keys = np.frombuffer(body[4:4 + 8 * n], np.uint64)
            vals = table.pull(keys.copy())
            _send_msg(sock, vals.astype(np.float32).tobytes())
        elif op == PUSH_SPARSE:
            (n,) = struct.unpack("<I", body[:4])
            keys = np.frombuffer(body[4:4 + 8 * n], np.uint64)
            width = getattr(table, "row_width", table.dim)
            grads = np.frombuffer(body[4 + 8 * n:], np.float32).reshape(
                n, width)
            table.push(keys.copy(), grads.copy())
            _send_msg(sock, b"\x01")
        elif op == PUSH_SPARSE_V2:
            n, width, flags = struct.unpack("<IIB", body[:9])
            off = 9
            keys = np.frombuffer(body[off:off + 8 * n], np.uint64)
            off += 8 * n
            grads = np.frombuffer(body[off:off + 4 * n * width],
                                  np.float32).reshape(n, width)
            off += 4 * n * width
            extras = {}
            for bit, name, dt in ((1, "shows", np.float32),
                                  (2, "clicks", np.float32),
                                  (4, "mf_dims", np.int32),
                                  (8, "slots", np.float32)):
                if flags & bit:
                    extras[name] = np.frombuffer(
                        body[off:off + 4 * n], dt).copy()
                    off += 4 * n
            table.push(keys.copy(), grads.copy(), **extras)
            _send_msg(sock, b"\x01")
        elif op == PULL_DENSE:
            vals = table.pull()
            _send_msg(sock, struct.pack("<I", vals.size)
                      + vals.astype(np.float32).tobytes())
        elif op == PUSH_DENSE:
            (n,) = struct.unpack("<I", body[:4])
            grads = np.frombuffer(body[4:4 + 4 * n], np.float32)
            table.push(grads.copy())
            _send_msg(sock, b"\x01")
        elif op == DENSE_ADD:
            (n,) = struct.unpack("<I", body[:4])
            delta = np.frombuffer(body[4:4 + 4 * n], np.float32)
            table.add(delta.copy())
            merged = table.pull()
            _send_msg(sock, struct.pack("<I", merged.size)
                      + merged.astype(np.float32).tobytes())
        elif op == SAVE:
            (ln,) = struct.unpack("<H", body[:2])
            path = body[2:2 + ln].decode()
            table.save(path)
            _send_msg(sock, b"\x01")
        return True

    def run(self, background=True):
        if background:
            self._thread = threading.Thread(
                target=self._server.serve_forever, daemon=True)
            self._thread.start()
        else:
            self._server.serve_forever()

    def stop(self):
        self._server.shutdown()


class PSClient:
    """Client with key-sharded routing across servers (BrpcPsClient
    capability: shard_of(key) -> server)."""

    def __init__(self, endpoints):
        self.endpoints = [(h, int(p)) for h, p in
                          (e.split(":") for e in endpoints)]
        self._socks = [self._connect(i)
                       for i in range(len(self.endpoints))]
        self._lock = threading.Lock()

    def _connect(self, si):
        host, port = self.endpoints[si]
        s = socket.create_connection((host, port), timeout=30)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Connect quickly, but allow long replies: BARRIER legitimately
        # parks the socket until the last participant arrives (server
        # waits up to 300s), far beyond the 30s connect timeout this
        # socket would otherwise inherit. Keep a bound (> the server's
        # 300s barrier wait) so a dead server still errors out.
        s.settimeout(330.0)
        return s

    def _request(self, si, payload: bytes, retry=True) -> bytes:
        """Send + receive on server si, reconnecting and retrying ONCE on
        a broken socket (brpc_ps_client reconnect capability). Retried
        pushes are at-least-once, matching the reference's async push
        semantics; non-idempotent ops (BARRIER: a double arrival would
        release the rendezvous early) pass retry=False and surface the
        error instead. Call with self._lock held."""
        for attempt in (0, 1):
            try:
                _send_msg(self._socks[si], payload)
                return _recv_msg(self._socks[si])
            except (ConnectionError, OSError):
                if attempt or not retry:
                    raise
                try:
                    self._socks[si].close()
                except OSError:
                    pass
                self._socks[si] = self._connect(si)
        raise ConnectionError("unreachable")

    def _shard_of(self, keys):
        n = len(self._socks)
        return ((keys * np.uint64(0x9E3779B97F4A7C15)) >> np.uint64(48)) \
            % np.uint64(n)

    def pull_sparse(self, table_id, keys: np.ndarray, dim: int):
        shape = keys.shape
        flat = keys.reshape(-1).astype(np.uint64)
        out = np.empty((flat.size, dim), np.float32)
        assign = self._shard_of(flat)
        with self._lock:
            for si in range(len(self._socks)):
                idx = np.where(assign == si)[0]
                if idx.size == 0:
                    continue
                sub = flat[idx]
                payload = struct.pack("<BII", PULL_SPARSE, table_id,
                                      sub.size) + sub.tobytes()
                resp = self._request(si, payload)
                out[idx] = np.frombuffer(resp, np.float32).reshape(
                    sub.size, dim)
        return out.reshape(*shape, dim)

    def push_sparse(self, table_id, keys: np.ndarray, grads: np.ndarray,
                    dim: int):
        flat = keys.reshape(-1).astype(np.uint64)
        g = grads.reshape(flat.size, dim).astype(np.float32)
        assign = self._shard_of(flat)
        with self._lock:
            for si in range(len(self._socks)):
                idx = np.where(assign == si)[0]
                if idx.size == 0:
                    continue
                sub = flat[idx]
                payload = struct.pack("<BII", PUSH_SPARSE, table_id,
                                      sub.size) + sub.tobytes() + \
                    g[idx].tobytes()
                self._request(si, payload)

    def push_sparse_v2(self, table_id, keys: np.ndarray,
                       grads: np.ndarray, dim: int, shows=None,
                       clicks=None, mf_dims=None, slots=None):
        """PUSH_SPARSE_V2: gradient + CTR accessor statistics in one
        message (show/click counts, per-key mf dims, slot ids)."""
        flat = keys.reshape(-1).astype(np.uint64)
        g = grads.reshape(flat.size, dim).astype(np.float32)
        opt = [
            (1, None if shows is None else np.asarray(
                shows, np.float32).reshape(-1)),
            (2, None if clicks is None else np.asarray(
                clicks, np.float32).reshape(-1)),
            (4, None if mf_dims is None else np.asarray(
                mf_dims, np.int32).reshape(-1)),
            (8, None if slots is None else np.asarray(
                slots, np.float32).reshape(-1)),
        ]
        flags = sum(bit for bit, a in opt if a is not None)
        assign = self._shard_of(flat)
        with self._lock:
            for si in range(len(self._socks)):
                idx = np.where(assign == si)[0]
                if idx.size == 0:
                    continue
                sub = flat[idx]
                payload = struct.pack("<BIIIB", PUSH_SPARSE_V2, table_id,
                                      sub.size, dim, flags)
                payload += sub.tobytes() + g[idx].tobytes()
                for bit, a in opt:
                    if a is not None:
                        payload += a[idx].tobytes()
                self._request(si, payload)

    # -- KV namespace (FL coordinator exchange / rendezvous) ---------
    def kv_set(self, key: str, value: bytes, server=0):
        kb = key.encode()
        payload = struct.pack("<BIH", KV_SET, 0, len(kb)) + kb + \
            struct.pack("<I", len(value)) + value
        with self._lock:
            self._request(server, payload)

    def kv_get(self, key: str, server=0):
        kb = key.encode()
        payload = struct.pack("<BIH", KV_GET, 0, len(kb)) + kb
        with self._lock:
            resp = self._request(server, payload)
        if resp[0] == 0:
            return None
        (vlen,) = struct.unpack("<I", resp[1:5])
        return resp[5:5 + vlen]

    def kv_list(self, prefix: str, server=0):
        pb = prefix.encode()
        payload = struct.pack("<BIH", KV_LIST, 0, len(pb)) + pb
        with self._lock:
            resp = self._request(server, payload)
        (cnt,) = struct.unpack("<I", resp[:4])
        out, off = {}, 4
        for _ in range(cnt):
            (klen,) = struct.unpack("<H", resp[off:off + 2])
            key = resp[off + 2:off + 2 + klen].decode()
            off += 2 + klen
            (vlen,) = struct.unpack("<I", resp[off:off + 4])
            out[key] = resp[off + 4:off + 4 + vlen]
            off += 4 + vlen
        return out

    def pull_dense(self, table_id, server=0):
        with self._lock:
            resp = self._request(server, struct.pack("<BI", PULL_DENSE,
                                                     table_id))
        (n,) = struct.unpack("<I", resp[:4])
        return np.frombuffer(resp[4:], np.float32)[:n]

    def push_dense(self, table_id, grads: np.ndarray, server=0):
        g = grads.reshape(-1).astype(np.float32)
        with self._lock:
            self._request(server, struct.pack(
                "<BII", PUSH_DENSE, table_id, g.size) + g.tobytes())

    def push_dense_delta(self, table_id, delta: np.ndarray, server=0):
        """Geo-async dense: merge a local delta into the server's params;
        returns the merged params (one round trip). Never retried: the
        additive merge is not idempotent — a reconnect retry could apply
        the delta twice and silently offset the shared params."""
        d = delta.reshape(-1).astype(np.float32)
        with self._lock:
            resp = self._request(server, struct.pack(
                "<BII", DENSE_ADD, table_id, d.size) + d.tobytes(),
                retry=False)
        (n,) = struct.unpack("<I", resp[:4])
        return np.frombuffer(resp[4:], np.float32)[:n]

    def barrier(self, num_trainers=1):
        """Block until `num_trainers` clients reach the barrier on each
        server (count-based rendezvous)."""
        with self._lock:
            for si in range(len(self._socks)):
                self._request(si, struct.pack("<BII", BARRIER, 0,
                                              num_trainers), retry=False)

    def save(self, table_id, path):
        with self._lock:
            for si in range(len(self._socks)):
                p = f"{path}.shard{si}".encode()
                self._request(si, struct.pack("<BIH", SAVE, table_id,
                                              len(p)) + p)

    def stop_server(self):
        with self._lock:
            for sock in self._socks:
                try:
                    _send_msg(sock, struct.pack("<BI", STOP, 0))
                    _recv_msg(sock)
                except (ConnectionError, OSError):
                    pass

    def close(self):
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass


class RemoteSparseTable:
    """MemorySparseTable-compatible facade over PSClient (so
    SparseEmbedding works transparently against remote servers — the
    distributed_lookup_table capability)."""

    def __init__(self, client: PSClient, table_id: int, dim: int,
                 accessor="ctr"):
        from .table import _ACCESSORS, ACCESSOR_CTR_DYMF
        self.client = client
        self.table_id = table_id
        self.dim = dim
        acc = _ACCESSORS[accessor] if isinstance(accessor, str) \
            else int(accessor)
        self.accessor = acc
        # dymf rows travel as [embed_w, embedx(dim)] = 1+dim floats
        self.row_width = 1 + dim if acc == ACCESSOR_CTR_DYMF else dim

    def pull(self, keys):
        return self.client.pull_sparse(self.table_id, np.asarray(keys),
                                       self.row_width)

    def push(self, keys, grads, shows=None, clicks=None, mf_dims=None,
             slots=None):
        if any(x is not None for x in (shows, clicks, mf_dims, slots)):
            # CTR statistics ride the v2 wire op so remote accessors
            # mature identically to local tables (ADVICE r4 #2)
            self.client.push_sparse_v2(
                self.table_id, np.asarray(keys), np.asarray(grads),
                self.row_width, shows=shows, clicks=clicks,
                mf_dims=mf_dims, slots=slots)
            return
        self.client.push_sparse(self.table_id, np.asarray(keys),
                                np.asarray(grads), self.row_width)

    def __len__(self):
        raise NotImplementedError("size query not in the wire protocol yet")
