"""paddle_tpu.ps — the native parameter-server / embedding engine
(SURVEY.md §2.3 PS core + §7.7): C++ sharded hash tables with in-table SGD
rules, dense tables, the out-of-core slot Dataset/DataFeed, and the
PS-backed SparseEmbedding layer feeding TPU steps.
"""
from .table import (MemorySparseTable, MemoryDenseTable,  # noqa: F401
                    InMemoryDataset)
from .embedding import SparseEmbedding  # noqa: F401
from .runtime import get_ps_runtime, PSRuntime  # noqa: F401
from .communicator import AsyncCommunicator, GeoCommunicator  # noqa: F401
from .trainer import (HogwildTrainer, MultiTrainer,  # noqa: F401
                      DistMultiTrainer)
from .pass_cache import PassCache, PassCacheEmbedding  # noqa: F401
from .graph import (GraphTable, ShardedGraphTable,  # noqa: F401
                    GraphEngine, SageTrainer)
from .pipeline import PullPushPipeline  # noqa: F401
from .data_generator import (DataGenerator,  # noqa: F401
                             MultiSlotDataGenerator,
                             MultiSlotStringDataGenerator)
from .coordinator import (Coordinator, FLClient,  # noqa: F401
                          ClientSelector, CapacityClientSelector,
                          FLStrategy)
from .heter import (ShardedSparseTable, HotIdCache,  # noqa: F401
                    HeterEmbeddingEngine, LookupService)
