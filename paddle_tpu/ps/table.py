"""Python wrappers over the native PS tables.

Parity surface: `Table`/`MemorySparseTable` (`paddle/fluid/distributed/ps/
table/table.h:67`, `memory_sparse_table.h`) + `MemoryDenseTable`, with the
accessor/SGD-rule semantics (`ctr_accessor.h`, `sparse_sgd_rule.h`)
executing natively inside the table on push.
"""
from __future__ import annotations

import numpy as np

from ._native import get_lib, u64_ptr, f32_ptr, i32_ptr

SGD_NAIVE = 0
SGD_ADAGRAD = 1
SGD_ADAM = 2

_RULES = {"naive": SGD_NAIVE, "sgd": SGD_NAIVE, "adagrad": SGD_ADAGRAD,
          "std_adagrad": SGD_ADAGRAD, "adam": SGD_ADAM}

ACCESSOR_CTR = 0         # CtrCommonAccessor: float show/click
ACCESSOR_CTR_DOUBLE = 1  # CtrDoubleAccessor: double show/click
ACCESSOR_CTR_DYMF = 2    # CtrDymfAccessor: per-key dynamic mf dims

_ACCESSORS = {"ctr": ACCESSOR_CTR, "CtrCommonAccessor": ACCESSOR_CTR,
              "DownpourCtrAccessor": ACCESSOR_CTR,
              "ctr_double": ACCESSOR_CTR_DOUBLE,
              "CtrDoubleAccessor": ACCESSOR_CTR_DOUBLE,
              "DownpourCtrDoubleAccessor": ACCESSOR_CTR_DOUBLE,
              "ctr_dymf": ACCESSOR_CTR_DYMF,
              "CtrDymfAccessor": ACCESSOR_CTR_DYMF}


class MemorySparseTable:
    """Sparse table with selectable accessor family.

    accessor="ctr" (default, CtrCommonAccessor parity),
    "ctr_double" (CtrDoubleAccessor: show/click accumulated in double —
    exact CTR statistics at billions of impressions), or
    "ctr_dymf" (CtrDymfAccessor: per-key dynamic mf dims — keys carry a
    1-d embed_w from birth and only grow their mf block, at the slot's
    dim, once their CTR score crosses `embedx_threshold`).
    Ref: ctr_accessor.h, ctr_double_accessor.h:29, ctr_dymf_accessor.h:30.
    """

    def __init__(self, dim=8, sgd_rule="adagrad", learning_rate=0.05,
                 initial_range=0.02, accessor="ctr",
                 embedx_threshold=10.0):
        self.dim = dim
        self._lib = get_lib()
        rule = _RULES[sgd_rule] if isinstance(sgd_rule, str) else sgd_rule
        acc = _ACCESSORS[accessor] if isinstance(accessor, str) \
            else int(accessor)
        self.accessor = acc
        if acc == ACCESSOR_CTR:
            self._h = self._lib.pscore_sparse_create(
                dim, rule, float(learning_rate), float(initial_range))
        else:
            self._h = self._lib.pscore_sparse_create2(
                dim, rule, float(learning_rate), float(initial_range),
                acc, float(embedx_threshold))
        if self._h < 0:
            raise ValueError(f"bad accessor {accessor}")

    def pull(self, keys: np.ndarray) -> np.ndarray:
        """keys: uint64 [n] (any shape; flattened) -> float32 [*, dim].

        dymf tables return rows [1 + dim]: [embed_w, embedx_w...] with
        zeros past each key's allocated mf dim."""
        shape = keys.shape
        flat = np.ascontiguousarray(keys.reshape(-1), dtype=np.uint64)
        if self.accessor == ACCESSOR_CTR_DYMF:
            stride = 1 + self.dim
            out = np.empty((flat.size, stride), np.float32)
            self._lib.pscore_sparse_pull_dymf(
                self._h, u64_ptr(flat), flat.size, f32_ptr(out), stride)
            return out.reshape(*shape, stride)
        out = np.empty((flat.size, self.dim), np.float32)
        self._lib.pscore_sparse_pull(self._h, u64_ptr(flat), flat.size,
                                     f32_ptr(out))
        return out.reshape(*shape, self.dim)

    def push(self, keys: np.ndarray, grads: np.ndarray, shows=None,
             clicks=None, mf_dims=None, slots=None):
        """dymf tables: grads rows are [embed_g, embedx_g(dim)];
        `mf_dims` [n] gives each key's slot-configured mf dim (used the
        moment the key matures past embedx_threshold; defaults to the
        table max dim)."""
        flat = np.ascontiguousarray(keys.reshape(-1), dtype=np.uint64)
        sp = np.ascontiguousarray(np.asarray(shows).reshape(-1),
                                  np.float32) if shows is not None \
            else None
        cp = np.ascontiguousarray(np.asarray(clicks).reshape(-1),
                                  np.float32) if clicks is not None \
            else None
        if self.accessor == ACCESSOR_CTR_DYMF:
            stride = 1 + self.dim
            g = np.ascontiguousarray(grads.reshape(flat.size, stride),
                                     dtype=np.float32)
            md = np.ascontiguousarray(
                np.asarray(mf_dims).reshape(-1) if mf_dims is not None
                else np.full(flat.size, self.dim), np.int32)
            sl = np.ascontiguousarray(np.asarray(slots).reshape(-1),
                                      np.float32) if slots is not None \
                else None
            self._lib.pscore_sparse_push_dymf(
                self._h, u64_ptr(flat), i32_ptr(md), f32_ptr(g),
                flat.size, stride,
                f32_ptr(sp) if sp is not None else None,
                f32_ptr(cp) if cp is not None else None,
                f32_ptr(sl) if sl is not None else None)
            return
        g = np.ascontiguousarray(grads.reshape(flat.size, self.dim),
                                 dtype=np.float32)
        self._lib.pscore_sparse_push(self._h, u64_ptr(flat), f32_ptr(g),
                                     flat.size,
                                     f32_ptr(sp) if sp is not None
                                     else None,
                                     f32_ptr(cp) if cp is not None
                                     else None)

    def key_stats(self, key: int):
        """(show, click, mf_dim) of one key — show/click exact doubles
        for the ctr_double accessor. None if the key is absent."""
        import ctypes
        show = ctypes.c_double()
        click = ctypes.c_double()
        mf = (np.zeros(1, np.int32))
        rc = self._lib.pscore_sparse_key_stats(
            self._h, ctypes.c_uint64(int(key)), ctypes.byref(show),
            ctypes.byref(click), i32_ptr(mf))
        if rc != 0:
            return None
        return float(show.value), float(click.value), int(mf[0])

    @property
    def row_width(self):
        """Floats per key in pull/push payloads: dim, or 1+dim for dymf
        ([embed_w, embedx...]). The PS wire protocol sizes rows by this."""
        return 1 + self.dim if self.accessor == ACCESSOR_CTR_DYMF \
            else self.dim

    def __len__(self):
        return int(self._lib.pscore_sparse_size(self._h))

    def enable_spill(self, directory: str, max_mem_keys: int):
        """SSDSparseTable capability (`ps/table/ssd_sparse_table.h`,
        re-designed as log-structured per-shard files instead of rocksdb):
        keys beyond `max_mem_keys` spill to disk and are promoted back on
        touch. save()+load() compacts the logs."""
        import os
        os.makedirs(directory, exist_ok=True)
        rc = self._lib.pscore_sparse_enable_spill(
            self._h, directory.encode(), int(max_mem_keys))
        if rc != 0:
            raise IOError(f"enable_spill failed ({rc}): {directory}")

    def mem_size(self):
        return int(self._lib.pscore_sparse_mem_size(self._h))

    def spill_size(self):
        return int(self._lib.pscore_sparse_spill_size(self._h))

    def shrink(self, threshold=0.0, max_unseen_days=30):
        """Decay show/click + age + drop low-score features (Table::Shrink
        parity). Spilled entries are not decayed in place; they age when
        promoted back to memory."""
        return int(self._lib.pscore_sparse_shrink(
            self._h, float(threshold), int(max_unseen_days)))

    def save(self, path: str):
        rc = self._lib.pscore_sparse_save(self._h, path.encode())
        if rc != 0:
            raise IOError(f"sparse table save failed ({rc}): {path}")

    def load(self, path: str):
        rc = self._lib.pscore_sparse_load(self._h, path.encode())
        if rc != 0:
            raise IOError(f"sparse table load failed ({rc}): {path}")


class MemoryDenseTable:
    def __init__(self, size, sgd_rule="adam", learning_rate=0.01):
        self.size = int(size)
        self._lib = get_lib()
        rule = _RULES[sgd_rule] if isinstance(sgd_rule, str) else sgd_rule
        self._h = self._lib.pscore_dense_create(self.size, rule,
                                                float(learning_rate))

    def set(self, values: np.ndarray):
        v = np.ascontiguousarray(values.reshape(-1), np.float32)
        self._lib.pscore_dense_set(self._h, f32_ptr(v), v.size)

    def pull(self) -> np.ndarray:
        out = np.empty(self.size, np.float32)
        self._lib.pscore_dense_pull(self._h, f32_ptr(out), self.size)
        return out

    def push(self, grads: np.ndarray):
        g = np.ascontiguousarray(grads.reshape(-1), np.float32)
        self._lib.pscore_dense_push(self._h, f32_ptr(g), g.size)

    def add(self, delta: np.ndarray):
        """Geo-async merge: server adds a trainer's local delta instead of
        applying an SGD rule (communicator.h geo dense mode)."""
        d = np.ascontiguousarray(delta.reshape(-1), np.float32)
        self._lib.pscore_dense_add(self._h, f32_ptr(d), d.size)

    def save(self, path: str):
        np.save(path if path.endswith(".npy") else path + ".npy",
                self.pull())

    def load(self, path: str):
        self.set(np.load(path if path.endswith(".npy") else path + ".npy"))


class InMemoryDataset:
    """Parity: `paddle.distributed.InMemoryDataset`
    (`python/paddle/distributed/fleet/dataset/dataset.py`, C++
    `data_set.h:230 LoadIntoMemory`): slot-file loading, in-memory global
    shuffle, fixed-slot batch iteration — all native."""

    def __init__(self):
        self._lib = get_lib()
        self._h = self._lib.pscore_dataset_create()
        self._files = []
        self.slots = []
        self.batch_size = 32
        self.max_per_slot = 1

    def init(self, batch_size=32, use_var=None, slots=None,
             max_per_slot=1, **kw):
        self.batch_size = batch_size
        if slots is not None:
            self.slots = [int(s) for s in slots]
        self.max_per_slot = max_per_slot

    def set_filelist(self, files):
        self._files = list(files)

    def load_into_memory(self):
        for f in self._files:
            rc = self._lib.pscore_dataset_load_file(self._h, f.encode())
            if rc != 0:
                raise IOError(f"failed to load {f}")

    def load_from_generator(self, generator, files=None):
        """Parse raw input files through a fleet `DataGenerator`
        subclass (ps/data_generator.py — the user-parser API) into the
        native record pool. `files` defaults to the set_filelist()
        list; the generator's slot registry must align with the slot
        ids passed to init()."""
        import tempfile
        files = list(files) if files is not None else list(self._files)

        def lines():
            for path in files:
                with open(path) as fh:
                    yield from fh

        import os
        tmp = tempfile.NamedTemporaryFile("w", suffix=".slot",
                                          delete=False)
        try:
            with tmp:
                generator.run_from_iterable(lines(), write=tmp.write)
            rc = self._lib.pscore_dataset_load_file(self._h,
                                                    tmp.name.encode())
            if rc != 0:
                raise IOError("failed to load generated slot file")
        finally:
            os.unlink(tmp.name)

    def local_shuffle(self, fleet=None, seed=0):
        self._lib.pscore_dataset_shuffle(self._h, seed)

    def global_shuffle(self, fleet=None, seed=0, client=None,
                       worker_id=0, n_workers=1, key_prefix="gshuf"):
        """Cross-worker global shuffle (`data_set.h:230` GlobalShuffle
        parity): records route to workers by a shared content hash;
        shards exchange over the PS service's KV namespace + barrier.
        With one worker (or no client) it degrades to a local shuffle.

        `client`: a ps.service.PSClient shared by all workers (or pass
        a fleet whose `_ps_client`/worker info we can read)."""
        import ctypes
        if client is None and fleet is not None:
            client = getattr(fleet, "_ps_client", None)
            worker_id = getattr(fleet, "worker_index", lambda: 0)()
            n_workers = getattr(fleet, "worker_num", lambda: 1)()
        if client is None or n_workers <= 1:
            self._lib.pscore_dataset_shuffle(self._h, seed)
            return
        lib = self._lib
        # 1) publish every remote-bound shard
        for dst in range(n_workers):
            if dst == worker_id:
                continue
            nb = lib.pscore_dataset_extract_size(self._h, dst, n_workers,
                                                 seed)
            buf = ctypes.create_string_buffer(max(int(nb), 1))
            lib.pscore_dataset_extract(self._h, dst, n_workers, seed, buf)
            client.kv_set(f"{key_prefix}/{worker_id}/{dst}",
                          buf.raw[:int(nb)])
        client.barrier(n_workers)
        # 2) keep only my records, ingest everyone else's shard for me
        lib.pscore_dataset_retain(self._h, worker_id, n_workers, seed)
        for src in range(n_workers):
            if src == worker_id:
                continue
            blob = client.kv_get(f"{key_prefix}/{src}/{worker_id}")
            if blob:
                rc = lib.pscore_dataset_ingest(self._h, blob, len(blob))
                if rc < 0:
                    raise IOError("global_shuffle: truncated shard blob")
        # 3) local order randomisation (seed varies per worker so ranks
        # don't iterate in lockstep) + leave no stale blobs behind
        self._lib.pscore_dataset_shuffle(self._h, seed + 1 + worker_id)
        client.barrier(n_workers)

    def get_memory_data_size(self, fleet=None):
        return int(self._lib.pscore_dataset_size(self._h))

    def rewind(self):
        self._lib.pscore_dataset_rewind(self._h)

    def __iter__(self):
        self.rewind()
        n_slots = len(self.slots)
        slot_arr = np.asarray(self.slots, np.int32)
        while True:
            keys = np.zeros((self.batch_size, n_slots, self.max_per_slot),
                            np.uint64)
            labels = np.zeros(self.batch_size, np.float32)
            n = self._lib.pscore_dataset_next_batch(
                self._h, self.batch_size, i32_ptr(slot_arr), n_slots,
                self.max_per_slot, u64_ptr(keys), f32_ptr(labels))
            if n <= 0:
                return
            yield keys[:n], labels[:n]
