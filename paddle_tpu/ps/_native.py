"""ctypes loader for the native ps_core library; builds on first import.

The reference's pybind bridge role (`paddle/fluid/pybind/`) is played by a
plain C ABI + ctypes (pybind11 is not in this image); numpy arrays pass
zero-copy via ctypes pointers.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "csrc", "ps_core.cpp")
_LIB = os.path.join(_HERE, "csrc", "libps_core.so")

_lib = None


def _build():
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", _SRC,
           "-o", _LIB, "-lpthread"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native ps_core build failed ({' '.join(cmd)}):\n"
            f"{proc.stderr[-4000:]}")


def get_lib():
    global _lib
    if _lib is not None:
        return _lib
    if (not os.path.exists(_LIB)) or \
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        _build()
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError:
        # stale/foreign binary (e.g. different arch): rebuild from source
        _build()
        lib = ctypes.CDLL(_LIB)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    f32p = ctypes.POINTER(ctypes.c_float)
    i32p = ctypes.POINTER(ctypes.c_int)

    lib.pscore_sparse_create.argtypes = [ctypes.c_int, ctypes.c_int,
                                         ctypes.c_float, ctypes.c_float]
    lib.pscore_sparse_create.restype = ctypes.c_int
    lib.pscore_sparse_pull.argtypes = [ctypes.c_int, u64p, ctypes.c_int,
                                       f32p]
    lib.pscore_sparse_push.argtypes = [ctypes.c_int, u64p, f32p,
                                       ctypes.c_int, f32p, f32p]
    lib.pscore_sparse_size.argtypes = [ctypes.c_int]
    lib.pscore_sparse_size.restype = ctypes.c_int64
    lib.pscore_sparse_enable_spill.argtypes = [ctypes.c_int,
                                               ctypes.c_char_p,
                                               ctypes.c_int64]
    lib.pscore_sparse_enable_spill.restype = ctypes.c_int
    lib.pscore_sparse_mem_size.argtypes = [ctypes.c_int]
    lib.pscore_sparse_mem_size.restype = ctypes.c_int64
    lib.pscore_sparse_spill_size.argtypes = [ctypes.c_int]
    lib.pscore_sparse_spill_size.restype = ctypes.c_int64
    lib.pscore_sparse_shrink.argtypes = [ctypes.c_int, ctypes.c_float,
                                         ctypes.c_int]
    lib.pscore_sparse_shrink.restype = ctypes.c_int64
    lib.pscore_sparse_save.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.pscore_sparse_save.restype = ctypes.c_int
    lib.pscore_sparse_load.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.pscore_sparse_load.restype = ctypes.c_int
    # accessor-family API (CtrCommon/CtrDouble/CtrDymf)
    lib.pscore_sparse_create2.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_float, ctypes.c_float,
        ctypes.c_int, ctypes.c_float]
    lib.pscore_sparse_create2.restype = ctypes.c_int
    lib.pscore_sparse_accessor.argtypes = [ctypes.c_int]
    lib.pscore_sparse_accessor.restype = ctypes.c_int
    lib.pscore_sparse_pull_dymf.argtypes = [
        ctypes.c_int, u64p, ctypes.c_int, f32p, ctypes.c_int]
    lib.pscore_sparse_push_dymf.argtypes = [
        ctypes.c_int, u64p, i32p, f32p, ctypes.c_int, ctypes.c_int,
        f32p, f32p, f32p]
    lib.pscore_sparse_key_stats.argtypes = [
        ctypes.c_int, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        i32p]
    lib.pscore_sparse_key_stats.restype = ctypes.c_int

    lib.pscore_dense_create.argtypes = [ctypes.c_int64, ctypes.c_int,
                                        ctypes.c_float]
    lib.pscore_dense_create.restype = ctypes.c_int
    lib.pscore_dense_set.argtypes = [ctypes.c_int, f32p, ctypes.c_int64]
    lib.pscore_dense_pull.argtypes = [ctypes.c_int, f32p, ctypes.c_int64]
    lib.pscore_dense_push.argtypes = [ctypes.c_int, f32p, ctypes.c_int64]
    lib.pscore_dense_add.argtypes = [ctypes.c_int, f32p, ctypes.c_int64]

    lib.pscore_dataset_create.restype = ctypes.c_int
    lib.pscore_dataset_load_file.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.pscore_dataset_load_file.restype = ctypes.c_int
    lib.pscore_dataset_shuffle.argtypes = [ctypes.c_int, ctypes.c_uint64]
    lib.pscore_dataset_size.argtypes = [ctypes.c_int]
    lib.pscore_dataset_size.restype = ctypes.c_int64
    lib.pscore_dataset_rewind.argtypes = [ctypes.c_int]
    lib.pscore_dataset_next_batch.argtypes = [
        ctypes.c_int, ctypes.c_int, i32p, ctypes.c_int, ctypes.c_int,
        u64p, f32p]
    lib.pscore_dataset_next_batch.restype = ctypes.c_int
    lib.pscore_dataset_extract_size.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint64]
    lib.pscore_dataset_extract_size.restype = ctypes.c_int64
    lib.pscore_dataset_extract.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
        ctypes.c_char_p]
    lib.pscore_dataset_extract.restype = ctypes.c_int64
    lib.pscore_dataset_retain.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint64]
    lib.pscore_dataset_ingest.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int64]
    lib.pscore_dataset_ingest.restype = ctypes.c_int64
    _lib = lib
    return lib


def u64_ptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def f32_ptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def i32_ptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int))
