// ps_core — native parameter-server engine for paddle_tpu.
//
// Reference parity (re-designed, not ported):
//   - MemorySparseTable (paddle/fluid/distributed/ps/table/
//     memory_sparse_table.h): shard-parallel hash tables keyed by uint64
//     feature ids, values = accessor-defined float blocks.
//   - Accessor + SGD rules (ps/table/ctr_accessor.h, sparse_sgd_rule.h):
//     CTR-style value layout [show, click, slot, emb(dim), g2sum(dim)]
//     with naive / adagrad / adam update applied IN the table on push
//     (the HeterPS optimizer.cuh.h "SGD inside the table" capability,
//     executed on host CPU feeding the TPU step).
//   - MemoryDenseTable (ps/table/memory_dense_table.h): flat dense params.
//   - DataFeed/Dataset channels (framework/data_feed.h, data_set.h:230
//     LoadIntoMemory + shuffle): slot-file parser + in-memory record pool.
//
// Plain C ABI (loaded via ctypes; no pybind dependency). Thread-safe per
// shard; bulk ops fan out over an internal thread pool.
//
// Build: g++ -O3 -march=native -std=c++17 -shared -fPIC ps_core.cpp -o libps_core.so -lpthread

#include <atomic>
#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kShardBits = 6;
constexpr int kShards = 1 << kShardBits;  // 64 shards

enum SgdRule : int { kNaive = 0, kAdaGrad = 1, kAdam = 2 };

// Accessor families (parity: ps/table/ctr_accessor.h,
// ctr_double_accessor.h:29, ctr_dymf_accessor.h:30 — semantics
// re-implemented, layouts our own):
//   kCtrCommon — float show/click, fixed embedding dim.
//   kCtrDouble — show/click accumulated in DOUBLE precision (stored in
//     two float slots each): at billions of impressions a float show
//     count stops absorbing +1 increments; the double variant keeps
//     CTR statistics exact.
//   kCtrDymf   — dynamic-mf: per-key embedding dim. Every key carries a
//     1-d embed_w from birth; the mf block (embedx_w, mf_dim floats) is
//     only allocated once the key's CTR score
//     (nonclk_coeff*(show-click) + clk_coeff*click) crosses
//     embedx_threshold (reference NeedExtendMF), with the dim supplied
//     by the slot's config at that push.
enum Accessor : int { kCtrCommon = 0, kCtrDouble = 1, kCtrDymf = 2 };

struct TableConfig {
  int dim = 8;             // embedding dim (common/double; max dim for dymf)
  int rule = kAdaGrad;
  float lr = 0.05f;
  float initial_range = 0.02f;
  float initial_g2sum = 3.0f;
  float beta1 = 0.9f, beta2 = 0.999f, eps = 1e-8f;
  float nonclk_coeff = 0.1f, clk_coeff = 1.0f;  // show/click score
  float decay_rate = 0.98f;  // show/click decay on shrink
  int accessor = kCtrCommon;
  float embedx_threshold = 10.0f;  // dymf mf-creation score threshold
};

// value block layouts:
//
// kCtrCommon (v1-compatible):
//   [0] show  [1] click  [2] unseen_days  [3..3+dim) w
//   adagrad: [3+dim .. 3+2*dim) g2sum
//   adam:    [3+dim..3+2dim) m, [3+2dim..3+3dim) v, [3+3dim] beta1_pow,
//            [3+3dim+1] beta2_pow
//
// kCtrDouble:
//   [0..1] show (double)  [2..3] click (double)  [4] unseen_days
//   [5..5+dim) w, then opt block (adagrad: g2sum[dim];
//   adam: m[dim], v[dim], b1p, b2p)
//
// kCtrDymf (variable length per key):
//   [0] show [1] click [2] unseen_days [3] slot [4] mf_dim [5] embed_w
//   [6..6+eol) embed opt block (naive: 0, adagrad: g2sum,
//   adam: m, v, b1p, b2p)
//   then, once matured (score >= embedx_threshold), the mf block:
//   embedx_w[mf], + opt (adagrad: g2sum[mf]; adam: m[mf], v[mf], b1p,
//   b2p)
struct SparseTable {
  TableConfig cfg;
  int value_len;
  std::unordered_map<uint64_t, std::vector<float>> shards[kShards];
  std::mutex locks[kShards];
  std::mt19937 rngs[kShards];

  // Spill mode (SSDSparseTable capability, ssd_sparse_table.h parity
  // re-designed: log-structured per-shard files instead of rocksdb).
  // Values past the per-shard memory budget are appended to a shard file
  // and indexed by offset; touching a spilled key promotes it back to
  // memory (evicting another). The log holds stale copies of re-promoted
  // keys; save()+load() compacts.
  bool spill_enabled = false;
  int64_t mem_budget_shard = 0;
  std::string spill_dir;
  std::unordered_map<uint64_t, int64_t> spill_idx[kShards];
  FILE* spill_f[kShards] = {nullptr};

  explicit SparseTable(const TableConfig& c) : cfg(c) {
    switch (cfg.accessor) {
      case kCtrDouble:
        value_len = 5 + cfg.dim + opt_len(cfg.dim);
        break;
      case kCtrDymf:
        // base length; the embedx block extends per key on maturation
        value_len = 6 + opt_len(1);
        break;
      default:  // kCtrCommon keeps its historical (v1) layout: the adam
        // block reserves 3*dim+2 even though m,v,pows use 2*dim+2, so
        // existing v1 save files load bit-identically
        value_len = 3 + cfg.dim +
            (cfg.rule == kAdaGrad ? cfg.dim
             : cfg.rule == kAdam ? 3 * cfg.dim + 2 : 0);
    }
    for (int i = 0; i < kShards; i++) rngs[i].seed(1234 + i);
  }

  // generic opt-state block length for `dim` weights
  int opt_len(int dim) const {
    if (cfg.rule == kAdaGrad) return dim;
    if (cfg.rule == kAdam) return 2 * dim + 2;
    return 0;
  }

  // offset of the weight block (common/double)
  int w_off() const { return cfg.accessor == kCtrDouble ? 5 : 3; }

  // --- accessor-generic show/click/unseen ---------------------------
  double get_show(const std::vector<float>& v) const {
    if (cfg.accessor == kCtrDouble) {
      double d;
      std::memcpy(&d, v.data(), sizeof(double));
      return d;
    }
    return v[0];
  }
  double get_click(const std::vector<float>& v) const {
    if (cfg.accessor == kCtrDouble) {
      double d;
      std::memcpy(&d, v.data() + 2, sizeof(double));
      return d;
    }
    return v[1];
  }
  void add_show_click(std::vector<float>& v, float show, float click) {
    if (cfg.accessor == kCtrDouble) {
      double s, c;
      std::memcpy(&s, v.data(), sizeof(double));
      std::memcpy(&c, v.data() + 2, sizeof(double));
      s += show;
      c += click;
      std::memcpy(v.data(), &s, sizeof(double));
      std::memcpy(v.data() + 2, &c, sizeof(double));
    } else {
      v[0] += show;
      v[1] += click;
    }
  }
  void scale_show_click(std::vector<float>& v, float f) {
    if (cfg.accessor == kCtrDouble) {
      double s, c;
      std::memcpy(&s, v.data(), sizeof(double));
      std::memcpy(&c, v.data() + 2, sizeof(double));
      s *= f;
      c *= f;
      std::memcpy(v.data(), &s, sizeof(double));
      std::memcpy(v.data() + 2, &c, sizeof(double));
    } else {
      v[0] *= f;
      v[1] *= f;
    }
  }
  int unseen_off() const {
    return cfg.accessor == kCtrDouble ? 4 : 2;
  }
  float score_of(const std::vector<float>& v) const {
    double show = get_show(v), click = get_click(v);
    return (float)(cfg.nonclk_coeff * (show - click) +
                   cfg.clk_coeff * click);
  }

  // apply the SGD rule to `dim` weights at w, opt block at opt
  // (layout: adagrad g2sum[dim]; adam m[dim], v[dim], b1p, b2p)
  void apply_rule(float* w, float* opt, const float* grad, int dim) {
    switch (cfg.rule) {
      case kNaive:
        for (int d = 0; d < dim; d++) w[d] -= cfg.lr * grad[d];
        break;
      case kAdaGrad:
        for (int d = 0; d < dim; d++) {
          opt[d] += grad[d] * grad[d];
          w[d] -= cfg.lr * grad[d] / std::sqrt(opt[d] + cfg.eps);
        }
        break;
      case kAdam: {
        float* m = opt;
        float* vv = opt + dim;
        float& b1p = opt[2 * dim];
        float& b2p = opt[2 * dim + 1];
        b1p *= cfg.beta1;
        b2p *= cfg.beta2;
        for (int d = 0; d < dim; d++) {
          m[d] = cfg.beta1 * m[d] + (1 - cfg.beta1) * grad[d];
          vv[d] = cfg.beta2 * vv[d] + (1 - cfg.beta2) * grad[d] * grad[d];
          float mhat = m[d] / (1 - b1p);
          float vhat = vv[d] / (1 - b2p);
          w[d] -= cfg.lr * mhat / (std::sqrt(vhat) + cfg.eps);
        }
        break;
      }
    }
  }

  void init_opt(float* opt, int dim) {
    if (cfg.rule == kAdaGrad) {
      for (int d = 0; d < dim; d++) opt[d] = cfg.initial_g2sum;
    } else if (cfg.rule == kAdam) {
      opt[2 * dim] = 1.0f;      // beta1_pow
      opt[2 * dim + 1] = 1.0f;  // beta2_pow
    }
  }

  // --- dymf helpers --------------------------------------------------
  int dymf_base_len() const { return 6 + opt_len(1); }
  int dymf_mf(const std::vector<float>& v) const { return (int)v[4]; }

  // allocate the embedx block with `mf` dims (reference NeedExtendMF /
  // CreateValue stage-2); call under shard lock
  void dymf_extend(std::vector<float>& v, int mf, int s) {
    std::uniform_real_distribution<float> dist(-cfg.initial_range,
                                               cfg.initial_range);
    size_t base = v.size();
    v.resize(base + mf + opt_len(mf), 0.0f);
    for (int d = 0; d < mf; d++) v[base + d] = dist(rngs[s]);
    init_opt(v.data() + base + mf, mf);
    v[4] = (float)mf;
  }

  ~SparseTable() {
    for (int s = 0; s < kShards; s++) {
      if (spill_f[s]) std::fclose(spill_f[s]);
    }
  }

  int enable_spill(const char* dir, int64_t max_mem_keys) {
    if (cfg.accessor == kCtrDymf) return -2;  // variable-length values
    if (spill_enabled) {
      // already spilling: only adjust the budget — re-opening "wb+"
      // would truncate logs that live spill_idx offsets point into
      mem_budget_shard = std::max<int64_t>(1, max_mem_keys / kShards);
      for (int s = 0; s < kShards; s++) {
        std::lock_guard<std::mutex> g(locks[s]);
        evict_to_budget(s, 0);
      }
      return 0;
    }
    // open all shard logs before flipping any state so a mid-loop
    // failure leaves the table fully un-spilled
    FILE* files[kShards] = {nullptr};
    for (int s = 0; s < kShards; s++) {
      std::string p = std::string(dir) + "/spill_" + std::to_string(s) +
          ".bin";
      files[s] = std::fopen(p.c_str(), "wb+");
      if (!files[s]) {
        for (int j = 0; j < s; j++) std::fclose(files[j]);
        return -1;
      }
    }
    spill_dir = dir;
    mem_budget_shard = std::max<int64_t>(1, max_mem_keys / kShards);
    for (int s = 0; s < kShards; s++) spill_f[s] = files[s];
    spill_enabled = true;
    for (int s = 0; s < kShards; s++) {
      std::lock_guard<std::mutex> g(locks[s]);
      evict_to_budget(s, 0);
    }
    return 0;
  }

  static int shard_of(uint64_t key) {
    // mix then take low bits
    uint64_t h = key * 0x9E3779B97F4A7C15ull;
    return static_cast<int>((h >> 32) & (kShards - 1));
  }

  // under shard lock. Evicts arbitrary (hash-order) residents until the
  // shard fits its budget; `protect` is never evicted.
  void evict_to_budget(int s, uint64_t protect) {
    if (!spill_enabled) return;
    auto& mp = shards[s];
    while ((int64_t)mp.size() > mem_budget_shard) {
      auto it = mp.begin();
      if (it->first == protect) {
        ++it;
        if (it == mp.end()) break;
      }
      std::fseek(spill_f[s], 0, SEEK_END);
      int64_t off = std::ftell(spill_f[s]);
      if (std::fwrite(it->second.data(), sizeof(float), value_len,
                      spill_f[s]) != (size_t)value_len) {
        // short write (disk full): keep the entry in memory rather than
        // indexing truncated data that would later read back "corrupt"
        // and silently re-initialize trained weights
        break;
      }
      spill_idx[s][it->first] = off;
      mp.erase(it);
    }
  }

  std::vector<float>& get_or_init(uint64_t key, int s) {
    auto it = shards[s].find(key);
    if (it != shards[s].end()) return it->second;
    if (spill_enabled) {
      auto sit = spill_idx[s].find(key);
      if (sit != spill_idx[s].end()) {
        std::vector<float> v(value_len);
        std::fseek(spill_f[s], sit->second, SEEK_SET);
        if (std::fread(v.data(), sizeof(float), value_len, spill_f[s]) ==
            (size_t)value_len) {
          spill_idx[s].erase(sit);
          auto& ref = shards[s].emplace(key, std::move(v)).first->second;
          evict_to_budget(s, key);  // node-based map: ref stays valid
          return ref;
        }
        spill_idx[s].erase(sit);  // corrupt entry: fall through to init
      }
    }
    std::vector<float> v(value_len, 0.0f);
    std::uniform_real_distribution<float> dist(-cfg.initial_range,
                                               cfg.initial_range);
    switch (cfg.accessor) {
      case kCtrDouble:
        for (int i = 0; i < cfg.dim; i++) v[5 + i] = dist(rngs[s]);
        init_opt(v.data() + 5 + cfg.dim, cfg.dim);
        break;
      case kCtrDymf:
        v[5] = dist(rngs[s]);          // embed_w; mf_dim starts 0
        init_opt(v.data() + 6, 1);
        break;
      default:
        for (int i = 0; i < cfg.dim; i++) v[3 + i] = dist(rngs[s]);
        init_opt(v.data() + 3 + cfg.dim, cfg.dim);
    }
    auto& ref = shards[s].emplace(key, std::move(v)).first->second;
    evict_to_budget(s, key);
    return ref;
  }

  void pull(const uint64_t* keys, int n, float* out) {
    // kCtrDymf values are variable-length ([.., embed_w, mf...]); the
    // fixed-stride generic path would read cfg.dim floats past embed_w
    // (heap overflow on immature rows). Route to the dymf layout
    // (stride = 1 + dim, matching RemoteSparseTable.row_width).
    if (cfg.accessor == kCtrDymf) {
      pull_dymf(keys, n, out, 1 + cfg.dim);
      return;
    }
    const int woff = w_off();
    parallel_for(n, [&](int i) {
      uint64_t k = keys[i];
      int s = shard_of(k);
      std::lock_guard<std::mutex> g(locks[s]);
      auto& v = get_or_init(k, s);
      std::memcpy(out + (size_t)i * cfg.dim, v.data() + woff,
                  sizeof(float) * cfg.dim);
    });
  }

  void push(const uint64_t* keys, const float* grads, int n,
            const float* shows, const float* clicks) {
    // see pull(): generic fixed-stride writes on kCtrDymf rows would
    // overflow immature (mf-unallocated) values — route to the dymf
    // path with the default mf dim.
    if (cfg.accessor == kCtrDymf) {
      push_dymf(keys, nullptr, grads, n, 1 + cfg.dim, shows, clicks,
                nullptr);
      return;
    }
    const int woff = w_off();
    parallel_for(n, [&](int i) {
      uint64_t k = keys[i];
      int s = shard_of(k);
      std::lock_guard<std::mutex> g(locks[s]);
      auto& v = get_or_init(k, s);
      add_show_click(v, shows ? shows[i] : 0.0f,
                     clicks ? clicks[i] : 0.0f);
      v[unseen_off()] = 0.0f;  // unseen_days reset
      apply_rule(v.data() + woff, v.data() + woff + cfg.dim,
                 grads + (size_t)i * cfg.dim, cfg.dim);
    });
  }

  // dymf pull: out row i = [embed_w, embedx_w(min(alloc, stride-1)),
  // zeros...]; rows whose mf block is unallocated read embed_w + zeros
  void pull_dymf(const uint64_t* keys, int n, float* out, int stride) {
    parallel_for(n, [&](int i) {
      uint64_t k = keys[i];
      int s = shard_of(k);
      std::lock_guard<std::mutex> g(locks[s]);
      auto& v = get_or_init(k, s);
      float* row = out + (size_t)i * stride;
      std::memset(row, 0, sizeof(float) * stride);
      row[0] = v[5];
      int mf = std::min(dymf_mf(v), stride - 1);
      if (mf > 0) {
        std::memcpy(row + 1, v.data() + dymf_base_len(),
                    sizeof(float) * mf);
      }
    });
  }

  // dymf push: grads row i = [embed_g, embedx_g(mf_dims[i])]; a key
  // matures (allocates its mf block at mf_dims[i]) when its CTR score
  // crosses cfg.embedx_threshold
  void push_dymf(const uint64_t* keys, const int* mf_dims,
                 const float* grads, int n, int stride,
                 const float* shows, const float* clicks,
                 const float* slots) {
    parallel_for(n, [&](int i) {
      uint64_t k = keys[i];
      int s = shard_of(k);
      std::lock_guard<std::mutex> g(locks[s]);
      auto& v = get_or_init(k, s);
      add_show_click(v, shows ? shows[i] : 0.0f,
                     clicks ? clicks[i] : 0.0f);
      v[2] = 0.0f;
      if (slots) v[3] = slots[i];
      const float* grad = grads + (size_t)i * stride;
      apply_rule(v.data() + 5, v.data() + 6, grad, 1);  // embed_w
      int mf = dymf_mf(v);
      const int mfd_i = mf_dims ? mf_dims[i] : cfg.dim;
      if (mf == 0 && mfd_i > 0 &&
          score_of(v) >= cfg.embedx_threshold) {
        // clamp to the push stride (= table max dim): an oversized
        // slot config would otherwise allocate an mf block no push
        // could ever update
        int want = std::min(mfd_i, stride - 1);
        dymf_extend(v, want, s);
        mf = want;
      }
      if (mf > 0 && stride - 1 >= mf) {
        // partial-gradient pushes (stride-1 < mf) are rejected rather
        // than mis-indexing the opt block (adam pows live at 2*mf)
        int base = dymf_base_len();
        apply_rule(v.data() + base, v.data() + base + mf, grad + 1, mf);
      }
    });
  }

  // test/introspection: exact show/click + mf dim of one key
  int key_stats(uint64_t key, double* show, double* click, int* mf) {
    int s = shard_of(key);
    std::lock_guard<std::mutex> g(locks[s]);
    auto it = shards[s].find(key);
    if (it == shards[s].end()) return -1;
    *show = get_show(it->second);
    *click = get_click(it->second);
    *mf = cfg.accessor == kCtrDymf ? dymf_mf(it->second) : cfg.dim;
    return 0;
  }

  // one pass of day-level maintenance: decay show/click, age features,
  // drop features whose score is below threshold (Table::Shrink parity)
  int64_t shrink(float score_threshold, int max_unseen_days) {
    std::atomic<int64_t> removed{0};
    std::vector<std::thread> ts;
    const int uoff = unseen_off();
    for (int s = 0; s < kShards; s++) {
      ts.emplace_back([&, s]() {
        std::lock_guard<std::mutex> g(locks[s]);
        auto& mp = shards[s];
        for (auto it = mp.begin(); it != mp.end();) {
          auto& v = it->second;
          scale_show_click(v, cfg.decay_rate);
          v[uoff] += 1.0f;
          if (score_of(v) < score_threshold &&
              v[uoff] > static_cast<float>(max_unseen_days)) {
            it = mp.erase(it);
            removed++;
          } else {
            ++it;
          }
        }
      });
    }
    for (auto& t : ts) t.join();
    return removed.load();
  }

  int64_t mem_size() const {
    int64_t n = 0;
    for (int s = 0; s < kShards; s++) n += (int64_t)shards[s].size();
    return n;
  }

  int64_t spill_size() const {
    int64_t n = 0;
    for (int s = 0; s < kShards; s++) n += (int64_t)spill_idx[s].size();
    return n;
  }

  int64_t size() const { return mem_size() + spill_size(); }

  // save format v2 (versioned — VERDICT r3 #3): magic "PSC2", then
  // accessor/rule/dim config, then (key, len, floats[len]) entries so
  // dymf's variable-length values round-trip. v1 files (no magic:
  // total + value_len header) still load for kCtrCommon tables.
  static constexpr uint32_t kMagicV2 = 0x32435350u;  // "PSC2" LE

  int save(const char* path) {
    FILE* f = std::fopen(path, "wb");
    if (!f) return -1;
    int64_t total = size();
    std::fwrite(&kMagicV2, sizeof(kMagicV2), 1, f);
    int32_t hdr[3] = {cfg.accessor, cfg.rule, cfg.dim};
    std::fwrite(hdr, sizeof(int32_t), 3, f);
    std::fwrite(&total, sizeof(total), 1, f);
    for (int s = 0; s < kShards; s++) {
      std::lock_guard<std::mutex> g(locks[s]);
      for (auto& kv : shards[s]) {
        int32_t len = (int32_t)kv.second.size();
        std::fwrite(&kv.first, sizeof(uint64_t), 1, f);
        std::fwrite(&len, sizeof(len), 1, f);
        std::fwrite(kv.second.data(), sizeof(float), len, f);
      }
      // spilled entries stream out of the shard log (this is also the
      // compaction point: a later load() rebuilds a dense log)
      std::vector<float> v(value_len);
      for (auto& kv : spill_idx[s]) {
        std::fseek(spill_f[s], kv.second, SEEK_SET);
        if (std::fread(v.data(), sizeof(float), value_len, spill_f[s]) !=
            (size_t)value_len) {
          std::fclose(f);
          return -4;
        }
        int32_t len = value_len;
        std::fwrite(&kv.first, sizeof(uint64_t), 1, f);
        std::fwrite(&len, sizeof(len), 1, f);
        std::fwrite(v.data(), sizeof(float), len, f);
      }
    }
    std::fclose(f);
    return 0;
  }

  int load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    uint32_t magic = 0;
    if (std::fread(&magic, sizeof(magic), 1, f) != 1) {
      std::fclose(f);
      return -2;
    }
    if (magic != kMagicV2) {
      // v1 legacy: [int64 total][int32 value_len] fixed-len entries
      // (only ever written by kCtrCommon tables)
      std::rewind(f);
      if (cfg.accessor != kCtrCommon) {
        std::fclose(f);
        return -5;
      }
      int64_t total = 0;
      int vl = 0;
      if (std::fread(&total, sizeof(total), 1, f) != 1 ||
          std::fread(&vl, sizeof(vl), 1, f) != 1 || vl != value_len) {
        std::fclose(f);
        return -2;
      }
      for (int64_t i = 0; i < total; i++) {
        uint64_t k;
        std::vector<float> v(value_len);
        if (std::fread(&k, sizeof(k), 1, f) != 1 ||
            std::fread(v.data(), sizeof(float), value_len, f) !=
                (size_t)value_len) {
          std::fclose(f);
          return -3;
        }
        insert_loaded(k, std::move(v));
      }
      std::fclose(f);
      return 0;
    }
    int32_t hdr[3];
    int64_t total = 0;
    if (std::fread(hdr, sizeof(int32_t), 3, f) != 3 ||
        std::fread(&total, sizeof(total), 1, f) != 1 ||
        hdr[0] != cfg.accessor || hdr[1] != cfg.rule ||
        hdr[2] != cfg.dim) {
      std::fclose(f);
      return -2;
    }
    for (int64_t i = 0; i < total; i++) {
      uint64_t k;
      int32_t len;
      if (std::fread(&k, sizeof(k), 1, f) != 1 ||
          std::fread(&len, sizeof(len), 1, f) != 1 || len <= 0 ||
          len > (1 << 20)) {
        std::fclose(f);
        return -3;
      }
      std::vector<float> v(len);
      if (std::fread(v.data(), sizeof(float), len, f) != (size_t)len) {
        std::fclose(f);
        return -3;
      }
      // structural validation: a truncated/corrupt entry must fail the
      // load, not become an under-sized value that later reads/writes
      // out of bounds in push/pull
      if (cfg.accessor == kCtrDymf) {
        int mf = (len >= 5) ? (int)v[4] : -1;
        bool ok = mf >= 0 && mf <= cfg.dim &&
            len == dymf_base_len() + (mf > 0 ? mf + opt_len(mf) : 0);
        if (!ok) {
          std::fclose(f);
          return -6;
        }
      } else if (len != value_len) {
        std::fclose(f);
        return -6;
      }
      insert_loaded(k, std::move(v));
    }
    std::fclose(f);
    return 0;
  }

  void insert_loaded(uint64_t k, std::vector<float>&& v) {
    int s = shard_of(k);
    std::lock_guard<std::mutex> g(locks[s]);
    shards[s][k] = std::move(v);
    spill_idx[s].erase(k);
    evict_to_budget(s, k);
  }

  template <typename F>
  static void parallel_for(int n, F&& fn) {
    int nthreads = std::min<int>(std::thread::hardware_concurrency(),
                                 std::max(1, n / 4096));
    if (nthreads <= 1) {
      for (int i = 0; i < n; i++) fn(i);
      return;
    }
    std::vector<std::thread> ts;
    std::atomic<int> next{0};
    for (int t = 0; t < nthreads; t++) {
      ts.emplace_back([&]() {
        constexpr int kChunk = 1024;
        while (true) {
          int start = next.fetch_add(kChunk);
          if (start >= n) break;
          int end = std::min(n, start + kChunk);
          for (int i = start; i < end; i++) fn(i);
        }
      });
    }
    for (auto& t : ts) t.join();
  }
};

struct DenseTable {
  std::vector<float> data;
  std::vector<float> m, v;  // adam state
  float lr = 0.01f;
  int rule = kNaive;
  float beta1 = 0.9f, beta2 = 0.999f, eps = 1e-8f;
  int64_t step = 0;
  std::mutex lock;
};

// ------------------------------------------------------------ DataFeed
// Slot-record text parser (MultiSlotDataFeed capability):
// each line: "<label> <slot_id>:<feature_sign> <slot_id>:<feature_sign> ..."
struct Record {
  float label;
  std::vector<std::pair<int, uint64_t>> feats;  // (slot, sign)
};

struct Dataset {
  std::vector<Record> records;
  std::mutex lock;
  std::atomic<int64_t> cursor{0};

  int load_file(const char* path) {
    FILE* f = std::fopen(path, "r");
    if (!f) return -1;
    char line[1 << 16];
    std::vector<Record> local;
    while (std::fgets(line, sizeof(line), f)) {
      Record r;
      char* save = nullptr;
      char* tok = strtok_r(line, " \t\n", &save);
      if (!tok) continue;
      r.label = std::strtof(tok, nullptr);
      while ((tok = strtok_r(nullptr, " \t\n", &save))) {
        char* colon = std::strchr(tok, ':');
        if (!colon) continue;
        *colon = 0;
        int slot = std::atoi(tok);
        uint64_t sign = std::strtoull(colon + 1, nullptr, 10);
        r.feats.emplace_back(slot, sign);
      }
      // skip malformed lines that parsed no features (a bare token would
      // otherwise become a label-0 empty record and pollute training)
      if (r.feats.empty()) continue;
      local.push_back(std::move(r));
    }
    std::fclose(f);
    std::lock_guard<std::mutex> g(lock);
    for (auto& r : local) records.push_back(std::move(r));
    return 0;
  }

  void shuffle(uint64_t seed) {
    std::lock_guard<std::mutex> g(lock);
    std::mt19937_64 rng(seed);
    std::shuffle(records.begin(), records.end(), rng);
    cursor = 0;
  }

  // fixed-slot batch: out_keys [batch, n_slots, max_feats_per_slot]
  // (0-padded), out_labels [batch]; returns #rows filled
  int next_batch(int batch, const int* slot_ids, int n_slots,
                 int max_per_slot, uint64_t* out_keys, float* out_labels) {
    int64_t start = cursor.fetch_add(batch);
    if (start >= (int64_t)records.size()) return 0;
    int nrows = std::min<int64_t>(batch, records.size() - start);
    std::memset(out_keys, 0,
                sizeof(uint64_t) * (size_t)batch * n_slots * max_per_slot);
    for (int i = 0; i < nrows; i++) {
      const Record& r = records[start + i];
      out_labels[i] = r.label;
      std::vector<int> counts(n_slots, 0);
      for (auto& f : r.feats) {
        for (int sidx = 0; sidx < n_slots; sidx++) {
          if (slot_ids[sidx] == f.first && counts[sidx] < max_per_slot) {
            out_keys[((size_t)i * n_slots + sidx) * max_per_slot +
                     counts[sidx]] = f.second;
            counts[sidx]++;
            break;
          }
        }
      }
    }
    return nrows;
  }
};

std::vector<SparseTable*> g_sparse;
std::vector<DenseTable*> g_dense;
std::vector<Dataset*> g_datasets;
std::mutex g_reg_lock;

}  // namespace

extern "C" {

// ---------------------------------------------------------- sparse table
int pscore_sparse_create(int dim, int rule, float lr, float initial_range) {
  std::lock_guard<std::mutex> g(g_reg_lock);
  TableConfig cfg;
  cfg.dim = dim;
  cfg.rule = rule;
  cfg.lr = lr;
  cfg.initial_range = initial_range;
  if (rule == kAdaGrad) cfg.initial_g2sum = 0.0f;
  g_sparse.push_back(new SparseTable(cfg));
  return (int)g_sparse.size() - 1;
}

// accessor-selecting constructor (CtrCommon=0 / CtrDouble=1 / CtrDymf=2;
// table-config accessor_class parity). For dymf, `dim` is the max mf
// dim (pull/push strides) and embedx_threshold gates mf creation.
int pscore_sparse_create2(int dim, int rule, float lr, float initial_range,
                          int accessor, float embedx_threshold) {
  if (accessor < kCtrCommon || accessor > kCtrDymf) return -1;
  std::lock_guard<std::mutex> g(g_reg_lock);
  TableConfig cfg;
  cfg.dim = dim;
  cfg.rule = rule;
  cfg.lr = lr;
  cfg.initial_range = initial_range;
  cfg.accessor = accessor;
  cfg.embedx_threshold = embedx_threshold;
  if (rule == kAdaGrad) cfg.initial_g2sum = 0.0f;
  g_sparse.push_back(new SparseTable(cfg));
  return (int)g_sparse.size() - 1;
}

int pscore_sparse_accessor(int h) { return g_sparse[h]->cfg.accessor; }

void pscore_sparse_pull_dymf(int h, const uint64_t* keys, int n,
                             float* out, int stride) {
  g_sparse[h]->pull_dymf(keys, n, out, stride);
}

void pscore_sparse_push_dymf(int h, const uint64_t* keys,
                             const int* mf_dims, const float* grads,
                             int n, int stride, const float* shows,
                             const float* clicks, const float* slots) {
  g_sparse[h]->push_dymf(keys, mf_dims, grads, n, stride, shows, clicks,
                         slots);
}

int pscore_sparse_key_stats(int h, uint64_t key, double* show,
                            double* click, int* mf_dim) {
  return g_sparse[h]->key_stats(key, show, click, mf_dim);
}

void pscore_sparse_pull(int h, const uint64_t* keys, int n, float* out) {
  g_sparse[h]->pull(keys, n, out);
}

void pscore_sparse_push(int h, const uint64_t* keys, const float* grads,
                        int n, const float* shows, const float* clicks) {
  g_sparse[h]->push(keys, grads, n, shows, clicks);
}

int64_t pscore_sparse_size(int h) { return g_sparse[h]->size(); }

int pscore_sparse_enable_spill(int h, const char* dir,
                               int64_t max_mem_keys) {
  return g_sparse[h]->enable_spill(dir, max_mem_keys);
}

int64_t pscore_sparse_mem_size(int h) { return g_sparse[h]->mem_size(); }

int64_t pscore_sparse_spill_size(int h) {
  return g_sparse[h]->spill_size();
}

int64_t pscore_sparse_shrink(int h, float threshold, int max_unseen) {
  return g_sparse[h]->shrink(threshold, max_unseen);
}

int pscore_sparse_save(int h, const char* path) {
  return g_sparse[h]->save(path);
}

int pscore_sparse_load(int h, const char* path) {
  return g_sparse[h]->load(path);
}

// ----------------------------------------------------------- dense table
int pscore_dense_create(int64_t size, int rule, float lr) {
  std::lock_guard<std::mutex> g(g_reg_lock);
  auto* t = new DenseTable();
  t->data.assign(size, 0.0f);
  t->rule = rule;
  t->lr = lr;
  if (rule == kAdam) {
    t->m.assign(size, 0.0f);
    t->v.assign(size, 0.0f);
  }
  g_dense.push_back(t);
  return (int)g_dense.size() - 1;
}

void pscore_dense_set(int h, const float* vals, int64_t n) {
  auto* t = g_dense[h];
  std::lock_guard<std::mutex> g(t->lock);
  std::memcpy(t->data.data(), vals, sizeof(float) * n);
}

void pscore_dense_pull(int h, float* out, int64_t n) {
  auto* t = g_dense[h];
  std::lock_guard<std::mutex> g(t->lock);
  std::memcpy(out, t->data.data(), sizeof(float) * n);
}

// geo-async merge (MemorySparseGeoTable/geo dense mode capability): the
// server adds trainer deltas instead of running an SGD rule
void pscore_dense_add(int h, const float* delta, int64_t n) {
  auto* t = g_dense[h];
  std::lock_guard<std::mutex> g(t->lock);
  for (int64_t i = 0; i < n; i++) t->data[i] += delta[i];
}

void pscore_dense_push(int h, const float* grads, int64_t n) {
  auto* t = g_dense[h];
  std::lock_guard<std::mutex> g(t->lock);
  t->step++;
  if (t->rule == kAdam) {
    float b1p = 1 - std::pow(t->beta1, (float)t->step);
    float b2p = 1 - std::pow(t->beta2, (float)t->step);
    for (int64_t i = 0; i < n; i++) {
      t->m[i] = t->beta1 * t->m[i] + (1 - t->beta1) * grads[i];
      t->v[i] = t->beta2 * t->v[i] + (1 - t->beta2) * grads[i] * grads[i];
      t->data[i] -= t->lr * (t->m[i] / b1p) /
                    (std::sqrt(t->v[i] / b2p) + t->eps);
    }
  } else {
    for (int64_t i = 0; i < n; i++) t->data[i] -= t->lr * grads[i];
  }
}

// -------------------------------------------------------------- dataset
int pscore_dataset_create() {
  std::lock_guard<std::mutex> g(g_reg_lock);
  g_datasets.push_back(new Dataset());
  return (int)g_datasets.size() - 1;
}

int pscore_dataset_load_file(int h, const char* path) {
  return g_datasets[h]->load_file(path);
}

void pscore_dataset_shuffle(int h, uint64_t seed) {
  g_datasets[h]->shuffle(seed);
}

int64_t pscore_dataset_size(int h) {
  return (int64_t)g_datasets[h]->records.size();
}

void pscore_dataset_rewind(int h) { g_datasets[h]->cursor = 0; }

int pscore_dataset_next_batch(int h, int batch, const int* slot_ids,
                              int n_slots, int max_per_slot,
                              uint64_t* out_keys, float* out_labels) {
  return g_datasets[h]->next_batch(batch, slot_ids, n_slots, max_per_slot,
                                   out_keys, out_labels);
}

// ---- cross-worker global shuffle support (data_set.h:230
// GlobalShuffle): records route to workers by a content hash so every
// worker computes the same destination for the same record. Wire
// format per record: f32 label, u32 nfeat, nfeat x (i32 slot, u64
// sign).
static uint64_t record_hash(const Record& r, uint64_t seed) {
  uint64_t x = seed * 0x9E3779B97F4A7C15ull + 0xD1B54A32D192ED03ull;
  x ^= (uint64_t)(int64_t)(r.label * 7919.0f) + 0x9E3779B97F4A7C15ull +
       (x << 6) + (x >> 2);
  for (auto& f : r.feats) {
    uint64_t v = f.second * 0xBF58476D1CE4E5B9ull + (uint64_t)f.first;
    v ^= v >> 31;
    x ^= v + 0x9E3779B97F4A7C15ull + (x << 6) + (x >> 2);
  }
  return x;
}

static size_t record_bytes(const Record& r) {
  return 4 + 4 + r.feats.size() * 12;
}

int64_t pscore_dataset_extract_size(int h, int dst, int n_workers,
                                    uint64_t seed) {
  auto* d = g_datasets[h];
  size_t total = 0;
  for (auto& r : d->records)
    if ((int)(record_hash(r, seed) % (uint64_t)n_workers) == dst)
      total += record_bytes(r);
  return (int64_t)total;
}

int64_t pscore_dataset_extract(int h, int dst, int n_workers,
                               uint64_t seed, char* buf) {
  auto* d = g_datasets[h];
  char* p = buf;
  for (auto& r : d->records) {
    if ((int)(record_hash(r, seed) % (uint64_t)n_workers) != dst)
      continue;
    std::memcpy(p, &r.label, 4); p += 4;
    uint32_t nf = (uint32_t)r.feats.size();
    std::memcpy(p, &nf, 4); p += 4;
    for (auto& f : r.feats) {
      int32_t slot = f.first;
      std::memcpy(p, &slot, 4); p += 4;
      std::memcpy(p, &f.second, 8); p += 8;
    }
  }
  return (int64_t)(p - buf);
}

void pscore_dataset_retain(int h, int me, int n_workers, uint64_t seed) {
  auto* d = g_datasets[h];
  std::vector<Record> keep;
  keep.reserve(d->records.size() / (n_workers ? n_workers : 1) + 1);
  for (auto& r : d->records)
    if ((int)(record_hash(r, seed) % (uint64_t)n_workers) == me)
      keep.push_back(std::move(r));
  d->records.swap(keep);
  d->cursor = 0;
}

int64_t pscore_dataset_ingest(int h, const char* buf, int64_t nbytes) {
  auto* d = g_datasets[h];
  const char* p = buf;
  const char* end = buf + nbytes;
  int64_t added = 0;
  while (p + 8 <= end) {
    Record r;
    std::memcpy(&r.label, p, 4); p += 4;
    uint32_t nf;
    std::memcpy(&nf, p, 4); p += 4;
    if (p + (size_t)nf * 12 > end) return -1;  // truncated payload
    r.feats.reserve(nf);
    for (uint32_t i = 0; i < nf; i++) {
      int32_t slot; uint64_t sign;
      std::memcpy(&slot, p, 4); p += 4;
      std::memcpy(&sign, p, 8); p += 8;
      r.feats.emplace_back((int)slot, sign);
    }
    d->records.push_back(std::move(r));
    added++;
  }
  return added;
}

}  // extern "C"

// ------------------------------------------------------------ graph store
// Parity: the fork's graph engine (`paddle/fluid/framework/fleet/heter_ps/
// graph_gpu_ps_table.h`, `gpu_graph_node.h`, `graph_sampler_inl.h`;
// distributed `ps/table/common_graph_table.h`): adjacency storage keyed by
// uint64 node ids + random-walk / neighbor sampling for GNN training
// (PGLBox-style). Host C++ here feeds slot/segment tensors to TPU steps.
namespace {

struct GraphTable {
  std::unordered_map<uint64_t, std::vector<uint64_t>> adj[kShards];
  // per-edge weights, parallel to adj lists; only materialised for nodes
  // that ever saw a weighted edge (graph_gpu_ps_table weighted-sampling
  // capability)
  std::unordered_map<uint64_t, std::vector<float>> wts[kShards];
  // node feature vectors (common_graph_table.h Node::get_feature parity);
  // the feature dim is caller-supplied per get call (Python tracks it)
  std::unordered_map<uint64_t, std::vector<float>> feats[kShards];
  std::mutex locks[kShards];
  std::vector<uint64_t> nodes;  // insertion order, for sampling starts
  std::mutex nodes_lock;
  // one RNG per shard, each only touched under its shard lock (same
  // pattern as SparseTable) + one for node sampling under nodes_lock
  std::mt19937_64 rngs[kShards];
  std::mt19937_64 nodes_rng{20240731ull};

  GraphTable() {
    for (int i = 0; i < kShards; i++) rngs[i].seed(977 + i);
  }

  static int shard_of(uint64_t key) {
    return SparseTable::shard_of(key);
  }

  void add_one(uint64_t src, uint64_t dst, float w, bool has_w) {
    int s = shard_of(src);
    std::lock_guard<std::mutex> g(locks[s]);
    auto it = adj[s].find(src);
    if (it == adj[s].end()) {
      adj[s][src] = {dst};
      if (has_w) wts[s][src] = {w};
      std::lock_guard<std::mutex> g2(nodes_lock);
      nodes.push_back(src);
      return;
    }
    it->second.push_back(dst);
    auto wit = wts[s].find(src);
    if (has_w || wit != wts[s].end()) {
      auto& wv = (wit != wts[s].end()) ? wit->second : wts[s][src];
      // earlier unweighted edges on this node default to weight 1
      while (wv.size() + 1 < it->second.size()) wv.push_back(1.0f);
      wv.push_back(has_w ? w : 1.0f);
    }
  }

  void add_edges(const uint64_t* src, const uint64_t* dst, int64_t n) {
    for (int64_t i = 0; i < n; i++) add_one(src[i], dst[i], 1.0f, false);
  }

  void add_edges_weighted(const uint64_t* src, const uint64_t* dst,
                          const float* w, int64_t n) {
    for (int64_t i = 0; i < n; i++) add_one(src[i], dst[i], w[i], true);
  }

  void set_node_feat(const uint64_t* keys, int64_t n, int dim,
                     const float* vals) {
    for (int64_t i = 0; i < n; i++) {
      int s = shard_of(keys[i]);
      std::lock_guard<std::mutex> g(locks[s]);
      feats[s][keys[i]].assign(vals + (size_t)i * dim,
                               vals + (size_t)(i + 1) * dim);
    }
  }

  void get_node_feat(const uint64_t* keys, int64_t n, int dim,
                     float* out) {
    for (int64_t i = 0; i < n; i++) {
      int s = shard_of(keys[i]);
      std::lock_guard<std::mutex> g(locks[s]);
      auto it = feats[s].find(keys[i]);
      float* dst = out + (size_t)i * dim;
      if (it == feats[s].end() || (int)it->second.size() != dim) {
        std::memset(dst, 0, sizeof(float) * dim);
      } else {
        std::memcpy(dst, it->second.data(), sizeof(float) * dim);
      }
    }
  }

  // pick an edge index from `nb`, weighted when this node has weights;
  // call under shard lock
  size_t choose_edge(int s, uint64_t node,
                     const std::vector<uint64_t>& nb) {
    auto wit = wts[s].find(node);
    if (wit == wts[s].end() || wit->second.size() != nb.size()) {
      std::uniform_int_distribution<uint64_t> u;
      return (size_t)(u(rngs[s]) % nb.size());
    }
    const auto& wv = wit->second;
    float total = 0.0f;
    for (float w : wv) total += (w > 0 ? w : 0);
    if (total <= 0.0f) {
      std::uniform_int_distribution<uint64_t> u;
      return (size_t)(u(rngs[s]) % nb.size());
    }
    std::uniform_real_distribution<float> ur(0.0f, total);
    float r = ur(rngs[s]);
    for (size_t j = 0; j < wv.size(); j++) {
      r -= (wv[j] > 0 ? wv[j] : 0);
      if (r <= 0) return j;
    }
    return wv.size() - 1;
  }

  // sample up to k neighbors per query node (out: [n, k]); slots past
  // the true degree pad with the node itself, so callers may mask either
  // via out_deg or by out[i][j] == q[i]
  void sample_neighbors(const uint64_t* q, int64_t n, int k,
                        uint64_t* out, int* out_deg) {
    std::uniform_int_distribution<uint64_t> u;
    for (int64_t i = 0; i < n; i++) {
      int s = shard_of(q[i]);
      std::lock_guard<std::mutex> g(locks[s]);
      auto it = adj[s].find(q[i]);
      if (it == adj[s].end() || it->second.empty()) {
        out_deg[i] = 0;
        for (int j = 0; j < k; j++) out[i * k + j] = q[i];
        continue;
      }
      auto& nb = it->second;
      int deg = (int)std::min<size_t>(nb.size(), (size_t)k);
      out_deg[i] = deg;
      for (int j = 0; j < k; j++) {
        if (j < deg) {
          out[i * k + j] = nb.size() <= (size_t)k
              ? nb[j]                              // take all
              : nb[choose_edge(s, q[i], nb)];      // (weighted) subsample
        } else {
          out[i * k + j] = q[i];                   // self-pad
        }
      }
    }
  }

  // random walks: for each start node, walk `walk_len` steps
  // (out: [n, walk_len+1]); dead ends repeat the last node
  void random_walk(const uint64_t* starts, int64_t n, int walk_len,
                   uint64_t* out) {
    std::uniform_int_distribution<uint64_t> u;
    for (int64_t i = 0; i < n; i++) {
      uint64_t cur = starts[i];
      out[i * (walk_len + 1)] = cur;
      for (int t = 1; t <= walk_len; t++) {
        int s = shard_of(cur);
        std::lock_guard<std::mutex> g(locks[s]);
        auto it = adj[s].find(cur);
        if (it == adj[s].end() || it->second.empty()) {
          out[i * (walk_len + 1) + t] = cur;
          continue;
        }
        cur = it->second[choose_edge(s, cur, it->second)];
        out[i * (walk_len + 1) + t] = cur;
      }
    }
  }

  int64_t num_nodes() {
    std::lock_guard<std::mutex> g(nodes_lock);
    return (int64_t)nodes.size();
  }

  void sample_nodes(int64_t n, uint64_t* out) {
    std::lock_guard<std::mutex> g(nodes_lock);
    std::uniform_int_distribution<uint64_t> u;
    for (int64_t i = 0; i < n; i++) {
      out[i] = nodes.empty() ? 0
          : nodes[(size_t)(u(nodes_rng) % nodes.size())];
    }
  }
};

std::vector<GraphTable*> g_graphs;

}  // namespace

extern "C" {

int pscore_graph_create() {
  std::lock_guard<std::mutex> g(g_reg_lock);
  g_graphs.push_back(new GraphTable());
  return (int)g_graphs.size() - 1;
}

void pscore_graph_add_edges(int h, const uint64_t* src,
                            const uint64_t* dst, int64_t n) {
  g_graphs[h]->add_edges(src, dst, n);
}

void pscore_graph_add_edges_weighted(int h, const uint64_t* src,
                                     const uint64_t* dst, const float* w,
                                     int64_t n) {
  g_graphs[h]->add_edges_weighted(src, dst, w, n);
}

void pscore_graph_set_node_feat(int h, const uint64_t* keys, int64_t n,
                                int dim, const float* vals) {
  g_graphs[h]->set_node_feat(keys, n, dim, vals);
}

void pscore_graph_get_node_feat(int h, const uint64_t* keys, int64_t n,
                                int dim, float* out) {
  g_graphs[h]->get_node_feat(keys, n, dim, out);
}

void pscore_graph_sample_neighbors(int h, const uint64_t* q, int64_t n,
                                   int k, uint64_t* out, int* out_deg) {
  g_graphs[h]->sample_neighbors(q, n, k, out, out_deg);
}

void pscore_graph_random_walk(int h, const uint64_t* starts, int64_t n,
                              int walk_len, uint64_t* out) {
  g_graphs[h]->random_walk(starts, n, walk_len, out);
}

int64_t pscore_graph_num_nodes(int h) { return g_graphs[h]->num_nodes(); }

void pscore_graph_sample_nodes(int h, int64_t n, uint64_t* out) {
  g_graphs[h]->sample_nodes(n, out);
}

}  // extern "C"
