"""SparseEmbedding: the PS-backed embedding layer feeding the TPU step.

Parity: the `distributed_lookup_table` / `distributed_push_sparse` op pair
the PS trainer pass rewrites embeddings into
(`python/paddle/distributed/passes/ps_trainer_pass.py`), plus the
HeterPS/PSGPU pull-train-push cycle (`fleet/ps_gpu_wrapper.h:157
PullSparse / :170 PushSparseGrad`).

Design (SURVEY.md §7.7): the hash-table lookup and the in-table SGD run in
native host code (ps/csrc); the TPU step consumes a dense [batch, slots,
dim] activation and produces its gradient. The pull happens in forward
(host), the push happens when the gradient for the pulled block arrives
(leaf grad hook) — so the surrounding model stays an ordinary autograd
graph and can be jitted between the pull/push boundaries.
"""
from __future__ import annotations

import numpy as np

from ..nn.layer_base import Layer
from ..core.tensor import Tensor
from .table import MemorySparseTable


class SparseEmbedding(Layer):
    """`engine` (default None = direct-table parity path) switches the
    layer onto a `ps.heter.HeterEmbeddingEngine`: pulls ride the
    sharded/cached/pipelined path, pushes are dedup-merged — the leaf
    grad-hook contract below is unchanged either way."""

    def __init__(self, dim=8, sgd_rule="adagrad", learning_rate=0.05,
                 initial_range=0.02, table=None, communicator=None,
                 engine=None, name=None):
        super().__init__()
        self.dim = dim
        self.engine = engine
        if engine is not None:
            if table is not None and table is not engine.table:
                raise ValueError(
                    "pass either table= or engine=, not both")
            table = engine.table
        self.table = table if table is not None else MemorySparseTable(
            dim, sgd_rule, learning_rate, initial_range)
        # a_sync mode: pushes go through the background communicator
        self.communicator = communicator
        if communicator is not None:
            if engine is not None:
                raise ValueError(
                    "communicator and engine are exclusive push paths")
            communicator.start()

    def forward(self, keys):
        """keys: uint64/int ndarray or Tensor [batch, n_slots, per_slot]
        -> Tensor [batch, n_slots, per_slot, dim] (requires_grad; grads
        are pushed to the table on backward)."""
        keys_np = keys.numpy() if isinstance(keys, Tensor) \
            else np.asarray(keys)
        keys_np = keys_np.astype(np.uint64)
        if self.engine is not None:
            # eval pulls are side traffic: they must not consume (or
            # retire) a prefetch the training loop has in flight
            values = self.engine.pull(keys_np, train=self.training,
                                      use_prefetch=self.training)
        else:
            values = self.table.pull(keys_np)
        t = Tensor(values, stop_gradient=not self.training)
        if self.training:
            table = self.table
            # leaf hooks fire once per accumulated edge with the CUMULATIVE
            # grad; push only the delta so multi-consumer graphs don't
            # double-apply earlier contributions
            state = {"pushed": None}

            comm = self.communicator
            eng = self.engine

            def push_hook(grad, _keys=keys_np, _table=table, _s=state,
                          _comm=comm, _eng=eng):
                g = grad.numpy()
                delta = g if _s["pushed"] is None else g - _s["pushed"]
                _s["pushed"] = g.copy()
                if _eng is not None:
                    _eng.push(_keys, delta)
                elif _comm is not None:
                    _comm.push_sparse(_table, _keys, delta)
                else:
                    _table.push(_keys, delta)
            t.register_hook(push_hook)
        return t

    def flush(self):
        """Drain the async push paths (engine pipeline / communicator)
        — the barrier before save/eval."""
        if self.engine is not None:
            self.engine.flush()
        if self.communicator is not None:
            self.communicator.flush()

    def state(self):
        s = {"size": len(self.table)}
        if self.engine is not None:
            s["engine"] = self.engine.state()
        return s
