"""Embedding-engine metrics — registered in the framework-wide PR 1
registry.

Exported names are part of the observability contract
(docs/EMBEDDING.md, tools/embedding_smoke.py greps them the same way
tools/serving_smoke.py greps the serving names). Recording follows the
hot-path discipline: the engine keeps raw python counters always on
(cheap ints) and mirrors them into the registry only when
`profiler.metrics._enabled` is set, so a training loop with
observability off pays one branch per pull/push.
"""
from __future__ import annotations

from ...profiler.metrics import REGISTRY, exponential_buckets

# 10us .. ~2.6s in x4 steps: a cached pull is a numpy gather (~100us),
# a cold sharded pull fans out to native tables, a spill-backed pull
# can touch disk
_LATENCY_BUCKETS = exponential_buckets(1e-5, 4.0, 9)

EMB_PULL_SECONDS = REGISTRY.histogram(
    "paddle_tpu_embedding_pull_seconds",
    "Latency of one engine pull (dedup + cache gather + shard misses)",
    buckets=_LATENCY_BUCKETS)
EMB_PUSH_SECONDS = REGISTRY.histogram(
    "paddle_tpu_embedding_push_seconds",
    "Latency of one engine push (merge + shard fan-out + refresh)",
    buckets=_LATENCY_BUCKETS)
EMB_CACHE_LOOKUPS = REGISTRY.counter(
    "paddle_tpu_embedding_cache_lookups_total",
    "Hot-ID cache lookups by result", ("result",))   # hit|miss
EMB_CACHE_EVICTIONS = REGISTRY.counter(
    "paddle_tpu_embedding_cache_evictions_total",
    "Cache rows reclaimed by LRU/frequency eviction")
EMB_CACHE_WRITEBACKS = REGISTRY.counter(
    "paddle_tpu_embedding_cache_writebacks_total",
    "Dirty rows whose pending gradient delta was pushed to the shards")
EMB_CACHE_ROWS = REGISTRY.gauge(
    "paddle_tpu_embedding_cache_rows",
    "Resident hot-ID cache rows")
EMB_DEDUP_KEYS = REGISTRY.counter(
    "paddle_tpu_embedding_dedup_keys_total",
    "Lookup keys before/after per-batch dedup", ("kind",))  # raw|unique
EMB_PREFETCH = REGISTRY.counter(
    "paddle_tpu_embedding_prefetch_total",
    "Prefetch consumption by outcome",
    ("result",))   # hit|repair|unused
EMB_SHARD_KEYS = REGISTRY.gauge(
    "paddle_tpu_embedding_shard_keys",
    "Features resident per table shard", ("shard",))
EMB_LOOKUPS_SERVED = REGISTRY.counter(
    "paddle_tpu_embedding_lookups_served_total",
    "Read-only LookupService requests served")

#: every name above, for the smoke-tool contract check
CONTRACT_METRICS = (
    "paddle_tpu_embedding_pull_seconds",
    "paddle_tpu_embedding_push_seconds",
    "paddle_tpu_embedding_cache_lookups_total",
    "paddle_tpu_embedding_cache_evictions_total",
    "paddle_tpu_embedding_cache_writebacks_total",
    "paddle_tpu_embedding_cache_rows",
    "paddle_tpu_embedding_dedup_keys_total",
    "paddle_tpu_embedding_prefetch_total",
    "paddle_tpu_embedding_shard_keys",
    "paddle_tpu_embedding_lookups_served_total",
)


def cache_hit_ratio():
    """hit / (hit + miss) from the registry — exported as a plain
    function so dashboards and the smoke tool agree on the definition."""
    ch = dict(EMB_CACHE_LOOKUPS.samples())
    hit = ch.get(("hit",))
    miss = ch.get(("miss",))
    h = hit.value if hit else 0.0
    t = h + (miss.value if miss else 0.0)
    return h / t if t else 0.0


def dedup_ratio():
    """1 - unique/raw: the fraction of lookup traffic removed by
    per-batch key dedup."""
    ch = dict(EMB_DEDUP_KEYS.samples())
    raw = ch.get(("raw",))
    uniq = ch.get(("unique",))
    r = raw.value if raw else 0.0
    return 1.0 - (uniq.value if uniq else 0.0) / r if r else 0.0
