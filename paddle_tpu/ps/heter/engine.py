"""HeterPS-style embedding engine: dedup -> hot-ID cache -> shards.

The pull/push cycle of `fleet/ps_gpu_wrapper.h` (PullSparse /
PushSparseGrad) rebuilt between the native PS tables and the TPU step:

* **Per-batch key dedup.** A batch's `[batch, slots, per_slot]` keys
  collapse to unique ids (`np.unique` + inverse index); the cache and
  the shards see each id once, and the dense `[*, dim]` activation is
  an inverse-index gather. The gradient push walks the same inverse
  index through `ops/selected_rows.py` merge, so duplicate keys are
  combined ONCE before any table sees them (the reference's merge_add).
* **Hot-ID cache** (`cache.py`): reads hit the dense row cache, misses
  fall through to the shards and are admitted (LRU + frequency
  eviction, refcounted pins while a step is in flight).
* **Async prefetch pipeline.** `prefetch(next_keys)` resolves batch
  N+1's unique ids on a background thread while the jitted dense step
  runs batch N (double-buffered: one pending prefetch). Strict mode
  repairs the prefetched block at consume time: any id pushed between
  the prefetch snapshot and the consuming pull is re-read so the
  pipelined schedule stays NUMERICALLY IDENTICAL to the sequential
  pull -> step -> push order.
* **Two push modes.**
  - ``strict`` (default): push applies the merged gradients to the
    shards synchronously and refreshes the cached rows from the table,
    so the cache is always coherent — bit-identical to the direct
    `MemorySparseTable` path (the engine-on parity contract).
  - ``stream``: online training. Resident ids accumulate their deltas
    in the cache's dirty buffer and are written back when evicted,
    when older than ``staleness_bound`` steps, or on `flush()`;
    non-resident ids ride a bounded background push queue. Reads may
    be up to the staleness bound behind — the reference
    AsyncCommunicator's async-SGD window.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...profiler import metrics as _pm
from . import metrics as _m
from .cache import HotIdCache


def _merge_grads(uniq_size, inv, grads_2d):
    """Combine duplicate-key gradients through the SelectedRows
    MergeAdd kernel (`ops/selected_rows.py`): segment i sums every
    occurrence of unique key i. The inverse index comes from the
    pull-side dedup, so the merge skips the redundant re-sort."""
    from ...ops.selected_rows import merge_with_inverse
    return merge_with_inverse(inv, grads_2d.astype(np.float32,
                                                   copy=False),
                              uniq_size)


class HeterEmbeddingEngine:
    """Sharded + cached + pipelined embedding engine.

    `table` is anything with the `MemorySparseTable` pull/push surface
    — one native table or a `ShardedSparseTable` fan-out."""

    def __init__(self, table, cache_capacity=4096, mode="strict",
                 staleness_bound=4, prefetch=True):
        if mode not in ("strict", "stream"):
            raise ValueError(f"mode={mode!r} not in ('strict','stream')")
        if getattr(table, "row_width", None) is not None and \
                table.row_width != table.dim:
            raise ValueError(
                "engine requires row_width == dim tables (dymf rows "
                "are variable-width; pull them directly)")
        self.table = table
        self.dim = table.dim
        self.mode = mode
        self.staleness_bound = int(staleness_bound)
        self._lock = threading.RLock()
        self.cache = HotIdCache(cache_capacity, self.dim,
                                writeback=self._writeback)
        self._step = 0                 # pull clock (staleness ages)
        self._dedup_memo = {}          # raw-key bytes -> (uniq, inv)
        self._dedup_order = deque()
        self._push_version = 0         # strict-mode repair clock
        self._pushed_sets = deque()    # (version, frozenset)
        self._pushed_floor = 0         # versions <= floor were dropped
        self._open_steps = deque()     # {sig, uniq, rows} pinned pulls
        # one pending prefetch (double buffering)
        self._pf_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="emb-prefetch") \
            if prefetch else None
        self._pf_pending = None
        # stream mode: bounded background push lane for non-resident ids
        self._push_q = None
        self._push_thread = None
        self._push_errors = []
        self._push_inflight = 0
        self._push_cv = threading.Condition()
        if mode == "stream":
            self._push_q = queue.Queue(maxsize=max(1, staleness_bound))
            self._push_thread = threading.Thread(
                target=self._push_loop, daemon=True)
            self._push_thread.start()
        # raw counters (bench/tests read these without the registry)
        self.raw_keys = 0
        self.uniq_keys = 0
        self.prefetch_hits = 0
        self.prefetch_repairs = 0
        self.prefetch_unused = 0

    # ======================================================== pull side
    def pull(self, keys, train=False, use_prefetch=True):
        """keys: int/uint array [batch, slots, per_slot] (any shape)
        -> float32 [*, dim]. `train=True` pins the backing cache rows
        until the matching `push` lands. `use_prefetch=False` bypasses
        the prefetch buffer entirely (read-only side traffic, e.g.
        LookupService — a mismatching side pull must not retire the
        trainer's pending prefetch)."""
        t0 = time.perf_counter()
        keys = np.asarray(keys)
        shape = keys.shape
        flat = np.ascontiguousarray(keys.reshape(-1), np.uint64)
        got = self._consume_prefetch(flat) if use_prefetch else None
        if got is None:
            uniq, inv = np.unique(flat, return_inverse=True)
            vals, rows = self._resolve(uniq, pin=train)
        else:
            # the prefetch worker already dedup'd and resolved; the
            # critical path is one raw-key compare + the final gather
            uniq, inv, vals, rows = got
            if train:
                # re-derive the row mapping under the lock: the
                # prefetch thread may have evicted/remapped rows since
                # the background resolve (the values were copied then)
                with self._lock:
                    rows = self.cache.lookup(uniq, count=False)
                    self.cache.pin(rows[rows >= 0])
        self.raw_keys += flat.size
        self.uniq_keys += uniq.size
        # remember the dedup so the matching push (possibly several
        # batches later on a drain thread) skips its re-sort. A
        # repeated key set only refreshes its entry — the order deque
        # holds each key once, so trimming by ITS length is exact
        # insertion-order LRU and nothing grows unboundedly.
        b = flat.tobytes()
        if b not in self._dedup_memo:
            self._dedup_order.append(b)
            if len(self._dedup_order) > 16:
                self._dedup_memo.pop(self._dedup_order.popleft(), None)
        self._dedup_memo[b] = (uniq, inv)
        if train:
            with self._lock:       # _close_step may scan from a
                self._open_steps.append(   # drain thread
                    {"sig": uniq.tobytes(), "uniq": uniq,
                     "rows": rows})
        with self._lock:
            self._step += 1
            if self.mode == "stream":
                stale = self.cache.stale_rows(
                    self._step - self.staleness_bound)
                if stale.size:
                    # _writeback ships these through the BACKGROUND
                    # push lane (put_nowait): a synchronous table
                    # round trip here would stall the very critical
                    # path stream mode exists to protect
                    self.cache.flush_rows(stale)
        if _pm._enabled:
            _m.EMB_PULL_SECONDS.observe(time.perf_counter() - t0)
            _m.EMB_DEDUP_KEYS.labels("raw").inc(int(flat.size))
            _m.EMB_DEDUP_KEYS.labels("unique").inc(int(uniq.size))
            _m.EMB_CACHE_ROWS.set(self.cache.num_rows)
        return vals[inv].reshape(*shape, self.dim)

    def _resolve(self, uniq, pin=False, count=True):
        """Unique ids -> (values [U, dim], cache rows [U] or -1).
        Cache hits gather; misses fan out to the shards and are
        admitted (or bypassed when every row is pinned)."""
        with self._lock:
            rows = self.cache.lookup(uniq, count=count)
            hit = rows >= 0
            vals = np.empty((uniq.size, self.dim), np.float32)
            if hit.any():
                vals[hit] = self.cache.gather(rows[hit])
        miss = ~hit
        if miss.any():
            pulled = self.table.pull(uniq[miss])     # outside the lock
            with self._lock:
                vals[miss] = pulled
                rows[miss] = self.cache.admit(uniq[miss], pulled,
                                              step=self._step)
        if pin:
            # re-derive rows at pin time: concurrent admissions may
            # have evicted what the lookup above saw (values are
            # copies, so only the pin bookkeeping needs freshness)
            with self._lock:
                rows = self.cache.lookup(uniq, count=False)
                self.cache.pin(rows[rows >= 0])
        if _pm._enabled and count:
            nh = int(hit.sum())
            _m.EMB_CACHE_LOOKUPS.labels("hit").inc(nh)
            _m.EMB_CACHE_LOOKUPS.labels("miss").inc(
                int(uniq.size) - nh)
        return vals, rows

    # ---------------------------------------------------------- prefetch
    def prefetch(self, keys):
        """Resolve the NEXT batch's unique ids on the background thread
        while the current dense step runs. One prefetch may be pending
        (double buffering); an unconsumed older one is retired (its
        stale admissions repaired) first."""
        if self._pf_pool is None:
            return
        keys = np.asarray(keys)
        flat = np.ascontiguousarray(keys.reshape(-1), np.uint64)
        self._retire_prefetch()
        self._pf_pending = {
            "raw": flat,
            "version": self._push_version,
            "future": self._pf_pool.submit(self._pf_job, flat),
        }

    def _pf_job(self, flat):
        """Background half of a prefetch: dedup + resolve (the main
        thread pays only the raw-key signature compare at consume)."""
        uniq, inv = np.unique(flat, return_inverse=True)
        vals, rows = self._resolve(uniq, pin=False, count=True)
        return uniq, inv, vals, rows

    def _conflicts_since(self, version, uniq):
        """Ids in `uniq` pushed after `version` (strict-mode repair
        set). A snapshot older than the retained history conservatively
        conflicts on every id."""
        if version < self._pushed_floor:
            return uniq.copy()
        touched = [ks for v, ks in self._pushed_sets if v > version]
        if not touched:
            return np.empty(0, np.uint64)
        return uniq[np.isin(uniq, np.concatenate(touched))]

    def _repair(self, version, uniq, vals):
        """Re-read every id of a prefetched block that was pushed after
        the prefetch snapshot: patch the handed-out values AND the
        cache rows the prefetch admitted, so the pipelined schedule is
        indistinguishable from sequential pull-after-push."""
        conf = self._conflicts_since(version, uniq)
        if conf.size == 0:
            return False
        fresh = self.table.pull(conf)
        pos = np.searchsorted(uniq, conf)
        if vals is not None:
            vals[pos] = fresh
        with self._lock:
            crow = self.cache.lookup(conf, count=False)
            ok = crow >= 0
            if ok.any():
                self.cache.set_values(crow[ok], fresh[ok])
        return True

    def _consume_prefetch(self, flat):
        """Take a pending prefetch if it matches the raw key array;
        None otherwise. Strict mode repairs push conflicts either
        way."""
        pf = self._pf_pending
        if pf is None:
            return None
        if pf["raw"].size != flat.size or \
                not np.array_equal(pf["raw"], flat):
            self._retire_prefetch()
            return None
        self._pf_pending = None
        uniq, inv, vals, rows = pf["future"].result()
        repaired = self.mode == "strict" and \
            self._repair(pf["version"], uniq, vals)
        if repaired:
            self.prefetch_repairs += 1
        else:
            self.prefetch_hits += 1
        if _pm._enabled:
            _m.EMB_PREFETCH.labels(
                "repair" if repaired else "hit").inc()
        return uniq, inv, vals, rows

    def _retire_prefetch(self):
        """Drop an unconsumed prefetch, repairing any stale admissions
        it made (strict mode) so the cache never serves pre-push
        values."""
        pf = self._pf_pending
        if pf is None:
            return
        self._pf_pending = None
        uniq, _, _, _ = pf["future"].result()
        if self.mode == "strict":
            self._repair(pf["version"], uniq, None)
        self.prefetch_unused += 1
        if _pm._enabled:
            _m.EMB_PREFETCH.labels("unused").inc()

    # ======================================================== push side
    def push(self, keys, grads):
        """Gradient push for a previous pull: dedup-merge duplicate
        keys (SelectedRows), then strict-apply or stream-accumulate.
        Matches and unpins the corresponding in-flight pull."""
        t0 = time.perf_counter()
        keys = np.asarray(keys)
        flat = np.ascontiguousarray(keys.reshape(-1), np.uint64)
        grads_2d = np.asarray(grads, np.float32).reshape(flat.size,
                                                        self.dim)
        memo = self._dedup_memo.get(flat.tobytes())
        if memo is not None:
            uniq, inv = memo         # the pull already dedup'd these
        else:
            uniq, inv = np.unique(flat, return_inverse=True)
        merged = _merge_grads(uniq.size, inv, grads_2d)
        if self.mode == "strict":
            self._push_strict(uniq, merged)
        else:
            self._push_stream(uniq, merged)
        self._close_step(uniq)
        if _pm._enabled:
            _m.EMB_PUSH_SECONDS.observe(time.perf_counter() - t0)
        return uniq.size

    def _refresh_resident(self, keys):
        """Coherence refresh after a table write: re-read the fresh
        values for every id of `keys` that is resident in the cache
        (the re-lookup under the second lock matters — rows may have
        been evicted/remapped during the unlocked table pull)."""
        with self._lock:
            rows = self.cache.lookup(keys, count=False)
        resident = rows >= 0
        if not resident.any():
            return
        fresh = self.table.pull(keys[resident])
        with self._lock:
            rr = self.cache.lookup(keys[resident], count=False)
            ok = rr >= 0
            if ok.any():
                self.cache.set_values(rr[ok], fresh[ok])

    def _push_strict(self, uniq, merged):
        self.table.push(uniq, merged)
        # the in-table SGD rule ran on push: resident ids must re-read
        self._refresh_resident(uniq)
        self._push_version += 1
        if self._pf_pool is not None:
            # repair history is only ever read by the prefetch paths
            self._pushed_sets.append((self._push_version, uniq.copy()))
            while len(self._pushed_sets) > 64:
                # remember how far back the retained history reaches,
                # so a repair against a dropped snapshot degrades to
                # re-reading EVERYTHING instead of missing conflicts
                self._pushed_floor = self._pushed_sets.popleft()[0]

    def _push_stream(self, uniq, merged):
        if self._push_errors:
            raise self._push_errors.pop(0)
        with self._lock:
            rows = self.cache.lookup(uniq, count=False)
            resident = rows >= 0
            if resident.any():
                self.cache.add_delta(rows[resident], merged[resident],
                                     step=self._step,
                                     unique_rows=True)
        cold = ~resident
        if cold.any():
            # bounded queue: blocks when the push lane is
            # staleness_bound batches behind (backpressure, not loss)
            with self._push_cv:
                self._push_inflight += 1
            self._push_q.put((uniq[cold].copy(), merged[cold].copy()))

    def _push_loop(self):
        while True:
            item = self._push_q.get()
            if item is None:
                return
            try:
                wb_keys, grads = item
                self.table.push(wb_keys, grads)
                # a key queued as COLD may have been admitted (from a
                # pre-push table read) while it sat in the queue: the
                # resident row would otherwise serve the stale value
                # forever, not just for the staleness window
                self._refresh_resident(wb_keys)
            except Exception as e:  # noqa: BLE001 — surface on flush
                self._push_errors.append(e)
            finally:
                with self._push_cv:
                    self._push_inflight -= 1
                    if self._push_inflight == 0:
                        self._push_cv.notify_all()

    def _writeback(self, wb_keys, deltas):
        """Cache dirty-row write-back (eviction / staleness / flush):
        apply the accumulated delta to the shards, then refresh any
        still-resident row so reads converge to the table. Often
        invoked UNDER the engine lock (evictions fire inside admit),
        so in stream mode the table round trips ride the background
        push lane when it has room — put_nowait, never a blocking put,
        because the lane's worker needs this same lock for its
        refreshes (a blocking put under the lock would deadlock)."""
        if self._push_q is not None:
            with self._push_cv:
                self._push_inflight += 1
            try:
                self._push_q.put_nowait((wb_keys, deltas))
            except queue.Full:
                with self._push_cv:
                    self._push_inflight -= 1
                    if self._push_inflight == 0:
                        self._push_cv.notify_all()
            else:
                if _pm._enabled:
                    _m.EMB_CACHE_WRITEBACKS.inc(int(len(wb_keys)))
                return
        self.table.push(wb_keys, deltas)
        # the sync path skips the freshness pull for evicted (now
        # non-resident) keys automatically
        self._refresh_resident(wb_keys)
        if _pm._enabled:
            _m.EMB_CACHE_WRITEBACKS.inc(int(len(wb_keys)))

    def _close_step(self, uniq):
        """Unpin the in-flight pull this push answers (FIFO by key
        signature)."""
        sig = uniq.tobytes()
        with self._lock:           # pull() appends concurrently
            for i, st in enumerate(self._open_steps):
                if st["sig"] == sig:
                    self.cache.unpin(st["rows"][st["rows"] >= 0])
                    del self._open_steps[i]
                    return
        # push without a recorded pull (e.g. eval-mode pull or direct
        # use): nothing pinned, nothing to do

    # ========================================================== control
    def flush(self):
        """Barrier: retire the prefetch, drain the stream push lane,
        write back every dirty row, release leftover pins. After
        flush() the shards hold every update and the cache is clean."""
        self._retire_prefetch()
        with self._lock:
            while self._open_steps:
                st = self._open_steps.popleft()
                self.cache.unpin(st["rows"][st["rows"] >= 0])
        with self._lock:
            # stream mode: these write-backs ENQUEUE on the push lane,
            # so the drain below must come after
            self.cache.flush_all()
        if self.mode == "stream":
            with self._push_cv:
                done = self._push_cv.wait_for(
                    lambda: self._push_inflight == 0
                    or self._push_errors, timeout=60)
            if not done:
                raise TimeoutError("embedding push lane stalled")
        if self._push_errors:
            raise self._push_errors.pop(0)
        if _pm._enabled:
            self.metrics_sync()
        return self

    def close(self):
        self.flush()
        if self._push_q is not None:
            self._push_q.put(None)
            self._push_thread.join(timeout=10)
            self._push_q = None
        if self._pf_pool is not None:
            self._pf_pool.shutdown(wait=True)
            self._pf_pool = None

    # ------------------------------------------------------------ stats
    def hit_ratio(self):
        return self.cache.hit_ratio()

    def dedup_ratio(self):
        return 1.0 - self.uniq_keys / self.raw_keys \
            if self.raw_keys else 0.0

    def state(self):
        s = {"mode": self.mode,
             "cache_rows": self.cache.num_rows,
             "cache_capacity": self.cache.capacity,
             "cache_hit_ratio": round(self.hit_ratio(), 4),
             "dedup_ratio": round(self.dedup_ratio(), 4),
             "evictions": self.cache.evictions,
             "writebacks": self.cache.writebacks,
             "prefetch": {"hits": self.prefetch_hits,
                          "repairs": self.prefetch_repairs,
                          "unused": self.prefetch_unused}}
        try:
            s["table_size"] = len(self.table)
        except (NotImplementedError, TypeError):
            pass          # RemoteSparseTable has no size query yet
        sizes = getattr(self.table, "shard_sizes", None)
        if sizes is not None:
            s["shard_sizes"] = sizes()
        return s

    def metrics_sync(self):
        """Mirror the cache-internal raw counters into the PR 1
        registry (hot paths record incrementally when metrics are on;
        evictions happen inside the cache, so they are mirrored as a
        delta here and at flush())."""
        delta = self.cache.evictions - getattr(
            self, "_mirrored_evictions", 0)
        if delta > 0:
            _m.EMB_CACHE_EVICTIONS.inc(delta)
        self._mirrored_evictions = self.cache.evictions
        _m.EMB_CACHE_ROWS.set(self.cache.num_rows)
        sizes = getattr(self.table, "shard_sizes", None)
        if sizes is not None:
            for s, n in enumerate(sizes()):
                _m.EMB_SHARD_KEYS.labels(str(s)).set(n)
        else:
            try:
                _m.EMB_SHARD_KEYS.labels("0").set(len(self.table))
            except (NotImplementedError, TypeError):
                _m.EMB_SHARD_KEYS.labels("0").set(0)
