"""Sharded table manager: one logical sparse table over N shards.

Parity: the HeterPS partitioned tables (`fleet/heter_ps/heter_ps_base.h`,
`graph_gpu_ps_table.h` — keys are hash-partitioned over table shards and
pull/push fan out per shard). Here every shard is a native
`MemorySparseTable`, so one logical table can exceed any single shard's
memory budget (each shard can spill independently via
`enable_spill`), and the per-shard ctypes calls release the GIL, so the
fan-out threads give real parallelism on the host.

Routing is `splitmix64(key) % num_shards`: raw CTR signs are slot-
prefixed (`slot * 100000 + sign`), so an unmixed modulo would send whole
slots to one shard.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..table import MemorySparseTable
from ...profiler import metrics as _pm
from . import metrics as _m


def splitmix64(keys: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 keys."""
    z = keys.astype(np.uint64, copy=True)
    z ^= z >> np.uint64(30)
    z *= np.uint64(0xBF58476D1CE4E5B9)
    z ^= z >> np.uint64(27)
    z *= np.uint64(0x94D049BB133111EB)
    z ^= z >> np.uint64(31)
    return z


def hash_partition(keys: np.ndarray, num_shards: int) -> np.ndarray:
    """The canonical shard routing: `splitmix64(key) % num_shards`.

    Public so other sharded stores (the graph adjacency table) can
    co-partition with a feature table instead of re-deriving the hash
    scheme; `ShardedSparseTable.partition_fn` hands out a bound form.
    """
    return (splitmix64(np.asarray(keys, np.uint64))
            % np.uint64(num_shards)).astype(np.int64)


class ShardedSparseTable:
    """Key-hash-partitioned logical table, duck-compatible with
    `MemorySparseTable` (pull/push/__len__/save/load/row_width), so it
    drops into `SparseEmbedding(table=...)` even without the engine."""

    def __init__(self, num_shards=2, dim=8, sgd_rule="adagrad",
                 learning_rate=0.05, initial_range=0.02, accessor="ctr",
                 table_factory=None, parallel=True):
        if num_shards < 1:
            raise ValueError(f"num_shards={num_shards} must be >= 1")
        self.num_shards = int(num_shards)
        self.dim = dim
        if table_factory is None:
            def table_factory():
                return MemorySparseTable(dim, sgd_rule, learning_rate,
                                         initial_range, accessor)
        self.shards = [table_factory() for _ in range(self.num_shards)]
        self.accessor = self.shards[0].accessor
        # the executor exists only for num_shards > 1; ctypes releases
        # the GIL inside the native calls, so the fan-out is parallel
        self._pool = ThreadPoolExecutor(
            max_workers=self.num_shards,
            thread_name_prefix="ps-shard") \
            if parallel and self.num_shards > 1 else None

    # ------------------------------------------------------------ routing
    def route(self, flat_keys: np.ndarray) -> np.ndarray:
        """Shard id per key."""
        return hash_partition(flat_keys, self.num_shards)

    @property
    def partition_fn(self):
        """`keys -> shard ids`, the public co-partitioning seam: hand
        this (plus `num_shards`) to another sharded store — e.g.
        `ShardedGraphTable(partition_fn=table.partition_fn, ...)` — so a
        node's adjacency lands on the same shard index as its feature
        row and one fan-out covers both."""
        return self.route

    def _partition(self, flat_keys):
        """-> list of index arrays, one per shard (empty allowed)."""
        sid = self.route(flat_keys)
        return [np.nonzero(sid == s)[0] for s in range(self.num_shards)]

    def _fan_out(self, jobs):
        """jobs: list of (callable, args) per shard; runs them in
        parallel when the pool exists. Returns results in shard order."""
        if self._pool is None:
            return [fn(*args) for fn, args in jobs]
        futs = [self._pool.submit(fn, *args) for fn, args in jobs]
        return [f.result() for f in futs]

    # ---------------------------------------------------------- pull/push
    def pull(self, keys: np.ndarray) -> np.ndarray:
        """keys: uint64 (any shape) -> float32 [*, row_width], exactly
        like `MemorySparseTable.pull` but fanned out per shard."""
        shape = keys.shape
        flat = np.ascontiguousarray(keys.reshape(-1), dtype=np.uint64)
        parts = self._partition(flat)
        out = np.empty((flat.size, self.row_width), np.float32)
        jobs, targets = [], []
        for s, idx in enumerate(parts):
            if idx.size:
                jobs.append((self.shards[s].pull, (flat[idx],)))
                targets.append(idx)
        for idx, res in zip(targets, self._fan_out(jobs)):
            out[idx] = res
        return out.reshape(*shape, self.row_width)

    def push(self, keys: np.ndarray, grads: np.ndarray, shows=None,
             clicks=None):
        flat = np.ascontiguousarray(keys.reshape(-1), dtype=np.uint64)
        g = np.ascontiguousarray(
            grads.reshape(flat.size, self.row_width), np.float32)
        sp = None if shows is None else \
            np.asarray(shows, np.float32).reshape(-1)
        cp = None if clicks is None else \
            np.asarray(clicks, np.float32).reshape(-1)
        jobs = []
        for s, idx in enumerate(self._partition(flat)):
            if idx.size:
                jobs.append((self.shards[s].push,
                             (flat[idx], g[idx],
                              sp[idx] if sp is not None else None,
                              cp[idx] if cp is not None else None)))
        self._fan_out(jobs)
        if _pm._enabled:
            for s, t in enumerate(self.shards):
                _m.EMB_SHARD_KEYS.labels(str(s)).set(len(t))

    # ------------------------------------------------------------ budgets
    def enable_spill(self, directory: str, max_mem_keys: int):
        """Per-shard capacity budgets: the logical budget is divided
        evenly; each shard spills its own overflow to disk."""
        import os
        per = max(1, int(max_mem_keys) // self.num_shards)
        for s, t in enumerate(self.shards):
            t.enable_spill(os.path.join(directory, f"shard{s}"), per)

    # -------------------------------------------------------------- state
    @property
    def row_width(self):
        return self.shards[0].row_width

    def shard_sizes(self):
        return [len(t) for t in self.shards]

    def __len__(self):
        return sum(self.shard_sizes())

    def mem_size(self):
        return sum(t.mem_size() for t in self.shards)

    def spill_size(self):
        return sum(t.spill_size() for t in self.shards)

    def shrink(self, threshold=0.0, max_unseen_days=30):
        return sum(t.shrink(threshold, max_unseen_days)
                   for t in self.shards)

    def save(self, path: str):
        for s, t in enumerate(self.shards):
            t.save(f"{path}.shard{s}")

    def load(self, path: str):
        for s, t in enumerate(self.shards):
            t.load(f"{path}.shard{s}")
