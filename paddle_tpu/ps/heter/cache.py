"""Hot-ID cache: a fixed-capacity dense row cache in front of the shards.

The HeterPS idea (`fleet/heter_ps/`, PAPER.md): the hot head of the key
distribution lives accelerator-adjacent in a dense `[capacity, width]`
buffer; reads hit the cache, misses fall through to the sharded tables.
The ledger adapts the refcount/LRU machinery proven in
`serving/prefix_cache.py` / `kv_cache.BlockAllocator`, but every ledger
is a flat numpy array (stamps, frequencies, pins, dirty flags) so batch
operations stay vectorized — a 2k-key batch costs a few array ops, not
2k heap pushes:

* **Rows are the unit of ownership.** A LIFO free list hands rows out;
  `len(free) + len(index) == capacity` is the ledger invariant the
  soak test asserts after every random op (the allocator's
  `allocated + free == pool` in cache clothing).
* **Pins** are refcounts held by in-flight steps: a pulled batch pins
  the rows backing its keys until its gradient push lands (or the
  engine flushes), so eviction can never reuse a row mid-step. Pinned
  rows are skipped by the evictor — if everything is pinned the caller
  falls through to the shards without caching (bypass), which is
  always correct.
* **Eviction is batched LRU with a frequency second chance**: victims
  are the lowest-stamp unpinned rows (one `argpartition` per admission
  wave); a victim whose id accumulated >= 2 hits since admission gets
  its frequency halved and its recency refreshed once instead of dying
  — hot ids survive bursts of cold ones.
* **Dirty rows carry pending gradient deltas** (streaming mode): the
  delta accumulates in a parallel `[capacity, width]` buffer and is
  ALWAYS written back through the `writeback` callback before the row
  is reused or dropped — eviction cannot lose an update.
"""
from __future__ import annotations

import numpy as np


class HotIdCache:
    """Fixed-capacity dense row cache with a hash-map index."""

    def __init__(self, capacity, width, writeback=None):
        if capacity < 1:
            raise ValueError(f"capacity={capacity} must be >= 1")
        self.capacity = int(capacity)
        self.width = int(width)
        self.values = np.zeros((self.capacity, self.width), np.float32)
        self.dirty = np.zeros((self.capacity, self.width), np.float32)
        self.writeback = writeback        # fn(keys_u64 [n], deltas [n,w])
        self._index = {}                  # key (int) -> row
        self._rowkey = {}                 # row -> key
        # the hot lookup path is a SORTED key array + aligned rows so a
        # whole batch resolves in one vectorized searchsorted; the
        # dicts above stay authoritative and are only walked on
        # admission/eviction (a few hundred keys, not every lookup)
        self._skeys = np.empty(0, np.uint64)
        self._srows = np.empty(0, np.int64)
        self._free = list(range(self.capacity - 1, -1, -1))   # LIFO
        self._used = np.zeros(self.capacity, bool)
        self._pin = np.zeros(self.capacity, np.int32)
        self._stamp = np.zeros(self.capacity, np.int64)
        self._freq = np.zeros(self.capacity, np.int64)
        self._dirtymask = np.zeros(self.capacity, bool)
        self._birth = np.zeros(self.capacity, np.int64)
        self._tick = 0                    # bumped once per batch op
        # raw counters (always on; the engine mirrors deltas into the
        # metrics registry under the one-branch discipline)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.writebacks = 0

    # -------------------------------------------------------------- state
    @property
    def num_rows(self):
        return len(self._index)

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_dirty(self):
        return int(self._dirtymask.sum())

    @property
    def num_pinned(self):
        return int((self._pin > 0).sum())

    @property
    def invariant_ok(self):
        """allocated + free == capacity with no overlap, a consistent
        key<->row mapping, and pins/dirt only on allocated rows."""
        rows = set(self._rowkey)
        free = set(self._free)
        used = set(np.nonzero(self._used)[0].tolist())
        return (len(self._index) == len(self._rowkey)
                and all(self._index[k] in self._rowkey
                        and self._rowkey[self._index[k]] == k
                        for k in self._index)
                and self._skeys.size == len(self._index)
                and (self._skeys[:-1] < self._skeys[1:]).all()
                and all(self._index.get(int(k)) == int(r)
                        for k, r in zip(self._skeys, self._srows))
                and rows == used
                and not (rows & free)
                and len(self._free) == len(free)
                and len(rows) + len(free) == self.capacity
                and not (self._pin > 0)[~self._used].any()
                and not self._dirtymask[~self._used].any())

    def hit_ratio(self):
        t = self.hits + self.misses
        return self.hits / t if t else 0.0

    # ------------------------------------------------------------- lookup
    def lookup(self, keys, count=True) -> np.ndarray:
        """-> int64 rows, -1 per miss. Touches LRU recency + hit
        frequency for hits; `count=False` skips all accounting
        (internal coherence reads, e.g. the push-side refresh)."""
        ks = np.ascontiguousarray(np.asarray(keys).reshape(-1),
                                  np.uint64)
        n = self._skeys.size
        if n == 0:
            rows = np.full(ks.size, -1, np.int64)
        else:
            pos = np.minimum(np.searchsorted(self._skeys, ks), n - 1)
            rows = np.where(self._skeys[pos] == ks,
                            self._srows[pos], -1)
        if count:
            hit = rows[rows >= 0]
            self.hits += hit.size
            self.misses += rows.size - hit.size
            if hit.size:
                self._tick += 1
                self._stamp[hit] = self._tick
                self._freq[hit] += 1
        return rows

    def gather(self, rows: np.ndarray) -> np.ndarray:
        return self.values[rows]

    def _sorted_insert(self, new_keys, new_rows):
        nk = np.asarray(new_keys, np.uint64)
        nr = np.asarray(new_rows, np.int64)
        order = np.argsort(nk, kind="stable")
        nk, nr = nk[order], nr[order]
        pos = np.searchsorted(self._skeys, nk)
        self._skeys = np.insert(self._skeys, pos, nk)
        self._srows = np.insert(self._srows, pos, nr)

    def _sorted_delete(self, dead_keys):
        dk = np.sort(np.asarray(dead_keys, np.uint64))
        pos = np.searchsorted(self._skeys, dk)
        self._skeys = np.delete(self._skeys, pos)
        self._srows = np.delete(self._srows, pos)

    # ---------------------------------------------------------- admission
    def _evict_batch(self, want):
        """Reclaim up to `want` unpinned rows into the free list
        (batched LRU + one frequency second chance per victim set).
        Returns the number reclaimed; dirty victims are written back
        FIRST — never dropped."""
        reclaimed = 0
        for _ in range(2):            # pass 2 re-selects after chances
            need = want - reclaimed
            if need <= 0:
                break
            cand = np.nonzero(self._used & (self._pin == 0))[0]
            if cand.size == 0:
                break
            stamps = self._stamp[cand]
            if cand.size > need:
                part = np.argpartition(stamps, need - 1)[:need]
                part = part[np.argsort(stamps[part], kind="stable")]
                victims = cand[part]
            else:
                victims = cand[np.argsort(stamps, kind="stable")]
            hot = self._freq[victims] >= 2
            spare = victims[hot]
            if spare.size:
                # hot ids: one second chance instead of death
                self._freq[spare] //= 2
                self._tick += 1
                self._stamp[spare] = self._tick
            victims = victims[~hot]
            if victims.size:
                self._reclaim(victims)
                reclaimed += victims.size
        if reclaimed < want:
            # everything left is hot (already spent its chance) —
            # force-evict coldest regardless of frequency
            cand = np.nonzero(self._used & (self._pin == 0))[0]
            need = want - reclaimed
            if cand.size:
                stamps = self._stamp[cand]
                take = min(need, cand.size)
                part = np.argpartition(stamps, take - 1)[:take] \
                    if cand.size > take else np.arange(cand.size)
                self._reclaim(cand[part])
                reclaimed += take
        return reclaimed

    def _reclaim(self, rows):
        """Write back + unmap a batch of resident, unpinned rows. The
        dirty deltas are captured while the key mapping is still live
        but DELIVERED after the unmap, so the engine's writeback skips
        its freshness re-pull for rows that no longer exist."""
        rows = np.asarray(rows, np.int64)
        wb = self.take_dirty(rows)
        dead = []
        for r in rows.tolist():
            key = self._rowkey.pop(r)
            del self._index[key]
            dead.append(key)
        self._sorted_delete(dead)
        self._used[rows] = False
        self._stamp[rows] = 0
        self._freq[rows] = 0
        if wb is not None:
            # delivered BEFORE the rows re-enter the free list: an
            # eviction can never lose (or reorder past reuse) a delta
            if self.writeback is not None:
                self.writeback(*wb)
            self.writebacks += int(wb[0].size)
        self._free.extend(rows.tolist())
        self.evictions += rows.size

    def admit(self, keys, values, step=0) -> np.ndarray:
        """Install rows for `keys` (absent ones only), evicting as
        needed. -> int64 rows, -1 where the key could not be admitted
        (cache saturated with pinned rows — the caller serves the
        value straight from the shards)."""
        keys = np.asarray(keys)
        values = np.asarray(values)
        rows = self.lookup(keys, count=False)
        have = rows >= 0
        if have.any():
            # refresh already-resident keys NOW, while this mapping is
            # still valid — the eviction below may reassign these very
            # rows to fresh keys, and a late write-through would then
            # plant one key's values under another key's row
            self.values[rows[have]] = values[have]
        fresh = np.nonzero(~have)[0]
        if fresh.size:
            shortfall = fresh.size - len(self._free)
            if shortfall > 0:
                self._evict_batch(shortfall)
            self._tick += 1
            added_k, added_r = [], []
            for i in fresh.tolist():
                k = int(keys.reshape(-1)[i])
                row = self._index.get(k, -1)   # dup key within call
                if row < 0:
                    if not self._free:
                        rows[i] = -1
                        continue
                    row = self._free.pop()
                    self._index[k] = row
                    self._rowkey[row] = k
                    self._used[row] = True
                    self._freq[row] = 0
                    self._stamp[row] = self._tick
                    added_k.append(k)
                    added_r.append(row)
                rows[i] = row
            if added_k:
                self._sorted_insert(added_k, added_r)
            got = rows[fresh]
            ok = got >= 0
            if ok.any():
                self.values[got[ok]] = values[fresh[ok]]
        return rows

    # ----------------------------------------------------------- pinning
    def pin(self, rows):
        rows = np.asarray(rows, np.int64).reshape(-1)
        if rows.size == 0:
            return
        if not self._used[rows].all():
            raise ValueError("pin of unallocated row")
        np.add.at(self._pin, rows, 1)

    def unpin(self, rows):
        rows = np.asarray(rows, np.int64).reshape(-1)
        if rows.size == 0:
            return
        pins = self._pin.copy()
        np.subtract.at(pins, rows, 1)
        if (pins[rows] < 0).any():
            raise ValueError("unpin of unpinned row")
        self._pin = pins

    # ------------------------------------------------------ dirty ledger
    def set_values(self, rows: np.ndarray, values: np.ndarray):
        """Coherence refresh (strict mode: fresh table values after a
        push)."""
        self.values[rows] = values

    def add_delta(self, rows: np.ndarray, deltas: np.ndarray, step=0,
                  unique_rows=False):
        """Accumulate pending gradient deltas (streaming mode).
        `unique_rows=True` (rows already dedup'd, the engine's merged
        push) takes the vectorized fancy-index path instead of
        np.add.at."""
        rows = np.asarray(rows, np.int64)
        if unique_rows:
            self.dirty[rows] += deltas
        else:
            np.add.at(self.dirty, rows, deltas)
        newly = rows[~self._dirtymask[rows]]
        self._dirtymask[rows] = True
        self._birth[newly] = step
        return rows

    def stale_rows(self, before_step):
        """Dirty rows whose first pending delta is older than
        `before_step` (the engine's staleness bound)."""
        return np.nonzero(self._dirtymask
                          & (self._birth < before_step))[0]

    def take_dirty(self, rows):
        """Extract (keys, deltas) for the dirty subset of `rows`,
        clearing their dirty state WITHOUT invoking the writeback
        callback — the caller delivers the deltas (e.g. through a
        background push lane). None when nothing is dirty."""
        rows = np.asarray(rows, np.int64).reshape(-1)
        todo = rows[self._dirtymask[rows]] if rows.size else rows
        todo = np.unique(todo)
        if todo.size == 0:
            return None
        keys = np.asarray([self._rowkey[int(r)] for r in todo],
                          np.uint64)
        deltas = self.dirty[todo].copy()
        # clear BEFORE handing out: a re-entrant add_delta during the
        # delivery must open a fresh delta, not re-dirty this one
        self.dirty[todo] = 0.0
        self._dirtymask[todo] = False
        return keys, deltas

    def flush_rows(self, rows):
        """Write back the pending deltas of `rows` (dirty ones only)
        through the writeback callback; clears their dirty state.
        Returns the number of rows written back."""
        wb = self.take_dirty(rows)
        if wb is None:
            return 0
        if self.writeback is not None:
            self.writeback(*wb)
        self.writebacks += int(wb[0].size)
        return int(wb[0].size)

    def flush_all(self):
        return self.flush_rows(np.nonzero(self._dirtymask)[0])

    # ------------------------------------------------------------- admin
    def drop(self, keys):
        """Invalidate keys (writes back dirty state first)."""
        rows = np.fromiter(
            (self._index.get(int(k), -1) for k in keys), np.int64,
            count=len(keys))
        rows = rows[rows >= 0]
        rows = rows[self._pin[rows] == 0]
        if rows.size:
            self._reclaim(np.unique(rows))

    def clear(self):
        self.flush_all()
        if self.num_pinned:
            raise RuntimeError(
                f"clear() with {self.num_pinned} pinned rows")
        self._index.clear()
        self._rowkey.clear()
        self._skeys = np.empty(0, np.uint64)
        self._srows = np.empty(0, np.int64)
        self._used[:] = False
        self._stamp[:] = 0
        self._freq[:] = 0
        self._dirtymask[:] = False
        self._free = list(range(self.capacity - 1, -1, -1))
