"""Read-only lookup serving over the embedding engine.

The inference half of the streaming story: an online-trained model
serves user/item embedding lookups through the SAME hot-ID cache the
trainer keeps warm (`fleet/heter_ps` serves its GPU tables to both the
train and the predict pass). Lookups never push, never pin past the
gather, and never mutate the SGD state — in ``stream`` mode they see
at most the engine's staleness window; in ``strict`` mode they are
exact table reads.
"""
from __future__ import annotations

import numpy as np

from ...profiler import metrics as _pm
from . import metrics as _m


class LookupService:
    """`lookup(keys) -> [*, dim]` through the engine's cache."""

    def __init__(self, engine):
        self.engine = engine
        self.served = 0          # raw counter (requests)

    def lookup(self, keys) -> np.ndarray:
        """keys: any-shape id array -> float32 [*, dim]. Read-only:
        misses are admitted to the shared cache (warming it for the
        trainer too), but nothing is pushed or pinned — and the
        trainer's pending prefetch is left untouched (side traffic
        must not retire the pipeline's double buffer)."""
        out = self.engine.pull(keys, train=False, use_prefetch=False)
        self.served += 1
        if _pm._enabled:
            _m.EMB_LOOKUPS_SERVED.inc()
        return out

    def lookup_one(self, key) -> np.ndarray:
        return self.lookup(np.asarray([key], np.uint64))[0]

    def state(self):
        return {"served": self.served,
                "cache_hit_ratio": round(self.engine.hit_ratio(), 4)}
