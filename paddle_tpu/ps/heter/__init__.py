"""paddle_tpu.ps.heter — HeterPS-style sharded embedding engine.

The recommender-scale path between the native PS tables and the TPU
step (ROADMAP item 4, `fleet/heter_ps/` + `ps_gpu_wrapper.h` parity):

* `ShardedSparseTable` — one logical table key-hash-partitioned over N
  native `MemorySparseTable` shards with parallel pull/push fan-out.
* `HotIdCache` — fixed-capacity dense row cache with refcounted pins,
  LRU/frequency eviction and dirty-row write-back.
* `HeterEmbeddingEngine` — per-batch dedup, background prefetch with
  strict-mode repair, merged gradient push (strict = coherent/parity,
  stream = online training with a bounded staleness window).
* `LookupService` — read-only inference lookups through the same cache.

`SparseEmbedding(engine=...)` switches the layer onto the engine while
keeping the leaf-hook autograd contract (docs/EMBEDDING.md).
"""
from .sharded import ShardedSparseTable, splitmix64  # noqa: F401
from .cache import HotIdCache  # noqa: F401
from .engine import HeterEmbeddingEngine  # noqa: F401
from .service import LookupService  # noqa: F401
from . import metrics  # noqa: F401
