"""Graph table + samplers over the native engine.

Parity: the fork-focus graph engine (`graph_gpu_ps_table.h`,
`gpu_graph_node.h`, `graph_sampler_inl.h`; `ps/table/common_graph_table.h`)
— adjacency storage keyed by uint64 node ids with random-walk and
neighbor sampling feeding GNN training (paddle_tpu.geometric ops consume
the sampled edges on the TPU).
"""
from __future__ import annotations

import ctypes

import numpy as np

from ._native import get_lib, u64_ptr, i32_ptr


def _bind_graph(lib):
    if getattr(lib, "_graph_bound", False):
        return lib
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i32p = ctypes.POINTER(ctypes.c_int)
    lib.pscore_graph_create.restype = ctypes.c_int
    lib.pscore_graph_add_edges.argtypes = [ctypes.c_int, u64p, u64p,
                                           ctypes.c_int64]
    lib.pscore_graph_sample_neighbors.argtypes = [
        ctypes.c_int, u64p, ctypes.c_int64, ctypes.c_int, u64p, i32p]
    lib.pscore_graph_random_walk.argtypes = [
        ctypes.c_int, u64p, ctypes.c_int64, ctypes.c_int, u64p]
    lib.pscore_graph_num_nodes.argtypes = [ctypes.c_int]
    lib.pscore_graph_num_nodes.restype = ctypes.c_int64
    lib.pscore_graph_sample_nodes.argtypes = [ctypes.c_int,
                                              ctypes.c_int64, u64p]
    lib._graph_bound = True
    return lib


class GraphTable:
    def __init__(self):
        self._lib = _bind_graph(get_lib())
        self._h = self._lib.pscore_graph_create()

    def add_edges(self, src, dst):
        src = np.ascontiguousarray(np.asarray(src).reshape(-1), np.uint64)
        dst = np.ascontiguousarray(np.asarray(dst).reshape(-1), np.uint64)
        assert src.size == dst.size
        self._lib.pscore_graph_add_edges(self._h, u64_ptr(src),
                                         u64_ptr(dst), src.size)

    def sample_neighbors(self, nodes, k):
        q = np.ascontiguousarray(np.asarray(nodes).reshape(-1), np.uint64)
        out = np.empty((q.size, k), np.uint64)
        deg = np.empty(q.size, np.int32)
        self._lib.pscore_graph_sample_neighbors(
            self._h, u64_ptr(q), q.size, k, u64_ptr(out), i32_ptr(deg))
        return out, deg

    def random_walk(self, starts, walk_len):
        s = np.ascontiguousarray(np.asarray(starts).reshape(-1),
                                 np.uint64)
        out = np.empty((s.size, walk_len + 1), np.uint64)
        self._lib.pscore_graph_random_walk(self._h, u64_ptr(s), s.size,
                                           walk_len, u64_ptr(out))
        return out

    def num_nodes(self):
        return int(self._lib.pscore_graph_num_nodes(self._h))

    def sample_nodes(self, n):
        out = np.empty(n, np.uint64)
        self._lib.pscore_graph_sample_nodes(self._h, n, u64_ptr(out))
        return out
