"""Multi-threaded PS training loops — the trainer/DeviceWorker family.

Parity: `exe.train_from_dataset` (`python/paddle/fluid/executor.py:2582`)
dispatching over the trainer hierarchy (`framework/trainer.h:59-341`):

- `HogwildTrainer` — `MultiTrainer`+`HogwildWorker::TrainFiles`
  (`framework/hogwild_worker.cc:223`): N threads share the model state,
  lock-free on the shard-parallel native tables.
- `MultiTrainer` — the thread-LOCAL-replica semantics of the reference's
  local `MultiTrainer` (`trainer.h:105`, `MergeToRootScope`): each
  worker trains its own dense-param copy; Finalize merges the replicas
  back into the root params by mean.
- `DistMultiTrainer` — `trainer.h:141`: Hogwild workers plus an
  `AsyncCommunicator` lifecycle (start before training, flush barrier
  per epoch, stop at finalize — `communicator.py` a_sync parity).

All of them share the TrainerBase dump machinery
(`trainer.h:88 dump_fields_path/DumpWork`): when `set_dump()` is
configured, every worker appends instance lines to `part-<tid>` under
the dump path — the reference's CTR feature-dump debugging flow.

Compiled steps release the GIL during XLA execution, so threads overlap
host pull/push with device compute.
"""
from __future__ import annotations

import os
import threading

from .table import InMemoryDataset


class TrainerBase:
    """Dump-env plumbing + shared epoch scaffolding (`trainer.h:59`)."""

    def __init__(self, num_threads=4):
        self.num_threads = num_threads
        self.metrics_lock = threading.Lock()
        self.losses = []
        self._dump_path = None
        self._dump_fields = None
        self._dump_param = None
        self._dump_files = {}
        self._dump_lock = threading.Lock()

    def set_dump(self, path, fields=True, param=None):
        """Enable per-worker instance dumping (`dump_fields_path`).
        `fields`: True dumps batch inputs; or a callable
        (keys, labels, loss) -> str line. `param`: optional callable
        () -> str appended once per epoch per worker. Existing part
        files under `path` are removed — a re-run must not interleave
        stale lines into the dump being debugged with."""
        self._dump_path = path
        self._dump_fields = fields
        self._dump_param = param
        os.makedirs(path, exist_ok=True)
        for name in os.listdir(path):
            if name.startswith("part-"):
                os.unlink(os.path.join(path, name))

    def _dump_file(self, tid):
        with self._dump_lock:
            f = self._dump_files.get(tid)
            if f is None:
                f = open(os.path.join(self._dump_path, f"part-{tid}"),
                         "a")
                self._dump_files[tid] = f
            return f

    def _dump_batch(self, tid, keys, labels, loss):
        if self._dump_path is None:
            return
        if callable(self._dump_fields):
            line = self._dump_fields(keys, labels, loss)
        else:
            ks = " ".join(str(int(k)) for k in
                          getattr(keys, "flat", keys))
            ls = " ".join(str(float(v)) for v in
                          getattr(labels, "flat", labels))
            line = f"keys:{ks}\tlabels:{ls}\tloss:{float(loss):.6f}"
        self._dump_file(tid).write(line + "\n")

    def _dump_param_line(self, tid):
        if self._dump_path is not None and self._dump_param is not None:
            self._dump_file(tid).write(self._dump_param() + "\n")

    def finalize_dump(self):
        with self._dump_lock:
            for f in self._dump_files.values():
                f.close()
            self._dump_files.clear()

    # shared epoch scaffolding: shuffle/rewind, locked iterator fetch,
    # N worker threads running a per-tid step over shared batches, dump
    # lines, first-error propagation. finalize_dump always runs (error
    # included) so the dump the user is debugging WITH is never left
    # truncated in open buffers.
    def _run_epochs(self, dataset, make_tid_step, epochs, shuffle,
                    end_epoch=None):
        try:
            for epoch in range(epochs):
                shuffle(epoch)
                it = iter(dataset)
                it_lock = threading.Lock()
                errors = []

                def worker(tid):
                    step_fn = make_tid_step(tid)
                    while True:
                        with it_lock:
                            batch = next(it, None)
                        if batch is None:
                            return
                        try:
                            loss = step_fn(*batch)
                            with self.metrics_lock:
                                self.losses.append(float(loss))
                            self._dump_batch(tid, batch[0], batch[-1],
                                             loss)
                        except Exception as e:  # noqa: BLE001
                            errors.append(e)
                            return

                threads = [threading.Thread(target=worker, args=(tid,))
                           for tid in range(self.num_threads)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                if errors:
                    raise errors[0]
                for tid in range(self.num_threads):
                    self._dump_param_line(tid)
                if end_epoch is not None:
                    end_epoch(epoch)
        finally:
            self.finalize_dump()
        return self.losses


class HogwildTrainer(TrainerBase):
    """train_from_dataset(dataset, step_fn, num_threads)."""

    def train_from_dataset(self, dataset: InMemoryDataset, step_fn,
                           epochs=1, shuffle_seed=None,
                           end_epoch=None):
        """step_fn(keys, labels) -> float loss. Called concurrently from
        worker threads; the PS tables underneath are shard-locked."""
        def shuffle(epoch):
            if shuffle_seed is not None:
                dataset.global_shuffle(seed=shuffle_seed + epoch)
            else:
                dataset.rewind()

        return self._run_epochs(dataset, lambda tid: step_fn, epochs,
                                shuffle, end_epoch=end_epoch)


class MultiTrainer(TrainerBase):
    """Thread-local-replica trainer (`trainer.h:105 MultiTrainer` +
    `MergeToRootScope`): every worker thread trains its OWN copy of the
    dense params; after each epoch the replicas are merged back into
    the root params by mean. Sparse state stays shared in the PS tables
    (exactly the reference's split: dense in thread scopes, sparse in
    the table service)."""

    def train_from_dataset(self, dataset: InMemoryDataset, make_step,
                           params, epochs=1, shuffle_seed=None):
        """`params`: dict name -> np.ndarray (the root dense scope).
        `make_step(local_params) -> step_fn(keys, labels) -> loss`
        builds a worker closure over its REPLICA dict (same keys,
        copies of the arrays, mutated in place by the step)."""
        import numpy as np
        replicas = []

        def shuffle(epoch):
            if shuffle_seed is not None:
                dataset.local_shuffle(seed=shuffle_seed + epoch)
            else:
                dataset.rewind()
            replicas[:] = [{k: np.array(v, copy=True)
                            for k, v in params.items()}
                           for _ in range(self.num_threads)]

        def merge(epoch):
            # MergeToRootScope: mean of the replicas into the root
            for k in params:
                params[k][...] = np.mean([r[k] for r in replicas],
                                         axis=0)

        return self._run_epochs(
            dataset, lambda tid: make_step(replicas[tid]), epochs,
            shuffle, end_epoch=merge)


class DistMultiTrainer(HogwildTrainer):
    """`trainer.h:141 DistMultiTrainer`: Hogwild workers plus the
    AsyncCommunicator lifecycle — start() before training, a flush
    barrier after every epoch (so merged sparse grads reach the
    service), stop() at finalize."""

    def __init__(self, num_threads=4, communicator=None):
        super().__init__(num_threads)
        self.communicator = communicator

    def train_from_dataset(self, dataset, step_fn, epochs=1,
                           shuffle_seed=None):
        comm = self.communicator
        if comm is None:
            return super().train_from_dataset(dataset, step_fn, epochs,
                                              shuffle_seed)
        comm.start()
        try:
            return super().train_from_dataset(
                dataset, step_fn, epochs, shuffle_seed,
                end_epoch=lambda epoch: comm.flush())
        finally:
            comm.stop()
