"""Graph table + samplers over the native engine.

Parity: the fork-focus graph engine (`graph_gpu_ps_table.h`,
`gpu_graph_node.h`, `graph_sampler_inl.h`; `ps/table/common_graph_table.h`)
— adjacency storage keyed by uint64 node ids with random-walk and
neighbor sampling (uniform or edge-weight-proportional) plus per-node
float feature vectors (`Node::get_feature` capability; the per-edge
feature supported is its sampling weight), feeding GNN training
(paddle_tpu.geometric ops consume the sampled edges on the TPU).
"""
from __future__ import annotations

import ctypes

import numpy as np

from .._native import get_lib, u64_ptr, f32_ptr, i32_ptr


def _bind_graph(lib):
    if getattr(lib, "_graph_bound", False):
        return lib
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i32p = ctypes.POINTER(ctypes.c_int)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.pscore_graph_create.restype = ctypes.c_int
    lib.pscore_graph_add_edges.argtypes = [ctypes.c_int, u64p, u64p,
                                           ctypes.c_int64]
    lib.pscore_graph_add_edges_weighted.argtypes = [
        ctypes.c_int, u64p, u64p, f32p, ctypes.c_int64]
    lib.pscore_graph_set_node_feat.argtypes = [
        ctypes.c_int, u64p, ctypes.c_int64, ctypes.c_int, f32p]
    lib.pscore_graph_get_node_feat.argtypes = [
        ctypes.c_int, u64p, ctypes.c_int64, ctypes.c_int, f32p]
    lib.pscore_graph_sample_neighbors.argtypes = [
        ctypes.c_int, u64p, ctypes.c_int64, ctypes.c_int, u64p, i32p]
    lib.pscore_graph_random_walk.argtypes = [
        ctypes.c_int, u64p, ctypes.c_int64, ctypes.c_int, u64p]
    lib.pscore_graph_num_nodes.argtypes = [ctypes.c_int]
    lib.pscore_graph_num_nodes.restype = ctypes.c_int64
    lib.pscore_graph_sample_nodes.argtypes = [ctypes.c_int,
                                              ctypes.c_int64, u64p]
    lib._graph_bound = True
    return lib


class GraphTable:
    def __init__(self):
        self._lib = _bind_graph(get_lib())
        self._h = self._lib.pscore_graph_create()

    def add_edges(self, src, dst):
        src = np.ascontiguousarray(np.asarray(src).reshape(-1), np.uint64)
        dst = np.ascontiguousarray(np.asarray(dst).reshape(-1), np.uint64)
        assert src.size == dst.size
        self._lib.pscore_graph_add_edges(self._h, u64_ptr(src),
                                         u64_ptr(dst), src.size)

    def add_edges_weighted(self, src, dst, weights):
        """Edges with sampling weights: sample_neighbors/random_walk pick
        neighbors with probability proportional to weight."""
        src = np.ascontiguousarray(np.asarray(src).reshape(-1), np.uint64)
        dst = np.ascontiguousarray(np.asarray(dst).reshape(-1), np.uint64)
        w = np.ascontiguousarray(np.asarray(weights).reshape(-1),
                                 np.float32)
        assert src.size == dst.size == w.size
        self._lib.pscore_graph_add_edges_weighted(
            self._h, u64_ptr(src), u64_ptr(dst), f32_ptr(w), src.size)

    def set_node_feat(self, nodes, feats):
        """Per-node float feature vectors [n, dim]."""
        q = np.ascontiguousarray(np.asarray(nodes).reshape(-1), np.uint64)
        f = np.ascontiguousarray(np.asarray(feats, np.float32).reshape(
            q.size, -1))
        self.feat_dim = f.shape[1]
        self._lib.pscore_graph_set_node_feat(
            self._h, u64_ptr(q), q.size, f.shape[1], f32_ptr(f))

    def get_node_feat(self, nodes, dim=None):
        """[n, dim] features; zeros for nodes without features."""
        q = np.ascontiguousarray(np.asarray(nodes).reshape(-1), np.uint64)
        dim = dim if dim is not None else getattr(self, "feat_dim", 0)
        out = np.empty((q.size, dim), np.float32)
        self._lib.pscore_graph_get_node_feat(
            self._h, u64_ptr(q), q.size, dim, f32_ptr(out))
        return out.reshape(*np.asarray(nodes).shape, dim)

    def sample_neighbors(self, nodes, k):
        q = np.ascontiguousarray(np.asarray(nodes).reshape(-1), np.uint64)
        out = np.empty((q.size, k), np.uint64)
        deg = np.empty(q.size, np.int32)
        self._lib.pscore_graph_sample_neighbors(
            self._h, u64_ptr(q), q.size, k, u64_ptr(out), i32_ptr(deg))
        return out, deg

    def random_walk(self, starts, walk_len):
        s = np.ascontiguousarray(np.asarray(starts).reshape(-1),
                                 np.uint64)
        out = np.empty((s.size, walk_len + 1), np.uint64)
        self._lib.pscore_graph_random_walk(self._h, u64_ptr(s), s.size,
                                           walk_len, u64_ptr(out))
        return out

    def num_nodes(self):
        return int(self._lib.pscore_graph_num_nodes(self._h))

    def sample_nodes(self, n):
        out = np.empty(n, np.uint64)
        self._lib.pscore_graph_sample_nodes(self._h, n, u64_ptr(out))
        return out
