"""GraphEngine: multi-hop neighbor sampling composed with the embedding
engine's feature pull, pipelined exactly like `ps/heter/engine.py`.

The pull/push cycle of the reference GPU graph engine
(`fleet/heter_ps/graph_gpu_ps_table.h` + `ps_gpu_wrapper` pull/push)
rebuilt on the PR 6 substrate:

* **Multi-hop frontier expansion with per-hop dedup.** Hop h's frontier
  collapses to unique nodes (`np.unique` + inverse gather) before the
  sharded sample — a power-law hub is sampled once per hop no matter
  how many frontier slots point at it, and every slot gets the SAME
  neighborhood (the dedup is semantics, not just traffic: it is what
  makes the bundle a pure function of (graph, seed)).
* **Fixed-shape bundles.** For fanouts (f0, f1, ...) the bundle arrays
  are `[B, f0]`, `[B*f0, f1]`, ... plus masks — shapes depend only on
  (B, fanouts), so the consuming jitted SAGE step compiles once.
* **Deterministic seeds from a sample clock.** Batch N's sampling seed
  is `splitmix64(base_seed + N)` where N counts *consumed* batches.
  A prefetch for batch N+1 predicts clock N+1; the pipelined and the
  sequential schedule therefore draw the SAME neighborhoods, which is
  what the bit-identity parity contract rests on.
* **Double-buffered bundle prefetch.** `prefetch(next_seeds)` samples
  batch N+1's hops on a background thread and hands the resulting key
  block to `features.prefetch(...)` — so batch N+1's adjacency AND
  feature traffic both overlap batch N's dense step. Consume-time
  coherence (strict mode): if any streamed mutation that landed after
  the prefetch snapshot touches any frontier node of the pending
  bundle, the whole bundle is resampled with the SAME seed (counted as
  a repair); the feature block then re-pulls through the embedding
  engine's own consume/repair machinery (a key mismatch after a graph
  repair retires the feature prefetch automatically).
* **Streaming mutations.** `add_edges`/`remove_edges` ride a bounded
  background queue (backpressure, not loss). ``strict`` mode makes
  `sample_batch` barrier on every mutation enqueued before the call —
  sample-after-update coherence for tests and the parity oracle;
  ``stream`` mode lets samples race the queue (online training: the
  staleness window is the queue depth).
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..heter.sharded import splitmix64
from ...profiler import metrics as _pm
from . import metrics as _m


def _seed_for(base_seed: int, clock: int) -> int:
    return int(splitmix64(np.asarray(
        [(int(base_seed) + int(clock)) & 0xFFFFFFFFFFFFFFFF],
        np.uint64))[0])


class GraphBatch:
    """One fixed-shape multi-hop bundle.

    `keys` is the concatenation [seeds, neighbors[0].ravel(), ...] —
    the exact array to `features.push(...)` gradients against (the
    embedding engine's dedup memo recognizes it and skips the re-sort).
    """
    __slots__ = ("seeds", "neighbors", "masks", "keys", "features",
                 "seed", "clock")

    def __init__(self, seeds, neighbors, masks, keys, features, seed,
                 clock):
        self.seeds = seeds          # [B] uint64
        self.neighbors = neighbors  # tuple: [B,f0], [B*f0,f1], ...
        self.masks = masks          # same shapes, bool
        self.keys = keys            # [B + B*f0 + ...] uint64
        self.features = features    # [len(keys), dim] f32 | None
        self.seed = seed
        self.clock = clock

    def level_sizes(self):
        sizes = [self.seeds.size]
        for nb in self.neighbors:
            sizes.append(nb.size)
        return sizes


class GraphEngine:
    """Sharded adjacency + embedding features behind one pipelined,
    coherence-checked sampling front end."""

    def __init__(self, graph, features=None, fanouts=(10, 5),
                 mode="strict", base_seed=0, prefetch=True,
                 update_queue=16):
        if mode not in ("strict", "stream"):
            raise ValueError(f"mode={mode!r} not in ('strict','stream')")
        fanouts = tuple(int(f) for f in fanouts)
        if not fanouts or any(f < 1 for f in fanouts):
            raise ValueError(f"fanouts={fanouts} must be >=1 each")
        self.graph = graph
        self.features = features
        self.fanouts = fanouts
        self.mode = mode
        self.base_seed = int(base_seed)
        self._clock = 0            # consumed batches (the seed source)
        # streaming-mutation lane
        self._cv = threading.Condition()
        self._submitted_seq = 0
        self._applied_seq = 0
        self._version = 0          # bumps once per applied mutation
        self._touched = deque()    # (version, unique src nodes)
        self._touched_floor = 0
        self._upd_q = queue.Queue(maxsize=max(1, int(update_queue)))
        self._upd_errors = []
        self._upd_thread = threading.Thread(target=self._update_loop,
                                            daemon=True)
        self._upd_thread.start()
        # one pending bundle prefetch (double buffering)
        self._pf_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="graph-prefetch") \
            if prefetch else None
        self._pf_pending = None
        # raw counters (bench/tests read these without the registry)
        self.raw_frontier = 0
        self.uniq_frontier = 0
        self.prefetch_hits = 0
        self.prefetch_repairs = 0
        self.prefetch_unused = 0
        self.stream_adds = 0
        self.stream_removes = 0
        self.sample_batches = 0

    # ================================================= streaming updates
    def add_edges(self, src, dst, weights=None):
        """Enqueue a directed-edge batch (applied in order by the
        background worker; blocks only when the queue is full)."""
        return self._enqueue("add", src, dst, weights)

    def remove_edges(self, src, dst):
        return self._enqueue("remove", src, dst, None)

    def _enqueue(self, op, src, dst, weights):
        src = np.ascontiguousarray(np.asarray(src).reshape(-1),
                                   np.uint64).copy()
        dst = np.ascontiguousarray(np.asarray(dst).reshape(-1),
                                   np.uint64).copy()
        w = None if weights is None else \
            np.asarray(weights, np.float32).reshape(-1).copy()
        with self._cv:
            self._submitted_seq += 1
            seq = self._submitted_seq
        self._upd_q.put((seq, op, src, dst, w))
        return seq

    def _update_loop(self):
        while True:
            item = self._upd_q.get()
            if item is None:
                return
            seq, op, src, dst, w = item
            touched = np.unique(src)
            try:
                if op == "add":
                    self.graph.add_edges(src, dst, w)
                    self.stream_adds += 1
                else:
                    self.graph.remove_edges(src, dst)
                    self.stream_removes += 1
                if _pm._enabled:
                    _m.GRAPH_STREAM_UPDATES.labels(op).inc()
            except Exception as e:  # noqa: BLE001 — surface on flush
                self._upd_errors.append(e)
            finally:
                with self._cv:
                    self._version += 1
                    self._touched.append((self._version, touched))
                    while len(self._touched) > 64:
                        self._touched_floor = \
                            self._touched.popleft()[0]
                    self._applied_seq = seq
                    self._cv.notify_all()

    def _barrier(self, upto_seq, timeout=60):
        with self._cv:
            done = self._cv.wait_for(
                lambda: self._applied_seq >= upto_seq
                or self._upd_errors, timeout=timeout)
        if not done:
            raise TimeoutError("graph update lane stalled")
        if self._upd_errors:
            raise self._upd_errors.pop(0)

    def _conflicts(self, version, node_union):
        """True when a mutation applied after `version` touches any
        node of the pending bundle's frontier union. A snapshot older
        than the retained history conservatively conflicts."""
        with self._cv:
            if version < self._touched_floor:
                return True
            touched = [ks for v, ks in self._touched if v > version]
        if not touched:
            return False
        return bool(np.isin(np.concatenate(touched), node_union,
                            assume_unique=False).any())

    # ========================================================= sampling
    def _sample_hops(self, seeds, batch_seed):
        """Pure multi-hop expansion: (neighbors, masks, node_union,
        raw, uniq). Deterministic in (graph state, batch_seed)."""
        neighbors, masks = [], []
        uniqs = [np.unique(seeds)]
        frontier = seeds
        raw = uniq_n = 0
        for h, f in enumerate(self.fanouts):
            raw += frontier.size
            uniq, inv = np.unique(frontier, return_inverse=True)
            uniq_n += uniq.size
            nb_u, mk_u = self.graph.sample_neighbors(
                uniq, f, seed=(batch_seed + h) & 0xFFFFFFFFFFFFFFFF)
            neighbors.append(nb_u[inv])
            masks.append(mk_u[inv])
            frontier = neighbors[-1].reshape(-1)
            uniqs.append(np.unique(frontier))
        node_union = np.unique(np.concatenate(uniqs))
        return (tuple(neighbors), tuple(masks), node_union, raw,
                uniq_n)

    @staticmethod
    def _bundle_keys(seeds, neighbors):
        return np.concatenate(
            [seeds] + [nb.reshape(-1) for nb in neighbors])

    def sample_batch(self, seeds, train=False):
        """seeds: uint64 [B] -> GraphBatch. In strict mode the sample
        reflects every mutation enqueued before this call (barrier +
        prefetch repair); in stream mode it reflects whatever the
        update worker has applied so far."""
        t0 = time.perf_counter()
        seeds = np.ascontiguousarray(np.asarray(seeds).reshape(-1),
                                     np.uint64)
        if self.mode == "strict":
            with self._cv:
                upto = self._submitted_seq
            self._barrier(upto)
        clock = self._clock
        batch_seed = _seed_for(self.base_seed, clock)
        got = self._consume_prefetch(seeds, clock)
        if got is None:
            neighbors, masks, node_union, raw, uniq_n = \
                self._sample_hops(seeds, batch_seed)
        else:
            neighbors, masks, node_union, raw, uniq_n = got
        self._clock = clock + 1
        self.sample_batches += 1
        self.raw_frontier += raw
        self.uniq_frontier += uniq_n
        keys = self._bundle_keys(seeds, neighbors)
        feats = None
        if self.features is not None:
            feats = self.features.pull(keys, train=train,
                                       use_prefetch=True)
        if _pm._enabled:
            _m.GRAPH_SAMPLE_SECONDS.observe(time.perf_counter() - t0)
            _m.GRAPH_FRONTIER_NODES.labels("raw").inc(int(raw))
            _m.GRAPH_FRONTIER_NODES.labels("unique").inc(int(uniq_n))
            _m.GRAPH_DEDUP_RATIO.set(self.dedup_ratio())
        return GraphBatch(seeds, neighbors, masks, keys, feats,
                          batch_seed, clock)

    # ---------------------------------------------------------- prefetch
    def prefetch(self, next_seeds):
        """Sample batch N+1's bundle (and prefetch its feature block)
        on the background thread while the current dense step runs."""
        if self._pf_pool is None:
            return
        seeds = np.ascontiguousarray(
            np.asarray(next_seeds).reshape(-1), np.uint64).copy()
        self._retire_prefetch()
        with self._cv:
            version = self._version
        clock = self._clock           # the NEXT consume's clock
        self._pf_pending = {
            "seeds": seeds, "clock": clock, "version": version,
            "future": self._pf_pool.submit(self._pf_job, seeds, clock),
        }

    def _pf_job(self, seeds, clock):
        batch_seed = _seed_for(self.base_seed, clock)
        out = self._sample_hops(seeds, batch_seed)
        if self.features is not None:
            # hand the key block to the embedding engine's own
            # double-buffered prefetch: features overlap the dense step
            # too, and its strict-mode repair machinery owns value
            # coherence (a graph repair changes the keys, which retires
            # this feature prefetch automatically at pull time)
            self.features.prefetch(
                self._bundle_keys(seeds, out[0]))
        return out

    def _consume_prefetch(self, seeds, clock):
        pf = self._pf_pending
        if pf is None:
            return None
        if pf["clock"] != clock or pf["seeds"].size != seeds.size or \
                not np.array_equal(pf["seeds"], seeds):
            self._retire_prefetch()
            return None
        self._pf_pending = None
        out = pf["future"].result()
        if self.mode == "strict" and self._conflicts(pf["version"],
                                                     out[2]):
            # a streamed mutation touched this bundle's frontier: the
            # deterministic seed makes a full resample land exactly
            # where the sequential oracle would
            out = self._sample_hops(seeds,
                                    _seed_for(self.base_seed, clock))
            self.prefetch_repairs += 1
            if _pm._enabled:
                _m.GRAPH_PREFETCH.labels("repair").inc()
        else:
            self.prefetch_hits += 1
            if _pm._enabled:
                _m.GRAPH_PREFETCH.labels("hit").inc()
        return out

    def _retire_prefetch(self):
        """Drop an unconsumed bundle prefetch. Sampling is pure (no
        graph-side state to repair); the feature block it may have
        prefetched is retired by the embedding engine at its next
        pull/flush."""
        pf = self._pf_pending
        if pf is None:
            return
        self._pf_pending = None
        pf["future"].result()
        self.prefetch_unused += 1
        if _pm._enabled:
            _m.GRAPH_PREFETCH.labels("unused").inc()

    # ======================================================== push side
    def push_feature_grads(self, batch: GraphBatch, grads):
        """Push the SAGE step's per-position feature grads back through
        the embedding engine (dedup-merged there; strict mode applies
        synchronously — the grad-flow parity seam)."""
        if self.features is None:
            raise ValueError("engine has no feature store")
        return self.features.push(batch.keys, grads)

    # ========================================================== control
    def flush(self):
        """Barrier: drain the mutation queue, retire the bundle
        prefetch, flush the feature engine (its cache writes back and
        unpins). After flush() the adjacency holds every enqueued edge
        and no prefetched state is live."""
        with self._cv:
            upto = self._submitted_seq
        self._barrier(upto)
        self._retire_prefetch()
        if self.features is not None:
            self.features.flush()
        if self._upd_errors:
            raise self._upd_errors.pop(0)
        return self

    def close(self):
        self.flush()
        self._upd_q.put(None)
        self._upd_thread.join(timeout=10)
        if self._pf_pool is not None:
            self._pf_pool.shutdown(wait=True)
            self._pf_pool = None

    # ------------------------------------------------------------ stats
    def dedup_ratio(self):
        return 1.0 - self.uniq_frontier / self.raw_frontier \
            if self.raw_frontier else 0.0

    def state(self):
        s = {"mode": self.mode,
             "fanouts": list(self.fanouts),
             "batches": self.sample_batches,
             "dedup_ratio": round(self.dedup_ratio(), 4),
             "graph_nodes": self.graph.num_nodes(),
             "graph_edges": self.graph.num_edges(),
             "stream": {"adds": self.stream_adds,
                        "removes": self.stream_removes},
             "prefetch": {"hits": self.prefetch_hits,
                          "repairs": self.prefetch_repairs,
                          "unused": self.prefetch_unused}}
        if self.features is not None:
            s["features"] = self.features.state()
        return s
