"""paddle_tpu.ps.graph — the sharded graph engine on the embedding
substrate (reference: `fleet/heter_ps/graph_gpu_ps_table.h`,
`gpu_graph_node.h`).

Layers:

* `native.GraphTable` — the original single-process ctypes adjacency
  store (walks, node features), kept for the eager examples.
* `sharded.ShardedGraphTable` — splitmix64-hash-partitioned adjacency
  with deterministic fixed-shape neighbor sampling; co-partitions with
  a `ShardedSparseTable` via its public `partition_fn`.
* `engine.GraphEngine` — multi-hop dedup + bundle prefetch + streaming
  mutations, composed with `HeterEmbeddingEngine` feature pulls.
* `sage.SageTrainer` — the jitted GraphSAGE training lane.
"""
from .native import GraphTable  # noqa: F401
from .sharded import ShardedGraphTable  # noqa: F401
from .engine import GraphEngine, GraphBatch  # noqa: F401
from .sage import (SageTrainer, sage_encode,  # noqa: F401
                   init_sage_params, make_power_law_graph,
                   contrastive_batches)
