"""Graph-engine metrics — registered in the framework-wide PR 1
registry.

Exported names are part of the observability contract (docs/GRAPH.md,
tools/graph_smoke.py greps them, tools/metrics_dump.py greps the
CONTRACT tuple). Same hot-path discipline as `ps/heter/metrics.py`:
the engine keeps raw python counters always on and mirrors them into
the registry only when `profiler.metrics._enabled` is set.
"""
from __future__ import annotations

from ...profiler.metrics import REGISTRY, exponential_buckets

# 10us .. ~2.6s in x4 steps: a one-shard uniform sample is a numpy
# lexsort (~100us), a multi-hop frontier fans out per shard, a strict
# sample may barrier on the streaming-update queue first
_LATENCY_BUCKETS = exponential_buckets(1e-5, 4.0, 9)

GRAPH_SAMPLE_SECONDS = REGISTRY.histogram(
    "paddle_tpu_graph_sample_seconds",
    "Latency of one multi-hop sample_batch (barrier + per-hop dedup + "
    "shard fan-out + feature pull)", buckets=_LATENCY_BUCKETS)
GRAPH_FRONTIER_NODES = REGISTRY.counter(
    "paddle_tpu_graph_frontier_nodes_total",
    "Frontier nodes per hop before/after np.unique dedup",
    ("kind",))   # raw|unique
GRAPH_DEDUP_RATIO = REGISTRY.gauge(
    "paddle_tpu_graph_dedup_ratio",
    "1 - unique/raw over the engine lifetime (power-law graphs "
    "re-visit hubs, so this climbs with fanout and hop count)")
GRAPH_STREAM_UPDATES = REGISTRY.counter(
    "paddle_tpu_graph_stream_updates_total",
    "Streaming adjacency mutations applied by the background worker",
    ("op",))     # add|remove
GRAPH_PREFETCH = REGISTRY.counter(
    "paddle_tpu_graph_prefetch_total",
    "Bundle-prefetch consumption by outcome",
    ("result",))  # hit|repair|unused
GRAPH_EDGES = REGISTRY.gauge(
    "paddle_tpu_graph_edges",
    "Directed edges resident across all adjacency shards")

#: every name above, for the smoke-tool / metrics_dump contract check
CONTRACT_METRICS = (
    "paddle_tpu_graph_sample_seconds",
    "paddle_tpu_graph_frontier_nodes_total",
    "paddle_tpu_graph_dedup_ratio",
    "paddle_tpu_graph_stream_updates_total",
    "paddle_tpu_graph_prefetch_total",
    "paddle_tpu_graph_edges",
)


def dedup_ratio():
    """1 - unique/raw frontier traffic removed by per-hop dedup."""
    ch = dict(GRAPH_FRONTIER_NODES.samples())
    raw = ch.get(("raw",))
    uniq = ch.get(("unique",))
    r = raw.value if raw else 0.0
    return 1.0 - (uniq.value if uniq else 0.0) / r if r else 0.0
