"""GraphSAGE training lane over the GraphEngine.

A jitted, fixed-shape mean/max-pool SAGE stack (Hamilton et al.) whose
inputs are exactly the engine's `[B, fanout]` bundles: per-level feature
blocks plus slot masks, aggregated with the `geometric.fixed` masked
segment ops. Because every batch has the same (B, fanouts, dim) shape,
the train step — forward, unsupervised edge-contrastive loss, grads for
BOTH the dense SAGE weights and the per-position input features, SGD on
the dense weights — is ONE `instrumented_jit` instance with a hard
one-compile budget (`graph_sage_step` in `analysis/guards`).

Feature gradients leave the jit as a `[len(bundle.keys), dim]` block
and ride `engine.push_feature_grads(...)` back into the embedding
engine, which dedup-merges duplicate keys (hubs, padding slots) through
SelectedRows and applies the in-table SGD rule — the same sparse push
path the wide&deep lane uses, now fed by a graph workload.

Determinism: the trainer owns no RNG. Batches come from
`contrastive_batches` (seeded numpy), neighborhoods from the engine's
clock-seeded sampler, and the jit is pure — so a pipelined
(prefetch-on) run and a sequential oracle produce bit-identical losses
and table state in strict mode, which tests/tools assert.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...geometric import fixed as gfixed
from ...jit.functional import instrumented_jit

SAGE_STEP_NAME = "graph_sage_step"


def make_power_law_graph(num_nodes=2000, avg_degree=8, alpha=1.1,
                         seed=0, node_base=1, weighted=False):
    """Synthetic undirected power-law graph: endpoints drawn with
    p(rank r) ~ (r+1)^-alpha, self-loops dropped, both directions
    returned. Node ids are `node_base .. node_base+num_nodes-1`
    (uint64). Returns (src, dst[, weights])."""
    rng = np.random.default_rng(seed)
    n_draw = max(1, num_nodes * avg_degree // 2)
    p = (np.arange(num_nodes) + 1.0) ** -float(alpha)
    p /= p.sum()
    a = rng.choice(num_nodes, n_draw, p=p)
    b = rng.choice(num_nodes, n_draw, p=p)
    keep = a != b
    a, b = a[keep], b[keep]
    ids = np.arange(node_base, node_base + num_nodes, dtype=np.uint64)
    src = np.concatenate([ids[a], ids[b]])
    dst = np.concatenate([ids[b], ids[a]])
    if not weighted:
        return src, dst
    w_half = rng.uniform(0.1, 1.0, a.size).astype(np.float32)
    return src, dst, np.concatenate([w_half, w_half])


def contrastive_batches(src, dst, node_ids, batch_size, steps, seed=0):
    """Deterministic (center, positive, negative) triples: a positive
    is the far end of a uniformly drawn edge, a negative a uniformly
    drawn node. Both parity lanes must iterate the SAME generator
    output, so this is a seeded pure function of the INITIAL edge
    list."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(int(steps)):
        e = rng.integers(0, src.size, batch_size)
        n = rng.integers(0, node_ids.size, batch_size)
        out.append((src[e].astype(np.uint64),
                    dst[e].astype(np.uint64),
                    node_ids[n].astype(np.uint64)))
    return out


def init_sage_params(in_dim, hidden_dims, seed=0):
    """Dense SAGE weights: per layer {w_self, w_neigh, b}. Plain
    pytree (list of dicts) so it jits/greps without nn.Layer
    machinery."""
    rng = np.random.default_rng(seed)
    params = []
    d = int(in_dim)
    for h in hidden_dims:
        h = int(h)
        scale = float(np.sqrt(2.0 / (d + h)))
        params.append({
            "w_self": jnp.asarray(
                rng.normal(0, scale, (d, h)).astype(np.float32)),
            "w_neigh": jnp.asarray(
                rng.normal(0, scale, (d, h)).astype(np.float32)),
            "b": jnp.zeros((h,), jnp.float32),
        })
        d = h
    return params


def sage_encode(params, feats, masks, fanouts, aggregator="mean"):
    """feats: tuple of [N_l, d] per level (N_0 = B, N_{l+1} =
    N_l * f_l); masks: tuple of [N_l, f_l]. One SAGE layer consumes one
    level, so len(feats) == len(params) + 1 == len(fanouts) + 1.
    Returns l2-normalized embeddings [B, out_dim]."""
    agg = gfixed.mean_aggregate if aggregator == "mean" \
        else gfixed.max_aggregate
    hs = list(feats)
    for li, layer in enumerate(params):
        nxt = []
        for lvl in range(len(hs) - 1):
            n = hs[lvl].shape[0]
            f = int(fanouts[lvl])
            neigh = hs[lvl + 1].reshape(n, f, hs[lvl + 1].shape[-1])
            a = agg(neigh, masks[lvl])
            h = (hs[lvl] @ layer["w_self"] + a @ layer["w_neigh"]
                 + layer["b"])
            if li < len(params) - 1:
                h = jax.nn.relu(h)
            nxt.append(h)
        hs = nxt
    # raw (unnormalized) embeddings: under l2 normalization the
    # collapsed state (every z the same unit vector) is a fixed point
    # of the edge-contrastive loss — the away-from-negative gradient is
    # purely radial and gets normalized out
    return hs[0]


class SageTrainer:
    """End-to-end unsupervised SAGE over a GraphEngine.

    `train_step(centers, positives, negatives)` runs one contrastive
    step on the 3B-seed bundle; `prefetch(...)` pipelines the next
    triple's bundle + features behind the current dense step."""

    def __init__(self, engine, hidden_dims=(16, 8), lr=0.5,
                 aggregator="mean", param_seed=0):
        if engine.features is None:
            raise ValueError("SageTrainer needs an engine with features")
        if len(hidden_dims) != len(engine.fanouts):
            raise ValueError(
                f"hidden_dims {hidden_dims} must have one entry per "
                f"fanout {engine.fanouts}")
        if aggregator not in ("mean", "max"):
            raise ValueError(f"aggregator={aggregator!r}")
        self.engine = engine
        self.dim = engine.features.dim
        self.fanouts = engine.fanouts
        self.aggregator = aggregator
        self.lr = float(lr)
        self.params = init_sage_params(self.dim, hidden_dims,
                                       seed=param_seed)
        self.steps = 0
        self._jit_step = instrumented_jit(self._step, SAGE_STEP_NAME)

    # ------------------------------------------------------- pure step
    def _loss(self, params, feats, masks):
        z = sage_encode(params, feats, masks, self.fanouts,
                        self.aggregator)
        b = z.shape[0] // 3
        zu, zv, zn = z[:b], z[b:2 * b], z[2 * b:]
        pos = -jax.nn.log_sigmoid(jnp.sum(zu * zv, axis=-1))
        neg = -jax.nn.log_sigmoid(-jnp.sum(zu * zn, axis=-1))
        return jnp.mean(pos + neg)

    def _step(self, params, feats, masks):
        loss, (pgrads, fgrads) = jax.value_and_grad(
            self._loss, argnums=(0, 1))(params, feats, masks)
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - self.lr * g, params, pgrads)
        return new_params, loss, fgrads

    # ----------------------------------------------------- engine glue
    def _split_features(self, batch):
        sizes = batch.level_sizes()
        offs = np.cumsum([0] + sizes)
        return tuple(
            jnp.asarray(batch.features[offs[i]:offs[i + 1]])
            for i in range(len(sizes)))

    def train_step(self, centers, positives, negatives):
        seeds = np.concatenate([
            np.asarray(centers, np.uint64).reshape(-1),
            np.asarray(positives, np.uint64).reshape(-1),
            np.asarray(negatives, np.uint64).reshape(-1)])
        batch = self.engine.sample_batch(seeds, train=True)
        feats = self._split_features(batch)
        masks = tuple(jnp.asarray(m) for m in batch.masks)
        self.params, loss, fgrads = self._jit_step(
            self.params, feats, masks)
        # explicit host readbacks (the sanitize transfer guard allows
        # device_get, not implicit np coercion)
        loss, fgrads = jax.device_get((loss, fgrads))
        grad_full = np.concatenate(
            [np.asarray(g).reshape(-1, self.dim) for g in fgrads])
        self.engine.push_feature_grads(batch, grad_full)
        self.steps += 1
        return float(loss)

    def prefetch(self, centers, positives, negatives):
        self.engine.prefetch(np.concatenate([
            np.asarray(centers, np.uint64).reshape(-1),
            np.asarray(positives, np.uint64).reshape(-1),
            np.asarray(negatives, np.uint64).reshape(-1)]))

    def embed(self, nodes):
        """Inference embeddings for `nodes` (no pins, no push)."""
        batch = self.engine.sample_batch(
            np.asarray(nodes, np.uint64).reshape(-1), train=False)
        z = sage_encode(self.params, self._split_features(batch),
                        tuple(jnp.asarray(m) for m in batch.masks),
                        self.fanouts, self.aggregator)
        return np.asarray(jax.device_get(z))
