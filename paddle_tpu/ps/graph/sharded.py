"""Sharded adjacency store: one logical graph over N hash-partitioned
shards.

Parity: the HeterPS GPU graph table (`fleet/heter_ps/
graph_gpu_ps_table.h`, `gpu_graph_node.h`) — node ids are
hash-partitioned over shards exactly like the sparse feature tables, and
neighbor sampling is a batched pull that fans out per shard. Routing
reuses `ps/heter/sharded.hash_partition` (splitmix64 % num_shards), and
the constructor accepts a foreign `partition_fn` so adjacency can
co-partition with a `ShardedSparseTable`'s feature rows: one node, one
shard index, for both stores.

Sampling is **deterministic and counter-based**: the sort key for a
neighbor is `splitmix64(splitmix64(node ^ seed) + slot)` where `slot` is
the neighbor's position in the node's stored (sorted, deduped) list.
A node's sample therefore depends only on (its adjacency, the seed) —
never on batch composition, shard count, or thread interleaving — which
is what lets the engine's pipelined prefetch be bit-identical to a
sequential oracle. Uniform sampling takes the fanout largest hash keys;
weighted sampling exponentiates them Efraimidis-Spirakis style
(`u ** (1/w)`), which draws without replacement proportional to edge
weight. Padded slots carry the *center node's own id* (mask False), so a
consumer that blindly pulls features for the `[B, fanout]` block never
fabricates phantom keys in an auto-creating feature table.
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..heter.sharded import hash_partition, splitmix64
from ...profiler import metrics as _pm
from . import metrics as _m

_INV_2POW53 = 1.0 / float(1 << 53)


def _u64(x) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(x).reshape(-1), np.uint64)


def _hash_slots(nodes: np.ndarray, slots: np.ndarray,
                seed: int) -> np.ndarray:
    """Uniform (0,1) float64 per (node, slot, seed) — the counter-based
    sampling key."""
    base = splitmix64(nodes ^ np.uint64(int(seed) & 0xFFFFFFFFFFFFFFFF))
    h = splitmix64(base + slots.astype(np.uint64) + np.uint64(1))
    return (h >> np.uint64(11)).astype(np.float64) * _INV_2POW53


class _GraphShard:
    """One shard: python dict adjacency + a lazily rebuilt CSR snapshot.

    Mutations build fresh arrays (never write in place), so a CSR
    snapshot taken under the lock stays valid for lock-free sampling
    even while a later mutation swaps in new lists.
    """

    def __init__(self, weighted: bool):
        self.adj: dict = {}                  # int(node) -> sorted uint64
        self.wts = {} if weighted else None  # int(node) -> float32
        self._csr = None
        self._lock = threading.Lock()

    # ---------------------------------------------------------- mutation
    def add(self, src, dst, w):
        """-> net new directed edges. Duplicate (src, dst) keeps the
        newest weight (last-wins)."""
        delta = 0
        with self._lock:
            order = np.argsort(src, kind="stable")
            src, dst = src[order], dst[order]
            if w is not None:
                w = w[order]
            uniq, starts = np.unique(src, return_index=True)
            bounds = np.append(starts, src.size)
            for i, s in enumerate(uniq):
                node = int(s)
                new_nb = dst[bounds[i]:bounds[i + 1]]
                new_w = w[bounds[i]:bounds[i + 1]] if w is not None \
                    else None
                old_nb = self.adj.get(node)
                before = old_nb.size if old_nb is not None else 0
                if old_nb is not None:
                    new_nb = np.concatenate([old_nb, new_nb])
                    if new_w is not None:
                        new_w = np.concatenate([self.wts[node], new_w])
                # keep the LAST occurrence of a duplicated neighbor so a
                # re-added edge updates its weight
                rev = new_nb[::-1]
                merged, first = np.unique(rev, return_index=True)
                self.adj[node] = merged
                if new_w is not None:
                    self.wts[node] = np.ascontiguousarray(
                        new_w[::-1][first], np.float32)
                delta += merged.size - before
            self._csr = None
        return delta

    def remove(self, src, dst):
        """-> directed edges actually removed (missing pairs no-op)."""
        delta = 0
        with self._lock:
            order = np.argsort(src, kind="stable")
            src, dst = src[order], dst[order]
            uniq, starts = np.unique(src, return_index=True)
            bounds = np.append(starts, src.size)
            for i, s in enumerate(uniq):
                node = int(s)
                old_nb = self.adj.get(node)
                if old_nb is None:
                    continue
                keep = ~np.isin(old_nb, dst[bounds[i]:bounds[i + 1]])
                kept = old_nb[keep]
                delta += old_nb.size - kept.size
                if kept.size:
                    self.adj[node] = kept
                    if self.wts is not None:
                        self.wts[node] = self.wts[node][keep]
                else:
                    del self.adj[node]
                    if self.wts is not None:
                        del self.wts[node]
            self._csr = None
        return delta

    # ---------------------------------------------------------- snapshot
    def csr(self):
        """(nodes_sorted, indptr, flat_neighbors, flat_weights|None) —
        immutable snapshot, rebuilt lazily after mutations."""
        with self._lock:
            if self._csr is None:
                if not self.adj:
                    self._csr = (np.empty(0, np.uint64),
                                 np.zeros(1, np.int64),
                                 np.empty(0, np.uint64), None)
                else:
                    nodes = np.sort(np.fromiter(
                        self.adj.keys(), np.uint64, len(self.adj)))
                    lists = [self.adj[int(n)] for n in nodes]
                    deg = np.fromiter((a.size for a in lists), np.int64,
                                      nodes.size)
                    indptr = np.zeros(nodes.size + 1, np.int64)
                    np.cumsum(deg, out=indptr[1:])
                    flat = np.concatenate(lists) if lists else \
                        np.empty(0, np.uint64)
                    fw = None
                    if self.wts is not None:
                        fw = np.concatenate(
                            [self.wts[int(n)] for n in nodes]) \
                            if lists else np.empty(0, np.float32)
                    self._csr = (nodes, indptr, flat, fw)
            return self._csr

    def num_nodes(self):
        with self._lock:
            return len(self.adj)

    def num_edges(self):
        with self._lock:
            return sum(a.size for a in self.adj.values())


class ShardedGraphTable:
    """Hash-partitioned adjacency with batched, deterministic,
    fixed-shape neighbor sampling.

    `sample_neighbors(ids, fanout, seed)` returns `(neighbors, mask)`
    of shape `[B, fanout]` (uint64 / bool) — never ragged, so the
    consumer jit compiles once per fanout. Slots past a node's degree
    are padded with the node's own id and masked False; isolated or
    unknown nodes come back fully masked.
    """

    def __init__(self, num_shards=2, weighted=False, partition_fn=None,
                 parallel=True):
        if num_shards < 1:
            raise ValueError(f"num_shards={num_shards} must be >= 1")
        self.num_shards = int(num_shards)
        self.weighted = bool(weighted)
        self._route = partition_fn if partition_fn is not None else \
            (lambda keys: hash_partition(keys, self.num_shards))
        self.shards = [_GraphShard(self.weighted)
                       for _ in range(self.num_shards)]
        self._edges = 0
        self._edges_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=self.num_shards,
            thread_name_prefix="graph-shard") \
            if parallel and self.num_shards > 1 else None

    # ------------------------------------------------------------ routing
    def route(self, flat_keys: np.ndarray) -> np.ndarray:
        """Shard id per node key (the injected/co-partitioned fn)."""
        sid = np.asarray(self._route(_u64(flat_keys)), np.int64)
        return sid

    @property
    def partition_fn(self):
        """Mirror of `ShardedSparseTable.partition_fn` — this table's
        routing seam, exposable onward."""
        return self.route

    def _partition(self, flat_keys):
        sid = self.route(flat_keys)
        if sid.size and (sid.min() < 0 or sid.max() >= self.num_shards):
            raise ValueError("partition_fn produced shard ids outside "
                             f"[0, {self.num_shards})")
        return [np.nonzero(sid == s)[0] for s in range(self.num_shards)]

    def _fan_out(self, jobs):
        if self._pool is None:
            return [fn(*args) for fn, args in jobs]
        futs = [self._pool.submit(fn, *args) for fn, args in jobs]
        return [f.result() for f in futs]

    # ----------------------------------------------------------- mutation
    def add_edges(self, src, dst, weights=None):
        """Directed edges src -> dst (batched). For an undirected graph
        add both directions. Returns net new edge count."""
        src, dst = _u64(src), _u64(dst)
        if src.size != dst.size:
            raise ValueError("src/dst length mismatch")
        if self.weighted:
            w = np.ones(src.size, np.float32) if weights is None else \
                np.ascontiguousarray(
                    np.asarray(weights, np.float32).reshape(-1))
            if w.size != src.size:
                raise ValueError("weights length mismatch")
        else:
            w = None
        jobs = []
        for s, idx in enumerate(self._partition(src)):
            if idx.size:
                jobs.append((self.shards[s].add,
                             (src[idx], dst[idx],
                              w[idx] if w is not None else None)))
        delta = sum(self._fan_out(jobs))
        with self._edges_lock:
            self._edges += delta
            total = self._edges
        if _pm._enabled:
            _m.GRAPH_EDGES.set(total)
        return delta

    def remove_edges(self, src, dst):
        """Remove directed edges (missing pairs are ignored)."""
        src, dst = _u64(src), _u64(dst)
        if src.size != dst.size:
            raise ValueError("src/dst length mismatch")
        jobs = []
        for s, idx in enumerate(self._partition(src)):
            if idx.size:
                jobs.append((self.shards[s].remove,
                             (src[idx], dst[idx])))
        delta = sum(self._fan_out(jobs))
        with self._edges_lock:
            self._edges -= delta
            total = self._edges
        if _pm._enabled:
            _m.GRAPH_EDGES.set(total)
        return delta

    # ----------------------------------------------------------- sampling
    def sample_neighbors(self, ids, fanout: int, seed: int = 0):
        """ids: uint64 [B] -> (neighbors [B, fanout] uint64,
        mask [B, fanout] bool). Deterministic in (adjacency, seed)."""
        ids = _u64(ids)
        fanout = int(fanout)
        if fanout < 0:
            raise ValueError(f"fanout={fanout} must be >= 0")
        out = np.repeat(ids[:, None], fanout, axis=1) if fanout else \
            np.empty((ids.size, 0), np.uint64)
        mask = np.zeros((ids.size, fanout), bool)
        if not ids.size or not fanout:
            return out, mask
        jobs, targets = [], []
        for s, idx in enumerate(self._partition(ids)):
            if idx.size:
                jobs.append((self._sample_shard,
                             (self.shards[s], ids[idx], fanout, seed)))
                targets.append(idx)
        for idx, (nb, mk) in zip(targets, self._fan_out(jobs)):
            out[idx] = nb
            mask[idx] = mk
        return out, mask

    def _sample_shard(self, shard, ids, fanout, seed):
        nodes, indptr, flat, flat_w = shard.csr()
        n = ids.size
        out = np.repeat(ids[:, None], fanout, axis=1)
        mask = np.zeros((n, fanout), bool)
        pos = np.searchsorted(nodes, ids)
        found = pos < nodes.size
        found[found] = nodes[pos[found]] == ids[found]
        deg = np.zeros(n, np.int64)
        deg[found] = (indptr[pos[found] + 1] - indptr[pos[found]])
        total = int(deg.sum())
        if not total:
            return out, mask
        # flatten every queried node's full neighbor list, then keep the
        # fanout best-ranked slots per row — one vectorized pass, no
        # per-node python loop
        row = np.repeat(np.arange(n, dtype=np.int64), deg)
        starts = np.zeros(n, np.int64)
        np.cumsum(deg[:-1], out=starts[1:])
        slot = np.arange(total, dtype=np.int64) - np.repeat(starts, deg)
        edge_pos = np.repeat(
            np.where(found, indptr[np.minimum(pos, nodes.size - 1)], 0),
            deg) + slot
        neigh = flat[edge_pos]
        key = _hash_slots(np.repeat(ids, deg), slot, seed)
        if flat_w is not None:
            # Efraimidis-Spirakis: k largest u**(1/w) ~ weighted
            # sampling without replacement
            w = np.maximum(flat_w[edge_pos].astype(np.float64), 1e-30)
            key = key ** (1.0 / w)
        order = np.lexsort((-key, row))
        rank = np.arange(total, dtype=np.int64) - np.repeat(starts, deg)
        sel = rank < fanout
        rows_sel = row[order][sel]
        rank_sel = rank[sel]
        out[rows_sel, rank_sel] = neigh[order][sel]
        mask[rows_sel, rank_sel] = True
        return out, mask

    # -------------------------------------------------------------- reads
    def degree(self, ids) -> np.ndarray:
        ids = _u64(ids)
        deg = np.zeros(ids.size, np.int64)
        for s, idx in enumerate(self._partition(ids)):
            if idx.size:
                nodes, indptr, _, _ = self.shards[s].csr()
                pos = np.searchsorted(nodes, ids[idx])
                ok = pos < nodes.size
                ok[ok] = nodes[pos[ok]] == ids[idx][ok]
                d = np.zeros(idx.size, np.int64)
                d[ok] = indptr[pos[ok] + 1] - indptr[pos[ok]]
                deg[idx] = d
        return deg

    def neighbors(self, node):
        """Exact adjacency of one node: (sorted uint64 neighbors,
        float32 weights | None) — the test/oracle seam."""
        node_arr = _u64([node])
        shard = self.shards[int(self.route(node_arr)[0])]
        with shard._lock:
            nb = shard.adj.get(int(node_arr[0]))
            if nb is None:
                return (np.empty(0, np.uint64),
                        np.empty(0, np.float32) if self.weighted
                        else None)
            w = shard.wts[int(node_arr[0])].copy() \
                if shard.wts is not None else None
            return nb.copy(), w

    # -------------------------------------------------------------- state
    def num_nodes(self):
        return sum(s.num_nodes() for s in self.shards)

    def num_edges(self):
        with self._edges_lock:
            return self._edges

    def shard_sizes(self):
        """Nodes resident per shard."""
        return [s.num_nodes() for s in self.shards]
