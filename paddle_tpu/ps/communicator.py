"""Async gradient communicator.

Parity: `Communicator` (`paddle/fluid/distributed/ps/service/communicator/
communicator.h:235`) — the a_sync PS mode: trainer threads enqueue sparse
grads; background send threads MERGE grads by key (the reference's
merge_add) and push batched updates to the tables/servers, decoupling the
training loop from PS latency. flush() drains (the barrier before
save/eval).
"""
from __future__ import annotations

import queue
import threading

import numpy as np


class AsyncCommunicator:
    def __init__(self, send_queue_size=64, merge_size=4, num_threads=1):
        self._q = queue.Queue(maxsize=send_queue_size)
        self.merge_size = merge_size
        self.num_threads = num_threads
        self._threads = []
        self._running = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._errors = []

    def start(self):
        if self._running:
            return
        self._running = True
        for _ in range(self.num_threads):
            t = threading.Thread(target=self._send_loop, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self):
        self.flush()
        self._running = False
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=10)
        self._threads = []

    def push_sparse(self, table, keys: np.ndarray, grads: np.ndarray):
        """Non-blocking enqueue (blocks only when the send queue is full —
        backpressure, like the reference's bounded send queue)."""
        if not self._running:
            raise RuntimeError(
                "AsyncCommunicator is stopped; call start() before pushing")
        with self._inflight_cv:
            self._inflight += 1
        self._q.put((table, keys.copy(), grads.copy()))

    def flush(self):
        """Barrier: wait until every enqueued push has been applied.
        Raises the first send-thread error, if any (silently dropped
        grads would otherwise masquerade as a completed flush)."""
        with self._inflight_cv:
            done = self._inflight_cv.wait_for(
                lambda: self._inflight == 0 or self._errors, timeout=60)
        if self._errors:
            raise self._errors[0]
        if not done:
            raise TimeoutError("AsyncCommunicator.flush timed out")

    def _send_loop(self):
        holdover = None  # different-table item deferred to next round
        while True:
            item = holdover if holdover is not None else self._q.get()
            holdover = None
            if item is None:
                return
            batch = [item]
            # opportunistically merge up to merge_size pending requests
            # for the same table (async merge_add). NOTE: never put items
            # back into the bounded queue — this thread is its consumer
            # and a blocking put would deadlock against producers.
            stop_after = False
            while len(batch) < self.merge_size:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    stop_after = True
                    break
                if nxt[0] is not batch[0][0]:
                    holdover = nxt
                    break
                batch.append(nxt)
            try:
                table = batch[0][0]
                dim = batch[0][2].reshape(
                    -1, batch[0][2].shape[-1]).shape[-1]
                all_keys = np.concatenate(
                    [b[1].reshape(-1) for b in batch]).astype(np.uint64)
                all_grads = np.concatenate(
                    [b[2].reshape(-1, dim) for b in batch])
                # merge duplicate keys: sum grads per unique key
                uniq, inv = np.unique(all_keys, return_inverse=True)
                merged = np.zeros((uniq.size, dim), np.float32)
                np.add.at(merged, inv, all_grads)
                table.push(uniq, merged)
            except Exception as e:  # noqa: BLE001
                self._errors.append(e)
            finally:
                with self._inflight_cv:
                    self._inflight -= len(batch)
                    if self._inflight == 0 or self._errors:
                        self._inflight_cv.notify_all()
            if stop_after:
                return


class GeoCommunicator(AsyncCommunicator):
    """Geo-SGD dense mode sketch (communicator.h geo): dense deltas pushed
    every k steps. Round-1: dense tables push synchronously; the geo delta
    logic applies when dense params train locally."""

    def __init__(self, k_steps=100, **kw):
        super().__init__(**kw)
        self.k_steps = k_steps
        self._dense_shadow = {}
        self._steps = {}  # per-table step counters

    def maybe_push_dense(self, table, params: np.ndarray):
        """Push the delta vs the last synced snapshot every k steps (per
        table)."""
        tid = id(table)
        self._steps[tid] = self._steps.get(tid, 0) + 1
        if tid not in self._dense_shadow:
            self._dense_shadow[tid] = params.copy()
            return
        if self._steps[tid] % self.k_steps == 0:
            # table.push applies -lr*g with lr=1 naive rule
            delta = self._dense_shadow[tid] - params
            table.push(delta)
            self._dense_shadow[tid] = table.pull().copy()
