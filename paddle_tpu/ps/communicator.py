"""Async gradient communicator.

Parity: `Communicator` (`paddle/fluid/distributed/ps/service/communicator/
communicator.h:235`) — the a_sync PS mode: trainer threads enqueue sparse
grads; background send threads MERGE grads by key (the reference's
merge_add) and push batched updates to the tables/servers, decoupling the
training loop from PS latency. flush() drains (the barrier before
save/eval).
"""
from __future__ import annotations

import queue
import threading

import numpy as np


class AsyncCommunicator:
    def __init__(self, send_queue_size=64, merge_size=4, num_threads=1):
        self._q = queue.Queue(maxsize=send_queue_size)
        self.merge_size = merge_size
        self.num_threads = num_threads
        self._threads = []
        self._running = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._errors = []

    def start(self):
        if self._running:
            return
        self._running = True
        for _ in range(self.num_threads):
            t = threading.Thread(target=self._send_loop, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self):
        self.flush()
        self._running = False
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=10)
        self._threads = []

    def push_sparse(self, table, keys: np.ndarray, grads: np.ndarray):
        """Non-blocking enqueue (blocks only when the send queue is full —
        backpressure, like the reference's bounded send queue)."""
        if not self._running:
            raise RuntimeError(
                "AsyncCommunicator is stopped; call start() before pushing")
        with self._inflight_cv:
            self._inflight += 1
        self._q.put((table, keys.copy(), grads.copy()))

    def flush(self):
        """Barrier: wait until every enqueued push has been applied.
        Raises the first send-thread error, if any (silently dropped
        grads would otherwise masquerade as a completed flush)."""
        with self._inflight_cv:
            done = self._inflight_cv.wait_for(
                lambda: self._inflight == 0 or self._errors, timeout=60)
        if self._errors:
            raise self._errors[0]
        if not done:
            raise TimeoutError("AsyncCommunicator.flush timed out")

    def _send_loop(self):
        holdover = None  # different-table item deferred to next round
        while True:
            item = holdover if holdover is not None else self._q.get()
            holdover = None
            if item is None:
                return
            batch = [item]
            # opportunistically merge up to merge_size pending requests
            # for the same table (async merge_add). NOTE: never put items
            # back into the bounded queue — this thread is its consumer
            # and a blocking put would deadlock against producers.
            stop_after = False
            while len(batch) < self.merge_size:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    stop_after = True
                    break
                if nxt[0] is not batch[0][0]:
                    holdover = nxt
                    break
                batch.append(nxt)
            try:
                table = batch[0][0]
                dim = batch[0][2].reshape(
                    -1, batch[0][2].shape[-1]).shape[-1]
                all_keys = np.concatenate(
                    [b[1].reshape(-1) for b in batch]).astype(np.uint64)
                all_grads = np.concatenate(
                    [b[2].reshape(-1, dim) for b in batch])
                # merge duplicate keys: sum grads per unique key
                uniq, inv = np.unique(all_keys, return_inverse=True)
                merged = np.zeros((uniq.size, dim), np.float32)
                np.add.at(merged, inv, all_grads)
                table.push(uniq, merged)
            except Exception as e:  # noqa: BLE001
                self._errors.append(e)
            finally:
                with self._inflight_cv:
                    self._inflight -= len(batch)
                    if self._inflight == 0 or self._errors:
                        self._inflight_cv.notify_all()
            if stop_after:
                return


class GeoCommunicator(AsyncCommunicator):
    """Geo-async dense mode (`communicator.h:235` GeoCommunicator): each
    trainer optimizes a LOCAL copy of the dense params; every k_steps it
    sends only the delta vs its last synced snapshot, the server MERGES
    deltas additively (so concurrent trainers' progress composes instead
    of overwriting), and the trainer rebases onto the merged params.

    `table` is anything exposing add(delta) -> None + pull() -> params —
    a local MemoryDenseTable — or a (PSClient, table_id) pair for the
    remote path, which merges and pulls in one DENSE_ADD round trip.
    """

    def __init__(self, k_steps=100, **kw):
        super().__init__(**kw)
        self.k_steps = k_steps
        self._base = {}   # tid -> snapshot at last sync
        self._steps = {}  # per-table step counters

    @staticmethod
    def _tid(table):
        return (id(table[0]), table[1]) if isinstance(table, tuple) \
            else id(table)

    @staticmethod
    def _pull(table):
        if isinstance(table, tuple):
            client, table_id = table
            return client.pull_dense(table_id)
        return table.pull()

    @staticmethod
    def _add(table, delta):
        if isinstance(table, tuple):
            client, table_id = table
            return client.push_dense_delta(table_id, delta)
        table.add(delta)
        return table.pull()

    def register_dense(self, table, params: np.ndarray, is_chief=True):
        """Start geo tracking. The chief seeds the server with its params
        (as a delta vs whatever is there); non-chief trainers adopt the
        server's. Returns the params the trainer should train from."""
        if is_chief:
            merged = self._add(table, params - self._pull(table))
        else:
            merged = self._pull(table)
        self._base[self._tid(table)] = merged.copy()
        return merged.copy()

    def maybe_sync_dense(self, table, params: np.ndarray):
        """Called each local step with the trainer's CURRENT local params.
        Every k_steps: push delta, rebase onto the merged result.
        Returns the params the trainer should continue from."""
        tid = self._tid(table)
        if tid not in self._base:
            # implicit registration ADOPTS the server's params: only an
            # explicit register_dense(..., is_chief=True) may seed, else a
            # late-joining trainer would wipe the merged progress
            return self.register_dense(table, params, is_chief=False)
        self._steps[tid] = self._steps.get(tid, 0) + 1
        if self._steps[tid] % self.k_steps != 0:
            return params
        merged = self._add(table, params - self._base[tid])
        self._base[tid] = merged.copy()
        return merged.copy()


class PullDenseWorker:
    """Background dense-parameter refresher.

    Parity: `paddle/fluid/framework/pull_dense_worker.cc:1` — in async
    PS training the dense params drift on the servers while trainers
    compute; a background thread re-pulls them on an interval (or after
    every `pull_every` trainer steps) so the training threads never
    block on a dense pull in their cycle. The freshest copy is handed
    out via `get()` (lock-free swap of an immutable array)."""

    def __init__(self, pull_fn, interval_s=0.05, pull_every=0):
        self._pull_fn = pull_fn
        self._interval = float(interval_s)
        self._pull_every = int(pull_every)
        self._latest = None
        self._version = 0
        self._steps = 0
        self._cv = threading.Condition()
        self._running = False
        self._thread = None
        self._errors = []

    def start(self):
        if self._running:
            return self
        self._latest = np.asarray(self._pull_fn())
        self._version = 1
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while self._running:
            with self._cv:
                self._cv.wait(timeout=self._interval)
                if not self._running:
                    return
                if self._pull_every and self._steps < self._pull_every:
                    continue
                self._steps = 0
            try:
                fresh = np.asarray(self._pull_fn())
            except Exception as e:  # noqa: BLE001 — surface on get()
                self._errors.append(e)
                continue
            self._latest = fresh            # atomic ref swap
            self._version += 1

    def increase_thread_version(self):
        """Trainer-step tick (pull_dense_worker IncreaseThreadVersion):
        with pull_every>0 the refresh fires once that many ticks
        accumulate instead of on the wall-clock interval."""
        with self._cv:
            self._steps += 1
            if self._pull_every and self._steps >= self._pull_every:
                self._cv.notify()

    def get(self):
        """Freshest dense params (never blocks on the network)."""
        if self._errors:
            raise self._errors.pop(0)
        return self._latest

    @property
    def version(self):
        return self._version

    def stop(self):
        self._running = False
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
