"""PS runtime facade.

Parity: `TheOnePSRuntime` (`python/paddle/distributed/ps/the_one_ps.py:921`
— `_init_worker:1044`, `_init_server:1202`) and the brpc client/server
pair (`BrpcPsClient`/`BrpcPsServer`).

Round-1 scope: the in-process local PS (the reference's `ps_local_client.h`
capability, used by its own single-process tests and HeterPS): tables live
in this process's native engine; init_server/init_worker manage the table
registry and persistence. The multi-host RPC transport (gRPC/TCP) is the
next native milestone — the table/accessor engine below it is already the
real one.
"""
from __future__ import annotations

import os

from .table import MemorySparseTable, MemoryDenseTable


class PSRuntime:
    def __init__(self):
        self._tables = {}
        self._running = False

    # ---- table registry (the_one_ps table config parity) ----
    def create_sparse_table(self, table_id, dim=8, sgd_rule="adagrad",
                            learning_rate=0.05, initial_range=0.02):
        if table_id not in self._tables:
            self._tables[table_id] = MemorySparseTable(
                dim, sgd_rule, learning_rate, initial_range)
        return self._tables[table_id]

    def create_dense_table(self, table_id, size, sgd_rule="adam",
                           learning_rate=0.01):
        if table_id not in self._tables:
            self._tables[table_id] = MemoryDenseTable(size, sgd_rule,
                                                      learning_rate)
        return self._tables[table_id]

    def get_table(self, table_id):
        return self._tables[table_id]

    # ---- lifecycle ----
    def init_server(self, *a, **k):
        self._running = True

    def run_server(self):
        self._running = True

    def init_worker(self, *a, **k):
        pass

    def stop_worker(self):
        self._running = False

    def save_persistables(self, dirname):
        import numpy as np
        os.makedirs(dirname, exist_ok=True)
        for tid, table in self._tables.items():
            if isinstance(table, MemorySparseTable):
                table.save(os.path.join(dirname, f"sparse_{tid}.bin"))
            elif isinstance(table, MemoryDenseTable):
                np.save(os.path.join(dirname, f"dense_{tid}.npy"),
                        table.pull())

    def load_persistables(self, dirname):
        import numpy as np
        for tid, table in self._tables.items():
            if isinstance(table, MemorySparseTable):
                path = os.path.join(dirname, f"sparse_{tid}.bin")
                if os.path.exists(path):
                    table.load(path)
            elif isinstance(table, MemoryDenseTable):
                path = os.path.join(dirname, f"dense_{tid}.npy")
                if os.path.exists(path):
                    table.set(np.load(path))


_runtime = None


def get_ps_runtime() -> PSRuntime:
    global _runtime
    if _runtime is None:
        _runtime = PSRuntime()
    return _runtime
