"""paddle.hub — local + remote sources (`python/paddle/hapi/hub.py:1`).

Remote protocol parity: `github`/`gitee` sources resolve
`owner/repo[:branch]` to an archive zip URL, download into
`~/.cache/paddle/hub` (once, unless force_reload), unzip, and load the
repo's `hubconf.py`. The download path is urllib-based and exercised in
tests through `file://` archive URLs; real github fetches additionally
need network egress (this image has none — the error is raised at
download time by urllib, not pre-emptively by us).
"""
from __future__ import annotations

import importlib.util
import os
import shutil
import zipfile

HUB_DIR = os.path.expanduser(
    os.environ.get("PADDLE_TPU_HUB_DIR", "~/.cache/paddle/hub"))


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _parse_repo(repo):
    """'owner/name[:branch]' -> (owner, name, branch)."""
    branch = "main"
    if ":" in repo:
        repo, branch = repo.split(":", 1)
    if repo.count("/") != 1:
        raise ValueError(
            f"remote repo must be 'owner/name[:branch]', got {repo!r}")
    owner, name = repo.split("/")
    return owner, name, branch


def _archive_url(repo, source):
    if source.startswith(("http://", "https://", "file://")):
        return repo, source  # direct archive URL (also the test path)
    owner, name, branch = _parse_repo(repo)
    if source == "github":
        return (f"{owner}_{name}_{branch}",
                f"https://github.com/{owner}/{name}/archive/{branch}.zip")
    if source == "gitee":
        return (f"{owner}_{name}_{branch}",
                f"https://gitee.com/{owner}/{name}/repository/archive/"
                f"{branch}.zip")
    raise ValueError(f"unknown hub source {source!r} "
                     "(expected 'github', 'gitee' or 'local')")


def _fetch_repo(repo, source, force_reload):
    """Download + unzip into the hub cache; returns the repo dir."""
    import urllib.request
    if source.startswith(("http://", "https://", "file://")):
        cache_key = os.path.basename(source).replace(".zip", "")
        url = source
    else:
        cache_key, url = _archive_url(repo, source)
    hub_dir = os.path.expanduser(
        os.environ.get("PADDLE_TPU_HUB_DIR", "~/.cache/paddle/hub"))
    dest = os.path.join(hub_dir, cache_key)
    if os.path.isdir(dest) and not force_reload:
        return dest
    os.makedirs(hub_dir, exist_ok=True)
    zpath = dest + ".zip"
    with urllib.request.urlopen(url) as r, open(zpath, "wb") as f:
        shutil.copyfileobj(r, f)
    if os.path.isdir(dest):
        shutil.rmtree(dest)
    tmp = dest + ".extract"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    with zipfile.ZipFile(zpath) as z:
        # refuse entries escaping the extraction root (zip-slip)
        for n in z.namelist():
            p = os.path.normpath(n)
            if p.startswith("..") or os.path.isabs(p):
                raise ValueError(f"unsafe archive member {n!r}")
        z.extractall(tmp)
    os.unlink(zpath)
    # archives contain a single top-level '<name>-<branch>/' dir
    entries = [e for e in os.listdir(tmp) if not e.startswith(".")]
    src_dir = os.path.join(tmp, entries[0]) if len(entries) == 1 and \
        os.path.isdir(os.path.join(tmp, entries[0])) else tmp
    shutil.move(src_dir, dest)
    if os.path.isdir(tmp):
        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def _repo_dir(repo_dir, source, force_reload):
    if source == "local":
        return repo_dir
    return _fetch_repo(repo_dir, source, force_reload)


def list(repo_dir, source="local", force_reload=False):
    mod = _load_hubconf(_repo_dir(repo_dir, source, force_reload))
    return [n for n in dir(mod) if callable(getattr(mod, n))
            and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    d = _repo_dir(repo_dir, source, force_reload)
    return getattr(_load_hubconf(d), model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    d = _repo_dir(repo_dir, source, force_reload)
    return getattr(_load_hubconf(d), model)(**kwargs)
