"""Build hook: compile the native PS engine (libps_core.so) at install.

The C-ABI library (no pybind dependency — loaded via ctypes) is the one
native component; everything device-side is jax/XLA. `_native.py` also
rebuilds it on import when the source is newer, so editable installs
never ship a stale binary.
"""
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        super().run()
        import os
        src = os.path.join("paddle_tpu", "ps", "csrc", "ps_core.cpp")
        for root in (self.build_lib, "."):
            out_dir = os.path.join(root, "paddle_tpu", "ps", "csrc")
            if not os.path.isdir(out_dir):
                continue
            out = os.path.join(out_dir, "libps_core.so")
            cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", src,
                   "-o", out, "-lpthread"]
            print("building native ps_core:", " ".join(cmd))
            subprocess.run(cmd, check=True)


setup(cmdclass={"build_py": BuildWithNative})
