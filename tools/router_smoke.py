"""Multi-replica router smoke run + metric-contract check.

CI contract (tests/test_router.py runs this in-process, the same way
tests/test_serving.py runs tools/serving_smoke.py):

* **Affinity phase** — a shared-prefix Poisson workload (two prompt
  "families" sharing 24-token heads) streams through a 2-replica
  `ReplicaRouter` with prefix-affinity dispatch. Outputs must be
  token-identical to a solo engine serving the same prompts, and the
  prefix caches must save AT LEAST 30% more prefill tokens than the
  same workload under round-robin dispatch (the acceptance bar of
  ISSUE 8: affinity concentrates a family on one replica, so each
  head misses once TOTAL instead of once per replica).
* **Round-robin phase** — the baseline: identical workload, fresh
  replicas, `policy="round_robin"`.
* **Failover phase** — mid-workload, one replica's engine is made to
  crash (its mixed step raises); its step loop dies, the router marks
  it down, and every in-flight request of the dead replica must
  complete on the surviving replica with outputs STILL identical to
  the solo engine (prompts are re-prefillable, greedy is
  deterministic). The surviving engine must come out clean: no
  resident slots, zero leaked KV blocks once its prefix cache drains.
* **Metric contract** — every router metric name in
  `serving.metrics.CONTRACT_METRICS` must appear in the Prometheus
  dump, with real activity on requests/affinity/failover counters.

Exit status is non-zero on any violation.

Usage: JAX_PLATFORMS=cpu python tools/router_smoke.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_REQUESTS = 8
HEAD_TOKENS = 24
MAX_NEW = 6

# family per arrival, deliberately NOT alternating: under round-robin
# dispatch (replica = arrival index % 2) each family hits BOTH
# replicas, so each of the 2 heads misses twice (4 head prefills);
# affinity concentrates each family on one replica (2 head prefills) —
# expected saved tokens 6*24 vs 4*24, a 50% margin over the 30% bar
_FAMILIES = (0, 0, 1, 0, 1, 1, 0, 1)


def _workload(vocab=193):
    """Deterministic shared-prefix Poisson workload: two prompt
    families, arrival gaps floored so a head's first prefill lands in
    its replica's cache before the next family member arrives (the
    analysis the 30%-more-saved contract is computed against)."""
    import random

    import numpy as np
    rng = np.random.RandomState(11)
    heads = [rng.randint(1, vocab, HEAD_TOKENS).tolist()
             for _ in range(2)]
    gaps = random.Random(5)
    t, events = 0.0, []
    for i in range(N_REQUESTS):
        t += 0.02 + min(gaps.expovariate(25.0), 0.2)
        events.append((t, f"tenant{i % 3}",
                       heads[_FAMILIES[i]]
                       + rng.randint(1, vocab, 4).tolist()))
    return events


def _replicas(model, n=2):
    """Fresh replicas, mixed steps pre-compiled: the Poisson schedule
    assumes millisecond steps, and an in-workload ~1s first-step
    compile would pile every early arrival into one cold cache."""
    from paddle_tpu.serving.engine import ServingEngine
    from paddle_tpu.serving.frontend import ServingFrontend
    fes = []
    for _ in range(n):
        eng = ServingEngine(model, max_slots=3, block_size=4,
                            max_seq_len=64, cache_dtype="float32",
                            seed=0, prefix_caching=True)
        eng.generate_batch([[7, 7]], max_new_tokens=1)   # warm compile
        fes.append(ServingFrontend(eng, max_pending=16))
    return fes


def _run_router(router, events):
    import asyncio

    async def fire(ev, t0):
        t, tenant, prompt = ev
        delay = t - (asyncio.get_event_loop().time() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        return await router.submit(prompt, max_new_tokens=MAX_NEW,
                                   tenant=tenant)

    async def run():
        async with router:
            t0 = asyncio.get_event_loop().time()
            return await asyncio.gather(
                *[fire(ev, t0) for ev in events])

    return asyncio.run(run())


def run_smoke():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForGeneration
    from paddle_tpu.profiler import metrics as pm
    from paddle_tpu.serving import metrics as sm
    from paddle_tpu.serving.distributed import ReplicaRouter
    from paddle_tpu.serving.engine import ServingEngine

    pm.enable()
    paddle.seed(1234)
    model = GPTForGeneration(vocab_size=193, hidden_size=32,
                             num_layers=2, num_attention_heads=4,
                             max_position_embeddings=128,
                             compute_dtype="float32")
    model.eval()
    events = _workload()
    prompts = [e[2] for e in events]
    failures = []

    # solo oracle: one engine, same greedy math — the parity baseline
    solo = ServingEngine(model, max_slots=4, block_size=4,
                         max_seq_len=64, cache_dtype="float32", seed=0)
    oracle = solo.generate_batch(prompts, max_new_tokens=MAX_NEW)
    baseline_prefill = sum(len(p) for p in prompts)

    # ---- affinity phase ----
    fes = _replicas(model)
    p0 = sm.SERVING_TOKENS.labels("prefill").value  # after warm-up
    router = ReplicaRouter(fes)
    outs = _run_router(router, events)
    prefilled_aff = sm.SERVING_TOKENS.labels("prefill").value - p0
    if outs != oracle:
        failures.append("affinity-routed outputs diverge from the solo "
                        "engine")
    if router.affinity_hits <= 0:
        failures.append("no affinity hits on a shared-prefix workload")
    aff_stats = router.stats()

    # ---- round-robin baseline ----
    rr_fes = _replicas(model)
    p1 = sm.SERVING_TOKENS.labels("prefill").value  # after warm-up
    rr = ReplicaRouter(rr_fes, policy="round_robin")
    rr_outs = _run_router(rr, events)
    prefilled_rr = sm.SERVING_TOKENS.labels("prefill").value - p1
    if rr_outs != oracle:
        failures.append("round-robin outputs diverge from the solo "
                        "engine")
    saved_aff = baseline_prefill - prefilled_aff
    saved_rr = baseline_prefill - prefilled_rr
    if saved_aff < 1.3 * max(saved_rr, 1):
        failures.append(
            f"affinity saved {saved_aff} prefill tokens vs {saved_rr} "
            "for round-robin — need >= 30% more")

    # ---- failover phase: crash a replica mid-workload ----
    import asyncio

    async def run_kill():
        fes = _replicas(model)
        router = ReplicaRouter(fes, probe_interval=0.02)
        async with router:
            tasks = [asyncio.ensure_future(
                router.submit(p, max_new_tokens=32))
                for p in prompts[:6]]
            await asyncio.sleep(0.05)     # requests mid-generation
            victim = max(range(2), key=router.queue_depth)

            def boom():
                raise RuntimeError("injected replica crash")
            fes[victim].engine.step = boom      # next step kills the loop
            outs = await asyncio.gather(*tasks)
        return outs, router, fes, victim

    f0 = sm.ROUTER_FAILOVERS.value
    kill_outs, krouter, kfes, victim = asyncio.run(run_kill())
    survivor = kfes[1 - victim].engine
    koracle = solo.generate_batch(prompts[:6], max_new_tokens=32)
    if kill_outs != koracle:
        failures.append("failover outputs diverge from the solo engine "
                        "(re-submission must be lossless)")
    if krouter.failovers < 1:
        failures.append("forced replica kill produced no failovers")
    if sm.ROUTER_FAILOVERS.value - f0 < 1:
        failures.append("failover counter not recorded in the registry")
    if survivor.scheduler.num_active or survivor.scheduler.queue:
        failures.append("surviving engine not drained after failover")
    survivor.prefix_cache.evict_all()
    if survivor.kv.blocks_in_use != 0:
        failures.append(f"{survivor.kv.blocks_in_use} KV blocks leaked "
                        "on the surviving replica")

    stats = {"prefilled_aff": int(prefilled_aff),
             "prefilled_rr": int(prefilled_rr),
             "saved_aff": int(saved_aff), "saved_rr": int(saved_rr),
             "affinity_hits": aff_stats["affinity_hits"],
             "dispatches": aff_stats["dispatches"],
             "failovers": krouter.failovers, "victim": victim}
    return stats, failures


def main():
    from paddle_tpu.profiler import metrics as pm
    from paddle_tpu.serving.metrics import CONTRACT_METRICS

    # runtime sanitizers (ISSUE 12): transfer guard + compile watchdog
    from paddle_tpu.analysis import guards
    with guards.sanitize() as wd:
        stats, failures = run_smoke()
    failures += [f"compile watchdog: {v}" for v in wd.violations]
    text = pm.REGISTRY.to_prometheus()
    print(text)
    for name in CONTRACT_METRICS:
        if name not in text:
            failures.append(f"MISSING serving metric: {name}")
    from paddle_tpu.serving import metrics as sm
    outcomes = {lv[1] for lv, _c in sm.ROUTER_REQUESTS.samples()}
    for outcome in ("finished", "failover"):
        if outcome not in outcomes:
            failures.append(
                f"router_requests_total recorded no {outcome!r} "
                f"dispatches (saw {sorted(outcomes)})")
    if sm.ROUTER_AFFINITY_HITS.value <= 0:
        failures.append("router_affinity_hits_total recorded nothing")
    if failures:
        for f in failures:
            print(f"SMOKE FAILURE: {f}", file=sys.stderr)
        return 1
    print(f"router smoke OK: {stats['dispatches']} dispatches, "
          f"{stats['affinity_hits']} affinity hits; prefilled "
          f"{stats['prefilled_aff']} tokens vs {stats['prefilled_rr']} "
          f"round-robin (saved {stats['saved_aff']} vs "
          f"{stats['saved_rr']}); {stats['failovers']} failover(s) "
          f"after killing replica {stats['victim']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
