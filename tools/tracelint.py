"""Trace-discipline lint CLI (ISSUE 12) — the static-analysis gate.

Runs the `paddle_tpu.analysis` tracelint + recompile-hazard passes
over the shipped package and reconciles against the allowlist
(tools/tracelint_allowlist.json). CI contract
(tests/test_static_analysis.py, tier-1): `--check` exits 0 on the
shipped tree; any NEW finding — a host call in a traced function, a
list-typed static arg, a trailing-None jit-boundary spec, ... — exits
1. Rule catalog + allowlist semantics: docs/ANALYSIS.md.

Usage:
  python tools/tracelint.py --check            # CI gate
  python tools/tracelint.py                    # full report
  python tools/tracelint.py --json             # machine-readable
  python tools/tracelint.py --root DIR         # lint another tree
  PADDLE_TPU_TRACELINT=0                       # skip the tier-1 gate
"""
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DEFAULT_ROOT = os.path.join(_REPO, "paddle_tpu")
DEFAULT_ALLOWLIST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "tracelint_allowlist.json")


def main(argv=None):
    from paddle_tpu.analysis import tracelint
    return tracelint.main(argv, root=DEFAULT_ROOT,
                          allowlist_path=DEFAULT_ALLOWLIST)


if __name__ == "__main__":
    sys.exit(main())
