"""Real-TPU tile validation for the Pallas kernel families (ISSUE 15
satellite, ROADMAP follow-on).

Tier-1 proves every Pallas kernel in INTERPRET mode on the CPU mesh —
the real scalar-prefetch/block-table plumbing, but not the real Mosaic
tiling. Device tiles therefore stay CI-unproven until someone runs the
kernels on actual hardware. This tool is that run: it replays the
paged-attention family (ragged / verify / decode / sparse short-table,
fp32 + bf16 + int8 + fp8 pools), the hand flash-forward kernel and the
grouped-expert matmul (fp32 / int8 / int4 weights) against their
pure-XLA oracles on the REAL backend — interpret mode OFF, shapes
chosen to satisfy the hardware alignment gate
(`autotune.paged_alignment_ok`: head_dim % 128, block_size % 8).

Off-TPU the tool exits 0 with a SKIP line (tests wire it in
slow-marked; a CPU CI run must stay green without pretending to have
validated anything). On TPU, any parity failure exits non-zero with
the offending (kernel, dtype, shape) cell.

Usage:
    python tools/tpu_tile_validate.py            # on a TPU host
    JAX_PLATFORMS=cpu python tools/tpu_tile_validate.py   # clean skip
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _allclose(out, ref, rtol, atol):
    import numpy as np
    out = np.asarray(out, np.float64)
    ref = np.asarray(ref, np.float64)
    return out.shape == ref.shape and np.allclose(out, ref, rtol=rtol,
                                                  atol=atol)


def validate_paged(failures):
    """Every paged entry x pool dtype on hardware-aligned shapes."""
    import numpy as np

    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.ops.pallas import paged_attention as pa

    N, H, Dh, BS = 4, 2, 128, 16
    for dtype in ("float32", "bfloat16", "int8", "float8_e4m3fn"):
        rtol = 2e-2 if dtype != "bfloat16" else 5e-2
        for kernel, G in (("paged_ragged", 1), ("paged_verify", 3),
                          ("paged_decode", 1)):
            q, kp, vp, bt, slots, pos, ks, vs = pa._synth_paged_inputs(
                N, G, H, Dh, BS, 4 * BS, np.dtype(dtype), seed=3)
            if kernel == "paged_decode":
                out = pa.decode_attend(q[:, 0], kp, vp, bt,
                                       pos[:, 0] + 1, ks, vs)
                ref = fa.ragged_gather_reference(
                    q[:, 0], kp, vp, bt, slots, pos[:, 0], ks, vs)
            elif G == 1:
                out = pa.ragged_attend(q[:, 0], kp, vp, bt, slots,
                                       pos[:, 0], ks, vs)
                ref = fa.ragged_gather_reference(
                    q[:, 0], kp, vp, bt, slots, pos[:, 0], ks, vs)
            else:
                out = pa.verify_attend(q, kp, vp, bt, slots, pos,
                                       ks, vs)
                ref = fa.verify_gather_reference(q, kp, vp, bt, slots,
                                                 pos, ks, vs)
            if not _allclose(out, ref, rtol, rtol):
                failures.append(f"paged: {kernel} x {dtype} "
                                f"(H={H}, Dh={Dh}, BS={BS})")
        # sparse short-table entry: same kernel, B-wide tables
        B = 3
        q, kp, vp, bt, slots, pos, ks, vs = pa._synth_paged_inputs(
            N, 1, H, Dh, BS, B * BS, np.dtype(dtype), seed=5)
        out = pa.ragged_attend(q[:, 0], kp, vp, bt, slots, pos[:, 0],
                               ks, vs, kernel_name="paged_sparse")
        ref = fa.ragged_gather_reference(q[:, 0], kp, vp, bt, slots,
                                         pos[:, 0], ks, vs)
        if not _allclose(out, ref, rtol, rtol):
            failures.append(f"paged: paged_sparse x {dtype} (B={B})")


def validate_flash(failures):
    """Hand flash-forward kernel at lane-aligned shapes."""
    import numpy as np

    from paddle_tpu.ops.pallas import flash_attention as fa

    rng = np.random.RandomState(11)
    for s, d, dtype in ((256, 128, "float32"), (512, 128, "bfloat16")):
        shape = (3, s, d)
        q = rng.randn(*shape).astype(np.float32)
        k = rng.randn(*shape).astype(np.float32)
        v = rng.randn(*shape).astype(np.float32)
        import jax.numpy as jnp
        qj, kj, vj = (jnp.asarray(a).astype(dtype) for a in (q, k, v))
        scale = 1.0 / np.sqrt(d)
        out = fa._flash_fwd(qj, kj, vj, scale, True, 128, 128)
        ref = fa._xla_reference(qj, kj, vj, scale, True)
        if not _allclose(out, ref, 3e-2, 3e-2):
            failures.append(f"flash_fwd: S={s} D={d} {dtype}")


def validate_grouped_matmul(failures):
    """Grouped-expert matmul: fp32 + int8/int4 weight-only dequant."""
    import numpy as np

    from paddle_tpu.ops.pallas import grouped_matmul as gmm

    rng = np.random.RandomState(23)
    E, C, D, F = 4, 128, 128, 256
    x = rng.randn(E, C, D).astype(np.float32)
    w = rng.randn(E, D, F).astype(np.float32)
    import jax.numpy as jnp
    xj, wj = jnp.asarray(x), jnp.asarray(w)
    out = gmm.grouped_expert_matmul(xj, wj)
    ref = gmm.grouped_matmul_oracle(xj, wj)
    if not _allclose(out, ref, 2e-2, 2e-2):
        failures.append("grouped_matmul: float32")
    # int8 weight-only (per-out-channel amax, qmax=127 convention)
    s8 = jnp.maximum(jnp.max(jnp.abs(wj), axis=-2), 1e-9)
    q8 = jnp.clip(jnp.round(wj / s8[:, None, :] * 127.0), -127,
                  127).astype(jnp.int8)
    out = gmm.grouped_expert_matmul(xj, q8, s8.astype(jnp.float32))
    ref = gmm.grouped_matmul_oracle(xj, q8, s8.astype(jnp.float32))
    if not _allclose(out, ref, 5e-2, 5e-2):
        failures.append("grouped_matmul: int8")
    # int4 nibble-packed (quantize_int4_experts' layout + fp16 scales)
    q4, s4 = gmm.quantize_int4_experts(wj)
    out = gmm.grouped_expert_matmul(xj, q4, s4)
    ref = gmm.grouped_matmul_oracle(xj, q4, s4)
    if not _allclose(out, ref, 5e-2, 5e-2):
        failures.append("grouped_matmul: int4")


def main():
    import jax
    platform = jax.devices()[0].platform
    if platform != "tpu":
        print(f"tpu_tile_validate: SKIP — backend is {platform!r}, "
              "not tpu (interpret-mode parity is tier-1's job; this "
              "tool exists to prove the REAL device tiles)",
              file=sys.stderr)
        return 0
    failures = []
    validate_paged(failures)
    validate_flash(failures)
    validate_grouped_matmul(failures)
    if failures:
        for f in failures:
            print(f"TPU TILE FAILURE: {f}", file=sys.stderr)
        return 1
    print("tpu tile validation OK: paged (4 dtypes x 4 entries), "
          "flash fwd, grouped matmul (fp32/int8/int4) all match "
          "their XLA oracles on the real device tiles",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
