"""Multi-tenant LoRA serving smoke run + CI contract (ISSUE 14).

Phase 1 — K=4 adapters through ONE engine over a Poisson multi-tenant
stream, with only TWO usable adapter slots (`max_adapters=3`: slot 0
is the reserved null adapter), so the run MUST churn the slot cache
(evictions + cold reloads mid-stream). Contracts:

1. **Null parity** — requests with `adapter_id=None` are
   token-identical to an engine built with no adapter support at all
   (the slot-0 zero-delta guarantee).
2. **Tenant parity** — every tenant's outputs are token-identical to
   a SOLO engine holding only that tenant's adapter (per-slot
   independence of the mixed step; the slot index an adapter happens
   to occupy never matters).
3. **One compile** — the mixed step compiles exactly once across all
   the adapter loads/evictions/reloads, and the slot-write load
   executable (`serving_adapter_load`) compiles exactly once too.
4. **No leaks** — after drain: zero adapter pins, zero KV blocks
   allocated, allocator ledger invariant intact.

Phase 2 — int4 weight-only MoE experts (the second ISSUE 14 barrel):
a MoE engine with `moe_weight_dtype="int4"` against the fp engine on
a model whose expert weights sit exactly on the int4 grid — the
engine-side pack/dequant round trip must then be LOSSLESS, so the
agreement contract (>= 0.99) actually asserts exactness of the whole
packed-serving path (generic-weight kernel accuracy is covered by the
tolerance-gated parity cells in tests/test_kernel_autotune.py).
Capacity: expert-weight bytes must shrink >= 1.9x vs bf16 — analytic
(`grouped_matmul.expert_weight_bytes`) AND measured on the engine's
actual device arrays (which verifies the nibble packing really
halves storage; the same dual check tools/kv_smoke.py applies to KV).

Both phases run with metrics on under `guards.sanitize` (transfer
guard + compile watchdog), and every serving contract metric —
including the new `paddle_tpu_serving_adapter_*` family — must appear
in the Prometheus dump. Exit status is non-zero on any violation.

Usage: JAX_PLATFORMS=cpu python tools/lora_smoke.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

TENANTS = ("t1", "t2", "t3", "t4")


def _model(moe=False, seed=0, snap_bits=0):
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForGeneration
    paddle.seed(seed)
    kw = {}
    if moe:
        kw["moe"] = dict(num_expert=4, top_k=2, capacity_factor=2.0)
    model = GPTForGeneration(vocab_size=211, hidden_size=32,
                             num_layers=2, num_attention_heads=4,
                             max_position_embeddings=128,
                             compute_dtype="float32", **kw)
    model.eval()
    if snap_bits:
        # snap expert weights onto the exact int`snap_bits` grid so
        # engine-side quantization is a lossless round trip — the
        # agreement contract then proves end-to-end exactness of the
        # packed path, not luck with quantization noise
        qmax = float(2 ** (snap_bits - 1) - 1)
        for attr in ("ffn1_weights", "ffn2_weights"):
            w = getattr(model.decoder, attr)._data.astype(jnp.float32)
            scale = jnp.maximum(jnp.max(jnp.abs(w), axis=-2), 1e-9)
            q = jnp.clip(jnp.round(w / scale[:, :, None, :] * qmax),
                         -qmax, qmax)
            getattr(model.decoder, attr)._data = \
                q * (scale[:, :, None, :] / qmax)
    return model


def run_lora_phase(failures):
    import numpy as np

    from paddle_tpu.profiler import metrics as pm
    from paddle_tpu.serving.adapters import make_random_adapter
    from paddle_tpu.serving.engine import ServingEngine, STEP_FN_NAME

    model = _model()
    adapters = {t: make_random_adapter(model.decoder, 4, seed=i + 1,
                                       scale=0.3)
                for i, t in enumerate(TENANTS)}
    rng = np.random.RandomState(11)
    # Poisson multi-tenant stream, arrivals Poisson per engine step.
    # The tenant mix is SKEWED (real multi-tenant traffic is): t1/t2
    # dominate and stay slot-resident (cache hits), t3/t4 arrive
    # rarely and force evict-reload churn; every 6th request is the
    # base model (None) riding the null slot
    n_req = 24
    hot = ("t1", "t2", "t1", "t2", "t1", "t2", "t1", "t2", "t3",
           "t1", "t2", "t4")
    req_tenants = [(None if i % 6 == 0 else hot[i % len(hot)])
                   for i in range(n_req)]
    prompts = [rng.randint(1, 211, int(n)).tolist()
               for n in rng.randint(3, 20, n_req)]
    arrivals = iter(rng.poisson(2.0, n_req * 4))

    def engine(max_adapters=0):
        return ServingEngine(model, max_slots=4, block_size=4,
                             max_seq_len=64, cache_dtype="float32",
                             seed=0, max_adapters=max_adapters,
                             lora_rank=4)

    c0 = pm.JIT_COMPILES.labels(STEP_FN_NAME).value
    multi = engine(max_adapters=3)       # slots: null + 2 usable for
    for t in TENANTS:                    # 4 tenants -> forced churn
        multi.register_adapter(t, adapters[t])
    reqs, next_i = [], 0
    while next_i < n_req or multi.scheduler.has_work:
        k = next(arrivals) if next_i < n_req else 0
        for _ in range(min(k, n_req - next_i)):
            reqs.append(multi.submit(prompts[next_i], 6,
                                     adapter_id=req_tenants[next_i]))
            next_i += 1
        if multi.scheduler.has_work:
            multi.step()
    outs = [list(r.output) for r in reqs]
    compiles = pm.JIT_COMPILES.labels(STEP_FN_NAME).value - c0
    if compiles != 1:
        failures.append(f"multi-tenant mixed step compiled {compiles} "
                        "times across adapter churn, want exactly 1")
    loads = pm.JIT_COMPILES.labels("serving_adapter_load").value
    if loads != 1:
        failures.append(f"serving_adapter_load compiled {loads} "
                        "times, want exactly 1 (slot id must ride as "
                        "a traced scalar)")
    if multi.adapters.evictions < 1:
        failures.append("the stream never evicted an adapter slot — "
                        "the smoke is not exercising churn")
    if multi.adapters.total_pins != 0:
        failures.append(f"{multi.adapters.total_pins} adapter pins "
                        "leaked after drain")
    if multi.kv.blocks_in_use != 0:
        failures.append(f"{multi.kv.blocks_in_use} KV blocks leaked")
    if not multi.kv.allocator.invariant_ok:
        failures.append("allocator ledger invariant violated")

    # null parity: base-model requests == an adapter-free engine
    base = engine()
    for t in (None,) + TENANTS:
        idxs = [i for i, rt in enumerate(req_tenants) if rt == t]
        if t is None:
            solo = base
        else:
            solo = engine(max_adapters=2)
            solo.register_adapter(t, adapters[t])
        sr = [solo.submit(prompts[i], 6, adapter_id=t) for i in idxs]
        solo.run()
        solo_out = [list(r.output) for r in sr]
        got = [outs[i] for i in idxs]
        if got != solo_out:
            kind = "null-adapter" if t is None else f"tenant {t}"
            failures.append(
                f"{kind} outputs diverge from the solo engine "
                f"({got} vs {solo_out})")
    return {
        "requests": n_req,
        "adapter_hits": multi.adapters.cache_hits,
        "adapter_misses": multi.adapters.cache_misses,
        "adapter_evictions": multi.adapters.evictions,
        "adapter_hit_ratio": round(multi.adapters.hit_ratio(), 3),
        "bytes_per_tenant": int(multi.adapters.bytes_per_slot),
    }


def run_int4_phase(failures):
    import numpy as np

    from paddle_tpu.ops.pallas.grouped_matmul import expert_weight_bytes
    from paddle_tpu.serving.engine import ServingEngine

    model = _model(moe=True, seed=7, snap_bits=4)
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 211, int(n)).tolist()
               for n in (3, 9, 17, 5, 12, 7, 21, 4)]

    def engine(moe_weight_dtype=None):
        return ServingEngine(model, max_slots=4, block_size=4,
                             max_seq_len=64, cache_dtype="float32",
                             seed=0, moe_weight_dtype=moe_weight_dtype)

    fp = engine()
    out_fp = fp.generate_batch(prompts, max_new_tokens=6)
    q4 = engine(moe_weight_dtype="int4")
    out_q4 = q4.generate_batch(prompts, max_new_tokens=6)
    total = sum(len(o) for o in out_fp)
    agree = sum(a == b for x, y in zip(out_fp, out_q4)
                for a, b in zip(x, y))
    agreement = agree / max(1, total)
    if agreement < 0.99:
        failures.append(f"int4 greedy agreement {agreement:.3f} "
                        f"({agree}/{total}) below the 0.99 contract "
                        "(grid-snapped experts must round-trip "
                        "losslessly)")
    # capacity: analytic bytes (bf16 vs int4, scales included) ...
    dec = model.decoder
    L, E = dec.num_layers, dec._num_experts
    D, F = dec.embed_dim, dec.dim_feedforward
    ana_bf16 = (expert_weight_bytes(E, D, F, "bfloat16", L)
                + expert_weight_bytes(E, F, D, "bfloat16", L))
    ana_int4 = (expert_weight_bytes(E, D, F, "int4", L)
                + expert_weight_bytes(E, F, D, "int4", L))
    ratio = ana_bf16 / ana_int4
    if ratio < 1.9:
        failures.append(f"analytic int4 expert-weight reduction "
                        f"{ratio:.2f}x vs bf16 below 1.9x")
    # ... AND measured on the engine's actual device arrays (proves
    # the nibble packing really halved storage)
    def measured(eng, names):
        return sum(int(eng._arrays[2 + eng._names.index(n)].nbytes)
                   for n in names if n in eng._names)
    got_int4 = measured(q4, ("ffn1_w", "ffn1_s", "ffn2_w", "ffn2_s"))
    bf16_equiv = 2 * (L * E * D * F + L * E * F * D)
    m_ratio = bf16_equiv / max(1, got_int4)
    if m_ratio < 1.9:
        failures.append(f"measured int4 expert bytes {got_int4} only "
                        f"{m_ratio:.2f}x below bf16-equivalent "
                        f"{bf16_equiv}; need >= 1.9x")
    if q4.kv.blocks_in_use != 0:
        failures.append("int4 MoE engine leaked KV blocks")
    return {
        "int4_agreement": round(agreement, 4),
        "expert_bytes_bf16_analytic": int(ana_bf16),
        "expert_bytes_int4_analytic": int(ana_int4),
        "expert_bytes_int4_measured": int(got_int4),
        "capacity_ratio_analytic": round(ratio, 2),
        "capacity_ratio_measured": round(m_ratio, 2),
    }


def main():
    from paddle_tpu.analysis import guards
    from paddle_tpu.profiler import metrics as pm
    from paddle_tpu.serving.metrics import CONTRACT_METRICS

    pm.enable()
    failures = []
    with guards.sanitize() as wd:
        stats = run_lora_phase(failures)
        stats.update(run_int4_phase(failures))
    failures += [f"compile watchdog: {v}" for v in wd.violations]
    text = pm.REGISTRY.to_prometheus()
    print(text)
    for name in CONTRACT_METRICS:
        if name not in text:
            failures.append(f"MISSING serving metric: {name}")
    if failures:
        for f in failures:
            print(f"LORA SMOKE FAILURE: {f}", file=sys.stderr)
        return 1
    print("lora smoke OK: "
          f"{stats['requests']} requests, adapter hit ratio "
          f"{stats['adapter_hit_ratio']:.2f} "
          f"({stats['adapter_hits']} hits / "
          f"{stats['adapter_misses']} misses / "
          f"{stats['adapter_evictions']} evictions), "
          f"{stats['bytes_per_tenant']} B marginal HBM/tenant; "
          f"int4 agreement {stats['int4_agreement']:.1%}, expert "
          f"bytes {stats['expert_bytes_int4_measured']} vs bf16 "
          f"{stats['expert_bytes_bf16_analytic']} "
          f"({stats['capacity_ratio_measured']:.2f}x measured)",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
